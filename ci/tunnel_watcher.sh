#!/bin/bash
# Probe the TPU tunnel every 5 minutes; when it answers, run the
# requested bench.py subset once and stop. Results land in
# $OUT_DIR/bench_recovered.json. The round-2/3 failure mode this guards:
# the tunnel wedges for hours, then recovers silently — a human (or
# agent) polling by hand misses the window.
set -u
# empty ONLY = the FULL suite: bench.py orders sub-benches by banking
# priority and banks each one to BENCH_TPU_BANKED.json as it completes,
# so a mid-run wedge still keeps everything measured up to that point
ONLY="${MMLSPARK_TPU_WATCH_ONLY:-}"
OUT_DIR="${MMLSPARK_TPU_WATCH_DIR:-/tmp/bench_watcher}"
# must exceed bench.py's worst-case per-sub-bench watchdog sum (~6300s
# for the full suite incl. the encoder_int8 and gen sub-benches): the
# sub-bench watchdogs are the designed wedge handling, and an outer
# kill before the final JSON print would leave an empty result and
# loop forever
RUN_TIMEOUT="${MMLSPARK_TPU_WATCH_TIMEOUT:-7800}"
mkdir -p "$OUT_DIR"
cd "$(dirname "$0")/.."
while true; do
  if timeout 60 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
      >>"$OUT_DIR/probe.log" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel up — running bench (${ONLY:-full})" >>"$OUT_DIR/probe.log"
    MMLSPARK_TPU_BENCH_ONLY="$ONLY" timeout "$RUN_TIMEOUT" python bench.py \
      >"$OUT_DIR/bench_recovered.json" 2>>"$OUT_DIR/probe.log"
    # only stop on a non-empty result with NO error keys at all — a
    # mid-suite wedge records error_gbdt/error_ranker (not
    # error_backend) and must keep the retry loop alive
    # also reject '"killed' explicitly: an outer `timeout` SIGTERM makes
    # bench.py emit a valid partial JSON (error_killed now, bare
    # "killed" in older builds) that must not count as a banked suite
    if [ -s "$OUT_DIR/bench_recovered.json" ] && \
       ! grep -q '"error\|"killed' "$OUT_DIR/bench_recovered.json"; then
      echo "$(date -u +%FT%TZ) banked" >>"$OUT_DIR/probe.log"
      break
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >>"$OUT_DIR/probe.log"
  fi
  sleep 300
done
