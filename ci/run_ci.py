#!/usr/bin/env python
"""One-command CI: style + per-package unit tests + examples + multichip.

The local engine behind ``ci/pipeline.yaml`` (which mirrors the
reference's per-package matrix, ``pipeline.yaml:323-384``).

    python ci/run_ci.py                # everything
    python ci/run_ci.py --only tests --package lightgbm2
    python ci/run_ci.py --only examples
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# package → test files (the reference splits slow packages into split1/2)
PACKAGES: dict[str, list[str]] = {
    "core": ["test_core_dataframe.py", "test_core_params_pipeline.py",
             "test_fuzzing.py", "test_longtail_io.py", "test_arrow.py"],
    "featurize": ["test_featurize.py", "test_stages.py",
              "test_vector_embedding.py"],
    "lightgbm1": ["test_lightgbm.py", "test_lightgbm_categorical.py", "test_pallas_hist.py"],
    "lightgbm2": ["test_lightgbm_sparse.py", "test_lightgbm_distributed.py",
                  "test_lightgbm_format_fixture.py"],
    "vw": ["test_vw.py"],
    "dl": ["test_text_encoder.py", "test_image_dl.py", "test_convert.py",
           "test_bert_convert.py", "test_transfer_learning.py",
           "test_checkpoint_profiling.py", "test_quantize.py",
           "test_parallel.py", "test_pipeline_moe.py",
           "test_sharding_analysis.py", "test_pallas_attention.py"],
    "serving": ["test_http_serving.py", "test_serving_distributed.py",
                "test_serving_native.py", "test_serving_model.py"],
    "cognitive": ["test_cognitive.py", "test_cognitive_speech.py",
                  "test_cognitive_breadth.py"],
    "learners": ["test_learners.py", "test_linear.py",
                 "test_recommendation_lime.py", "test_cyber.py"],
    "io": ["test_native_codegen.py", "test_benchmarks.py",
           "test_reference_parity.py", "test_out_of_core.py",
           "test_ci.py", "test_bench_banking.py", "test_rcheck.py"],
    "obs": ["test_obs.py", "test_obs_profile.py"],
    # fleet telemetry plane: federation + straggler/burn health + the
    # chaos trajectory, and the HBM memory profiler's degradation story
    "fleet": ["test_fleet.py", "test_obs_memory.py"],
    # telemetry history plane: the bounded time-series store, recorder,
    # /debug/timeline on both fronts, and the recorder overhead guard
    "timeseries": ["test_timeseries.py"],
    # perf-regression sentinel: offline bench-trajectory gate + live
    # CUSUM watch + seeded regression-chaos acceptance
    "regression": ["test_regression.py"],
    "analysis": ["test_analysis.py"],  # graftcheck passes + gate + clock
    "sched": ["test_sched.py"],  # admission/batching policy + scheduler
    "tenancy": ["test_tenancy.py"],  # quotas, SLO tiers, fair dispatch
    "autoscale": ["test_autoscale.py"],  # autoscaler + mixed-tenant chaos
    "resilience": ["test_resilience.py"],  # retry/breaker/faults/chaos
    "parallel": ["test_partition.py"],  # partition rules + pjit steps
    "compile": ["test_pipeline_compile.py"],  # whole-pipeline fusion
    "aot": ["test_aot.py"],  # AOT executable store + warm boot
    "perf": ["test_perf.py"],  # learned cost model + kernel autotuner
    # pod-scale SPMD harness: runs UNFILTERED (no -m 'not slow'), so
    # the 2-process CPU pods execute here under the package wall clock
    "multihost": ["test_multihost.py"],
    "text": ["test_text_transfer.py", "test_causal_lm.py",
             "test_speculative.py"],
    # LLM serving engine: paged KV bookkeeping (no-JAX half) +
    # disaggregated prefill/decode + in-batch speculation + the
    # paged-attention kernel equivalence suite
    "llm": ["test_paged_kv.py", "test_llm_serving.py",
            "test_paged_attention.py"],
    # zero-downtime model lifecycle: versioned registry + blue/green
    # router + canary burn-rate rollback, and the rollout acceptance
    "deploy": ["test_deploy.py"],
    # device cost-attribution plane: PeakSpec/rooflines, AOT cost
    # persistence, goodput ledger, xprof capture surface, schema v6
    "attribution": ["test_attribution.py"],
}

# traceable-count ratchet (ISSUE 10): the analysis gate fails if the
# regenerated traceability report classifies fewer stages TRACEABLE
# than the committed burn-down achieved — host ops must not creep back
# into stage transform/fit paths. Raise this as more stages convert;
# never lower it without a written justification in the PR.
# 36 → 38 (ISSUE 11): UnrollImage + IDFModel grew _trace forms, so the
# AOT executable store covers them too.
TRACEABLE_RATCHET = 38


def _run(cmd: list[str], **kw) -> int:
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, cwd=REPO, **kw)


def style() -> int:
    rc = _run([sys.executable, "-m", "compileall", "-q",
               "mmlspark_tpu", "tests", "examples", "ci"])
    if rc:
        return rc
    # obs must import cleanly with no backend and no JAX import at all
    # (serving fronts scrape it from handler threads before/without any
    # device init; a JAX import sneaking in would drag backend setup
    # into every importer). The tracing data plane rides along: the
    # propagation/export/profile surfaces must inject+extract a
    # traceparent, retain a trace in the flight recorder, and render
    # Chrome-trace JSON — all with no JAX in the process.
    smoke = (
        "import sys; "
        "from mmlspark_tpu.obs import (registry, tracer, inject, "
        "extract, flight_recorder, compile_tracker, step_profiler, "
        "feature_log, chrome_trace); "
        "assert 'jax' not in sys.modules, 'obs import pulled in jax'; "
        "exec('with tracer.span(\"ci\") as sp:\\n    h = inject({}, sp)'); "
        "ctx = extract(h); assert ctx.trace_id == sp.trace_id; "
        "flight_recorder.install(); "
        "flight_recorder.note_request(sp.trace_id, 0.5, status=200); "
        "assert flight_recorder.tree(sp.trace_id) is not None; "
        "assert chrome_trace([sp])['traceEvents']; "
        "feature_log.record(service='ci', route='/', batch=1); "
        # the cost-attribution plane rides along: PeakSpec resolution,
        # a roofline record, a goodput ledger tick, and the xprof
        # capture surface must all answer jax-free — a capture request
        # degrades to 503-with-reason, it NEVER imports jax
        "from mmlspark_tpu.obs.attribution import (CostAttribution, "
        "peak_spec); "
        "from mmlspark_tpu.obs.goodput import GoodputLedger; "
        "from mmlspark_tpu.obs.xprof import XprofCaptures; "
        "from mmlspark_tpu.obs.metrics import MetricsRegistry; "
        "assert peak_spec().platform == 'cpu'; "
        "ca = CostAttribution(registry=MetricsRegistry()); "
        "assert ca.record_program('ci', 1e9, 1e3, "
        "platform='cpu')['bound'] == 'compute'; "
        "led = GoodputLedger(registry=MetricsRegistry()); "
        "assert led.tick()['goodput_ratio'] == 1.0; "
        "assert led.tick()['ticks'] == 2; "
        "xc = XprofCaptures(root='/tmp/mmlspark_tpu_ci_xprof', "
        "registry=MetricsRegistry()); "
        "status, body = xc.handle_query('duration_ms=10', b''); "
        "assert status == 503 and b'reason' in body, (status, body); "
        "assert 'jax' not in sys.modules, 'obs data plane pulled jax'; "
        "print('obs import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the fleet telemetry plane is control-plane code scraped from
    # handler threads: it must import, merge two ranks' snapshots into
    # one collision-free exposition, and answer a health tick with no
    # JAX at all — and the HBM memory gauges must be ABSENT (not zero,
    # not raising) in a backend-free process
    smoke = (
        "import sys\n"
        "from mmlspark_tpu.obs.fleet import (FleetAggregator, "
        "FleetHealth, fleet_aggregator)\n"
        "from mmlspark_tpu.obs.memory import (device_memory_stats, "
        "memory_profiler)\n"
        "from mmlspark_tpu.obs.metrics import MetricsRegistry\n"
        "assert 'jax' not in sys.modules, 'obs.fleet pulled in jax'\n"
        "agg = FleetAggregator(MetricsRegistry())\n"
        "agg.ingest_snapshot({'profile_step_seconds_sum"
        "{stage=\"x\"}': 1.0}, process='0')\n"
        "agg.ingest_snapshot({'profile_step_seconds_sum"
        "{stage=\"x\"}': 2.0}, process='1')\n"
        "text = agg.exposition()\n"
        "assert 'process=\"0\"' in text and 'process=\"1\"' in text\n"
        "merged = agg.merged_samples()\n"
        "assert len(merged) == 2, merged  # zero collisions\n"
        "h = FleetHealth(agg, registry=MetricsRegistry())\n"
        "assert h.tick() == 'ok'\n"
        "status, body = h.healthz_payload()\n"
        "assert status == 200 and b'\"ok\"' in body\n"
        "assert device_memory_stats() == []\n"
        "assert memory_profiler.update() == []\n"
        "from mmlspark_tpu.obs import registry\n"
        "assert not any(k.startswith('mem_hbm_') "
        "for k in registry.snapshot())\n"
        "assert 'jax' not in sys.modules, 'fleet health tick pulled jax'\n"
        "print('obs.fleet federation OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the telemetry history plane is control-plane code ticked from a
    # daemon thread and served from handler threads: the store must
    # record/query, the offline gate must diff a synthetic regression,
    # and the CUSUM sentinel must warm up and alarm — all stdlib-only,
    # with no JAX in the process
    smoke = (
        "import sys\n"
        "from mmlspark_tpu.obs.metrics import MetricsRegistry\n"
        "from mmlspark_tpu.obs.timeseries import Recorder, "
        "TimeSeriesStore, timeline_payload\n"
        "from mmlspark_tpu.obs.regression import (CusumDetector, "
        "compare_benches, gate_verdict)\n"
        "assert 'jax' not in sys.modules, 'history plane pulled in jax'\n"
        "reg = MetricsRegistry()\n"
        "g = reg.gauge('sched_ci_depth', 'smoke')\n"
        "store = TimeSeriesStore(reg)\n"
        "rec = Recorder(store, reg)\n"
        "for v in (1.0, 2.0, 3.0):\n"
        "    g.set(v)\n"
        "    rec.tick()\n"
        "assert [p[1] for p in store.points('sched_ci_depth')] == "
        "[1.0, 2.0, 3.0]\n"
        "status, body = timeline_payload('series=sched_&window=60', "
        "store=store)\n"
        "assert status == 200 and b'sched_ci_depth' in body\n"
        "rows = compare_benches({'m_per_sec': 100.0}, "
        "{'m_per_sec': 80.0})\n"
        "assert gate_verdict(rows).startswith('REGRESSION')\n"
        "det = CusumDetector(warmup=4, direction='lower_bad')\n"
        "for v in (0.42, 0.41, 0.43, 0.42, 0.42):\n"
        "    det.update(v)\n"
        "assert any(det.update(0.05) for _ in range(4))\n"
        "assert 'jax' not in sys.modules, 'history plane pulled in jax'\n"
        "print('obs history plane OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # sched (admission control + batch policy) is pure stdlib + obs:
    # it must import and schedule with no device and no JAX at all —
    # the serving fronts run it from handler threads, and offline
    # pipelines use the same BatchPolicy on machines with no TPU
    smoke = ("import sys; import mmlspark_tpu.sched as s; "
             "assert 'jax' not in sys.modules, 'sched import pulled jax'; "
             "s.RequestScheduler('ci-smoke').submit(type('I', (), {})()); "
             "print('sched import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the paged KV cache's bookkeeping half (block table, prefix index,
    # LRU, handoff payloads) is pure Python + numpy: the serving
    # control plane allocates/adopts/exports on machines with no
    # device, so the whole lifecycle must run with no JAX at all —
    # device pools only materialize when an executor gathers/scatters
    smoke = (
        "import sys\n"
        "from mmlspark_tpu.dl.paged_kv import (PagedKVManager, "
        "SequenceHandle, TRASH_BLOCK, blocks_for_hbm_budget)\n"
        "from mmlspark_tpu.obs.metrics import MetricsRegistry\n"
        "assert 'jax' not in sys.modules, 'paged_kv import pulled jax'\n"
        "m = PagedKVManager(9, 4, registry=MetricsRegistry(), "
        "service='ci')\n"
        "h = m.allocate('a', list(range(1, 9)))\n"
        "assert len(h.chain) == 2 and TRASH_BLOCK not in h.chain\n"
        "m.publish('a'); m.advance('a', 8)\n"
        "state = m.export_seq('a')\n"
        "assert m.adopt(state).length == 8\n"
        "m.release('a')\n"
        "assert m.allocate('b', list(range(1, 9))).reused_tokens == 8\n"
        "assert m.block_rows(['b', None], 3).shape == (2, 3)\n"
        "assert blocks_for_hbm_budget(1024, default=5) >= 0\n"
        # the paged-attention kill switch is control-plane too: the
        # executors read it at init on machines with no device, and
        # consulting it must not drag in the Pallas kernel module
        "from mmlspark_tpu.dl.paged_kv import paged_attention_enabled\n"
        "assert paged_attention_enabled() in (True, False)\n"
        "assert 'mmlspark_tpu.dl.pallas_paged_attention' not in "
        "sys.modules, 'paged kernel imported eagerly'\n"
        "assert 'jax' not in sys.modules, 'kv bookkeeping pulled jax'\n"
        "print('dl.paged_kv import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # tenancy (per-tenant quotas + SLO tiers + weighted-fair dispatch)
    # and the autoscaler are control-plane code: both must import AND
    # make decisions with no device and no JAX at all — admission runs
    # from handler threads, the autoscaler from its own control thread
    smoke = (
        "import sys\n"
        "from mmlspark_tpu.sched import Tenancy, TenantQuota, "
        "RequestScheduler, Shed, GOLD\n"
        "from mmlspark_tpu.serving.autoscale import Autoscaler, "
        "AutoscaleConfig, AutoscaleSignals\n"
        "assert 'jax' not in sys.modules, 'tenancy/autoscale pulled "
        "jax'\n"
        "t = Tenancy('ci', quotas={'g': TenantQuota(tier=GOLD, "
        "rate=1.0, burst=1.0)}, tier_deadlines={GOLD: 0.5})\n"
        "s = RequestScheduler('ci', tenancy=t)\n"
        "s.submit(type('I', (), {})(), tenant='g')\n"
        "try:\n"
        "    s.submit(type('I', (), {})(), tenant='g')\n"
        "except Shed as e:\n"
        "    assert e.status == 429 and e.retry_after >= 1\n"
        "class P:\n"
        "    n = 1\n"
        "    def count(self): return self.n\n"
        "    def scale_up(self): self.n += 1\n"
        "    def scale_down(self): self.n -= 1\n"
        "a = Autoscaler('ci', P(), AutoscaleConfig(up_stable=1))\n"
        "assert a.tick(AutoscaleSignals(queue_depth=99)) == 'up'\n"
        "assert 'jax' not in sys.modules, 'tenancy/autoscale pulled "
        "jax'\n"
        "print('tenancy+autoscale import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # resilience (retry policy + breakers + fault injector) is pure
    # stdlib + obs: it must import, back off, break, and arm a seeded
    # fault schedule with no device and no JAX at all — the HTTP client
    # stack and serving mesh run it from handler threads
    smoke = (
        "import sys; "
        "from mmlspark_tpu.resilience import (RetryPolicy, FaultRule, "
        "breaker_for, faults); "
        "assert 'jax' not in sys.modules, 'resilience import pulled jax'; "
        "p = RetryPolicy(seed=0, sleep=lambda s: None); "
        "c = p.start(deadline=1.0, op='ci'); "
        "assert c.backoff(status=503) and not c.backoff(status=404); "
        "b = breaker_for('ci-smoke', min_calls=1); b.record_failure(); "
        "assert b.state == 'open' and not b.allow(); "
        "exec('with faults(7, [FaultRule(point=\"p\", kind=\"error\")]) "
        "as inj:\\n    assert inj.probe(\"p\") is not None'); "
        "print('resilience import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the partition-rule engine must import, match, and register rule
    # sets with no JAX at all: model modules register their rules at
    # import time on device-less machines, and rule sets are plain
    # (regex, tuple) data until something shards for real
    smoke = (
        "import sys; "
        "from mmlspark_tpu.parallel.partition import ("
        "DtypePolicy, match_partition_rules, partition_rules_for, "
        "register_partition_rules); "
        "assert 'jax' not in sys.modules, 'partition import pulled jax'; "
        "register_partition_rules('ci-smoke', [(r'kernel', (None, 'tp'))]); "
        "assert partition_rules_for('ci-smoke'); "
        "assert DtypePolicy().param_dtype == 'float32'; "
        "assert 'jax' not in sys.modules, 'rule registration pulled jax'; "
        "print('parallel.partition import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the multi-host launcher is pure stdlib until a worker boots: the
    # coordinator side (port pick, env synthesis, target validation)
    # must work on the build/driver machine with no JAX at all — JAX
    # only loads inside the spawned worker processes
    smoke = (
        "import sys; "
        "from mmlspark_tpu.parallel.multihost import ("
        "free_port, launch_pod, worker_env); "
        "assert 'jax' not in sys.modules, 'multihost import pulled jax'; "
        "env = worker_env(process_id=1, num_processes=2, "
        "coordinator='127.0.0.1:1234', local_devices=4); "
        "assert env['MMLSPARK_TPU_COORDINATOR'] == '127.0.0.1:1234'; "
        "assert env['MMLSPARK_TPU_PROCESS_ID'] == '1'; "
        "assert env['JAX_CPU_COLLECTIVES_IMPLEMENTATION'] == 'gloo'; "
        "assert 'JAX_COMPILATION_CACHE_DIR' not in env; "
        "assert 0 < free_port() < 65536; "
        "assert 'jax' not in sys.modules, 'launcher plumbing pulled jax'; "
        "print('parallel.multihost import OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the pipeline compiler must import AND build an (all-host) plan
    # with no JAX in the process: plan construction is schema walking,
    # and fused segments only touch a backend on first execution — a
    # JAX import sneaking into compile/plan time would drag backend
    # setup into every control-plane importer of core
    smoke = (
        "import sys; import numpy as np; "
        "from mmlspark_tpu.core import (DataFrame, compile_pipeline, "
        "CompiledPipeline); "
        "from mmlspark_tpu.stages import TextPreprocessor; "
        "assert 'jax' not in sys.modules, 'core.compile pulled in jax'; "
        "df = DataFrame({'t': np.asarray(['A', 'B'], object)}); "
        "cp = compile_pipeline([TextPreprocessor(inputCol='t', "
        "outputCol='o', normFunc='lower')], df); "
        "assert isinstance(cp, CompiledPipeline); "
        "assert cp.compiled_segments == 0 and cp.eager_stages == 1; "
        "assert cp.transform(df)['o'].tolist() == ['a', 'b']; "
        "assert 'jax' not in sys.modules, 'host-only plan pulled jax'; "
        "print('core.compile import+plan OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the AOT store's fingerprint layer must compute keys with no JAX
    # in the process: the build CLI may need a backend, but key
    # computation runs in control-plane processes (gc tooling, store
    # audits, registries) that must never drag in device init
    smoke = (
        "import sys; "
        "from mmlspark_tpu.core import aot; "
        "from mmlspark_tpu.featurize.vector import OneHotEncoderModel; "
        "assert 'jax' not in sys.modules, 'aot import pulled in jax'; "
        "key = aot.segment_static_key([OneHotEncoderModel("
        "inputCol='c', outputCol='o', categorySize=3, "
        "handleInvalid='keep')], platform='cpu'); "
        "s, f = aot.fingerprints(key, [['c', 'int32', [8]]], []); "
        "assert len(s) == 64 and len(f) == 64 and s != f; "
        "import tempfile; "
        "store = aot.AotStore(tempfile.mkdtemp()); "
        "assert store.entries() == [] and store.stats()['entries'] == 0; "
        "assert 'jax' not in sys.modules, 'aot key/store pulled in jax'; "
        "print('core.aot fingerprint+store OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # the learned-performance layer (cost model + autotuner registry)
    # is control-plane code consulted from scheduler/handler threads:
    # it must import, train on synthetic FeatureLog rows, predict, and
    # answer winner lookups with no device and no JAX in the process
    smoke = (
        "import sys\n"
        "from mmlspark_tpu.obs.profile import FEATURE_SCHEMA_VERSION\n"
        "from mmlspark_tpu.perf import CostModel, autotune\n"
        "from mmlspark_tpu.sched.policy import ServiceTimeEstimator, "
        "bucket_of\n"
        "assert 'jax' not in sys.modules, 'perf import pulled in jax'\n"
        "rows = [dict(service='ci', route='/', batch=b, "
        "bucket=bucket_of(b), entity_bytes=b * 64.0, queue_depth=2.0, "
        "execute_ms=1.0 + 0.1 * bucket_of(b), "
        "schema_version=FEATURE_SCHEMA_VERSION) "
        "for b in (1, 2, 3, 4, 6, 8, 12, 16) * 8]\n"
        "m = CostModel(min_rows=16)\n"
        "assert m.predict_batch_ms('ci', 4) is None  # cold -> EWMA\n"
        "assert m.fit(rows) == len(rows)\n"
        "p = m.predict_batch_ms('ci', 4)\n"
        "assert p is not None and 1.0 < p < 3.0, p\n"
        "est = ServiceTimeEstimator('ci-est', cost_model=m)\n"
        "assert est.estimate(4) is None  # model cold for THIS service\n"
        "assert autotune.kernel_winner('hist', "
        "autotune.hist_key(1024, 8, 16), 'cpu') is None\n"
        "assert autotune.hist_candidates(1024, 8, 16)\n"
        "assert 'jax' not in sys.modules, 'perf data plane pulled jax'\n"
        "print('perf import OK (no jax)')")
    # isolated perf store: a developer's ambient /tmp autotune registry
    # (import-time maybe_load) would otherwise fail the winner-is-None
    # assert on a machine where the CLI was ever run at this shape
    import tempfile
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu",
                       MMLSPARK_TPU_PERF_STORE=tempfile.mkdtemp(
                           prefix="mmlspark_tpu_perf_smoke_")))
    if rc:
        return rc
    # the deploy plane is control-plane code (registry + router +
    # rollout controller): it must register versions, stage + flip
    # atomically, and answer a controller tick with no JAX in the
    # process — the serving fronts route every request through it from
    # handler threads, long before any device init
    smoke = (
        "import sys\n"
        "from mmlspark_tpu.serving.deploy import (ModelRegistry, "
        "RolloutConfig, RolloutController, VersionRouter)\n"
        "from mmlspark_tpu.obs.metrics import MetricsRegistry\n"
        "assert 'jax' not in sys.modules, 'deploy import pulled jax'\n"
        "reg = MetricsRegistry()\n"
        "m = ModelRegistry(service='smoke', registry=reg)\n"
        "m.register('v1', transform=lambda b: b)\n"
        "m.register('v2', transform=lambda b: b)\n"
        "r = VersionRouter(m, service='smoke', metrics=reg)\n"
        "r.set_active('v1')\n"
        "r.stage('v2', canary_share=0.25)\n"
        "assert r.assign('gold')[0] == 'v1'\n"
        "assert r.flip() == 'v2' and r.active == 'v2'\n"
        "assert r.draining_inflight() == 1\n"
        "r.release('v1')\n"
        "assert r.draining_inflight() == 0\n"
        "c = RolloutController(r, metrics=reg, "
        "config=RolloutConfig(rollback_windows=1))\n"
        "assert c.tick(burns={}) == 'idle'\n"
        "m.register('v3', transform=lambda b: b)\n"
        "r.stage('v3')\n"
        "assert c.tick(burns={'canary': {'fast': 9.0, 'slow': 9.0}}) "
        "== 'rollback'\n"
        "assert c.deploy_reasons(), 'rollback flap must degrade healthz'\n"
        "assert 'jax' not in sys.modules, 'deploy plane pulled jax'\n"
        "print('serving.deploy control plane OK (no jax)')")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # graftcheck (static analysis) is pure stdlib: it must import AND
    # analyze with no JAX at all — it runs as a gate on machines (and
    # in contexts) where importing the analyzed code is not an option
    smoke = ("import sys; from mmlspark_tpu.analysis import ("
             "Project, run_passes); "
             "assert 'jax' not in sys.modules, 'analysis import pulled "
             "jax'; "
             "p = Project.load('.', 'mmlspark_tpu'); "
             "assert len(p.modules) > 100, len(p.modules); "
             "run_passes(p); "
             "assert 'jax' not in sys.modules, 'analysis run pulled "
             "jax'; "
             "print('analysis import+run OK (no jax, "
             "%d modules)' % len(p.modules))")
    rc = _run([sys.executable, "-c", smoke],
              env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc:
        return rc
    # codegen reflection must walk every stage without error (the
    # reference's Style job runs codegen as part of the build)
    code = ("import os, tempfile, jax; "
            "jax.config.update('jax_platforms', 'cpu'); "
            "from mmlspark_tpu.codegen import generate_all; "
            "d = tempfile.mkdtemp(); out = generate_all(d); "
            "assert out['stubs'] and out['r'] and out['pyspark'], out; "
            "print('codegen OK:', {k: len(v) if isinstance(v, list) else v"
            " for k, v in out.items()})")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return _run([sys.executable, "-c", code], env=env)


def tests(package: str | None, retries: int = 1) -> int:
    missing = [f for files in PACKAGES.values() for f in files
               if not os.path.exists(os.path.join(REPO, "tests", f))]
    if missing:
        print(f"pipeline references missing test files: {missing}")
        return 2
    untracked = sorted(
        f for f in os.listdir(os.path.join(REPO, "tests"))
        if f.startswith("test_") and f.endswith(".py")
        and not any(f in files for files in PACKAGES.values()))
    if untracked:
        print(f"test files not assigned to any CI package: {untracked}")
        return 2
    selected = ([package] if package else sorted(PACKAGES))
    for pkg in selected:
        files = [os.path.join("tests", f) for f in PACKAGES[pkg]]
        for attempt in range(retries + 1):
            rc = _run([sys.executable, "-m", "pytest", "-q", *files])
            if rc == 0:
                break
            if attempt < retries:
                print(f"package {pkg} failed (rc={rc}) — flaky retry")
        if rc != 0:
            return rc
    return 0


def analysis() -> int:
    """The graftcheck gate: zero unbaselined findings over the package,
    stale baseline entries fail too (--strict), and the traceability
    report is regenerated to a TEMP file and diffed against the
    committed copy — regenerating in place would overwrite the evidence
    and mask staleness from everything that runs after this stage.
    Budget: < 60 s — it runs pure ast, no JAX, so it actually finishes
    in a few seconds."""
    import filecmp
    import tempfile
    t0 = time.monotonic()
    committed = os.path.join(REPO, "mmlspark_tpu", "analysis",
                             "traceability.json")
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        fresh = f.name
    try:
        rc = _run([sys.executable, "-m", "mmlspark_tpu.analysis",
                   "--strict", "--traceability", fresh])
        if rc == 0 and not filecmp.cmp(fresh, committed, shallow=False):
            print("analysis: committed traceability.json is STALE — "
                  "regenerate it:\n  python -m mmlspark_tpu.analysis "
                  "--strict --traceability "
                  "mmlspark_tpu/analysis/traceability.json")
            rc = 1
        if rc == 0:
            # the traceable-count ratchet: a host op creeping back into
            # a converted stage silently shrinks the fused spans —
            # whole-pipeline compilation's work-list only burns DOWN
            import json
            with open(fresh, encoding="utf-8") as f:
                n = json.load(f)["summary"]["traceable"]
            if n < TRACEABLE_RATCHET:
                print(f"analysis: traceability ratchet broken — "
                      f"{n} stages TRACEABLE < committed floor "
                      f"{TRACEABLE_RATCHET}. A host op (numpy call, "
                      f".tolist) crept back into a stage transform/fit "
                      f"path; see the stage's 'reasons' in the report.")
                rc = 1
    finally:
        os.unlink(fresh)
    took = time.monotonic() - t0
    if took > 60:
        print(f"analysis gate exceeded its 60s budget ({took:.0f}s)")
        return rc or 3
    return rc


def regression_gate() -> int:
    """The perf-regression trajectory gate (ISSUE 16): diff the newest
    banked ``BENCH_r0*.json`` against its predecessor, the whole
    trajectory pricing each metric's noise. Exit 1 = a gated metric
    regressed beyond tolerance; a too-short trajectory (fresh clone,
    < 2 banked runs) is a pass with a note, not a failure. Budget:
    < 60 s — it is pure JSON diffing, no JAX, no benchmarks re-run."""
    t0 = time.monotonic()
    rc = _run([sys.executable, "-m", "mmlspark_tpu.obs.regression",
               "gate"], env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if rc == 2:
        print("regression gate: trajectory too short to judge — "
              "treating as pass")
        rc = 0
    took = time.monotonic() - t0
    if took > 60:
        print(f"regression gate exceeded its 60s budget ({took:.0f}s)")
        return rc or 3
    return rc


def aot_roundtrip() -> int:
    """Build-then-load round trip across two scrubbed processes: the
    store built by one process must warm-load in a fresh one with zero
    runtime compiles and bit-equal output (the AOT acceptance's
    cross-process half, as a standing CI job)."""
    return _run([sys.executable, "-m", "mmlspark_tpu.core.aot",
                 "selftest"])


def examples() -> int:
    return _run([sys.executable, os.path.join("examples", "run_all.py")])


def multichip() -> int:
    code = "import __graft_entry__ as g; g.dryrun_multichip(8)"
    return _run([sys.executable, "-c", code])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", choices=["style", "analysis",
                                       "regression_gate", "tests",
                                       "aot_roundtrip", "examples",
                                       "multichip"])
    ap.add_argument("--package", choices=sorted(PACKAGES))
    args = ap.parse_args()
    t0 = time.monotonic()
    stages = ([args.only] if args.only
              else ["style", "analysis", "regression_gate", "tests",
                    "aot_roundtrip", "examples", "multichip"])
    for stage in stages:
        rc = {"style": style, "analysis": analysis,
              "regression_gate": regression_gate,
              "aot_roundtrip": aot_roundtrip,
              "examples": examples, "multichip": multichip}.get(
                  stage, lambda: tests(args.package))()
        if rc:
            print(f"CI FAILED at {stage} (rc={rc})")
            return rc
    print(f"CI OK ({time.monotonic() - t0:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
