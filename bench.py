"""Headline benchmark: ImageFeaturizer ResNet-50 inference throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

This is the north-star workload (BASELINE.json config 2: ImageFeaturizer
ResNet-50; reference path = CNTKModel JNI evaluation,
``cntk/CNTKModel.scala:499-541``). The baseline constant is an A100
bf16 ResNet-50 inference figure (~2500 images/s) per the BASELINE.json
"≥3× A100 on a v5e-64 pod" target, i.e. per-chip parity ≈ 0.33×... 1×+
is chip-for-chip parity with A100.
"""

from __future__ import annotations

import json
import time

A100_IMAGES_PER_SEC = 2500.0  # bf16 ResNet-50 inference, batch ~128


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    # persistent XLA cache: first compile of the ResNet-50 graph via the
    # remote-compile tunnel is slow; later runs reuse it
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/mmlspark_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from mmlspark_tpu.models import ModelDownloader

    loaded = ModelDownloader().download_by_name("ResNet50")
    module, variables = loaded.module, loaded.variables

    batch = 128

    @jax.jit
    def forward(x):
        return module.apply(variables, x, False)["pooled"]

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)), jnp.bfloat16)

    forward(x).block_until_ready()  # compile
    # warmup
    for _ in range(3):
        forward(x).block_until_ready()

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        out = forward(x)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    images_per_sec = batch * iters / dt
    print(json.dumps({
        "metric": "imagefeaturizer_resnet50_inference",
        "value": round(images_per_sec, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(images_per_sec / A100_IMAGES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
