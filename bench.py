"""Headline benchmark suite. Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", "extras": {...}}``.

Primary metric: ImageFeaturizer ResNet-50 inference throughput
(BASELINE.json config 2; reference path = CNTKModel JNI evaluation,
``cntk/CNTKModel.scala:499-541``). ``vs_baseline`` is against an A100
bf16 ResNet-50 inference figure (~2500 img/s) per the BASELINE.json
"≥3× A100 on a v5e-64 pod" target — 1.0 is chip-for-chip A100 parity.

``extras`` carries the rest of the suite (VERDICT r1 item 2):
- ``resnet50_mfu`` — achieved FLOP/s ÷ chip peak (XLA cost analysis),
  best over a batch-size sweep with bf16-cast weights.
- ``vit_mfu`` / ``encoder_mfu`` — ViT-B/16 and the long-context
  TextEncoder under the same sweep harness.
- ``train_images_per_sec`` / ``train_mfu_est`` — ResNet-50 SGD training
  step throughput (the transfer north star is a training workload).
- ``gbdt_rows_per_sec`` — LightGBMClassifier training row-scans/sec
  (rows × iterations ÷ fit seconds) on a Higgs-shaped synthetic
  (28 features; ``docs/lightgbm.md:17-21`` is the speed claim being
  chased). vs_baseline inside extras uses ~20M row-iter/s, upstream
  LightGBM's published Higgs pace on a 16-core CPU box.
- ``ranker_rows_per_sec`` / ``ranker_ndcg10`` — LightGBMRanker
  lambdarank training pace + quality on an MSLR-WEB30K-shaped synthetic
  (~100 docs/query, graded 0-4 relevance; BASELINE.json configs[2]).
- ``serving_p50_ms`` / ``serving_p99_ms`` — end-to-end HTTP latency of
  a live ServingServer with a jitted pipeline, against the reference's
  ~1 ms continuous-mode claim (``docs/mmlspark-serving.md:9-12``).

Every sub-bench is individually fault-isolated: a failure records an
``error`` string in extras and the line still prints (round-1 failure
mode was rc=1 with no line at all; VERDICT "What's weak" #1).
"""

from __future__ import annotations

import functools
import glob
import json
import os
import time
import traceback

A100_IMAGES_PER_SEC = 2500.0    # bf16 ResNet-50 inference, batch ~128
# per-chip bf16 peak from the shared PeakSpec table (obs.attribution) —
# env-overridable via MMLSPARK_TPU_PEAK_FLOPS, same as StepProfiler MFU
from mmlspark_tpu.obs.attribution import peak_spec as _peak_spec
V5E_PEAK_BF16_FLOPS = _peak_spec("tpu-v5e").peak_flops
RESNET50_FLOPS_PER_IMAGE = 4.09e9   # fallback if XLA cost analysis absent
GBDT_BASELINE_ROW_ITERS = 20e6  # upstream LightGBM Higgs rows×iters/sec
SERVING_TARGET_MS = 1.0
_BACKEND_OK = False            # set by main() after _acquire_backend
_PLATFORM: str | None = None   # set by main(); gates _bank
BANKED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_TPU_BANKED.json")


def _load_banked() -> dict:
    try:
        with open(BANKED_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


_BANK_SKIP = {"platform", "contended", "load_avg_start", "stale",
              # config knobs, not measurements — they must not resurface
              # as last_measured_* on wedged runs
              "train_remat", "serving_concurrency",
              "featurizer_e2e_u8_pipeline_depth"}


def _bank(extras: dict, headline: float, platform: str | None) -> None:
    """Persist every successful TPU measurement to the committed
    BENCH_TPU_BANKED.json (VERDICT r3 Missing #1: three rounds of real
    numbers were lost to a tunnel that wedged before the driver's
    capture ran). Called after EVERY sub-bench so a mid-suite wedge
    still banks whatever completed. Merge semantics: keys measured this
    run overwrite their banked entry; everything else is preserved, and
    a key whose value is unchanged keeps its original measured_at (the
    suite re-banks accumulated extras after every sub-bench — the
    timestamp must record measurement, not last-write)."""
    chip_up = platform in ("tpu", "axon")
    if not chip_up and not any(
            k.startswith("serving") for k in extras):
        return  # chip rows need the chip
    banked = _load_banked()
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    contended = bool(extras.get("contended"))
    for k, v in extras.items():
        # measurements (and provenance strings like encoder_best_impl)
        # only: marker keys (*_skipped), bools and config knobs must not
        # resurface as last_measured_* later
        if k.startswith("error") or k in _BANK_SKIP or \
                k.endswith("_skipped") or isinstance(v, bool) or \
                not isinstance(v, (int, float, dict, str)):
            continue
        # serving rows score on the host CPU by design, so they may
        # bank even with the tunnel wedged; every other row needs the
        # real chip
        if not chip_up and not k.startswith("serving"):
            continue
        prev = banked.get(k)
        if prev is not None and prev.get("value") == v:
            continue  # unchanged: keep the original measurement stamp
        # serving scores on the host CPU by design (the chip is behind
        # a ~69 ms tunnel here; see bench_serving) — label it honestly
        plat = "cpu-host" if k.startswith("serving") else platform
        rec = {"value": v, "measured_at": now, "platform": plat}
        if contended:  # taken on a loaded host — stained at the record
            rec["contended"] = True
        banked[k] = rec
    if headline and chip_up:
        prev = banked.get("imagefeaturizer_resnet50_inference")
        if prev is None or prev.get("value") != round(headline, 1):
            rec = {"value": round(headline, 1), "measured_at": now,
                   "platform": platform}
            if contended:
                rec["contended"] = True
            banked["imagefeaturizer_resnet50_inference"] = rec
    try:
        tmp = BANKED_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(banked, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, BANKED_PATH)
    except OSError:
        # a read-only checkout or full disk must not cost the output
        # line (same fault-isolation stance as every sub-bench)
        pass


def _merge_banked_into(extras: dict) -> None:
    """Wedged-tunnel path: surface the most recent banked real-chip
    numbers as explicitly-stamped ``last_measured_*`` extras. Never
    silently substituted — the headline stays 0.0 and ``stale: true``
    plus per-key timestamps make the provenance unmissable."""
    banked = _load_banked()
    if not banked:
        return
    extras["stale"] = True
    extras["last_measured_at"] = {
        k: rec.get("measured_at") for k, rec in banked.items()}
    for k, rec in banked.items():
        extras[f"last_measured_{k}"] = rec.get("value")


def _ensure_cpu_backend_available():
    """Keep the tunnel TPU as default but make the host CPU backend
    addressable so weight init never round-trips the remote compiler."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and "cpu" not in plats.split(","):
        os.environ["JAX_PLATFORMS"] = plats + ",cpu"


def _acquire_backend():
    """Backend acquisition with the reference's retry semantics
    (``ModelDownloader.scala:37-60``): the axon tunnel can be slow to
    come up; round 1 died here with zero retries, and a wedged tunnel
    can block forever — the per-attempt timeout turns that into a
    diagnosable error instead of an rc=124 hang."""
    import jax
    from mmlspark_tpu.core.utils import retry_with_timeout
    return retry_with_timeout(jax.devices, timeout_s=120,
                              backoffs_ms=(0, 2000, 10000))


def _timeout_scale() -> float:
    try:
        scale = float(os.environ.get("MMLSPARK_TPU_BENCH_TIMEOUT_SCALE",
                                     "1"))
    except ValueError:
        return 1.0  # a bad knob must never cost the output line
    # 0/negative would zero every deadline and fake-timeout healthy runs
    return scale if scale > 0 else 1.0


def _watchdog(fn, extras: dict, key: str, timeout_s: float):
    """Run one sub-bench with a deadline: a half-alive TPU tunnel can pass
    backend acquisition and then hang inside a remote compile, which
    would reproduce round 1's no-output rc=124. The sub-bench runs in a
    daemon thread; on timeout its error is recorded, the suite moves on,
    and the final os._exit abandons the stuck thread. The sub-bench
    writes into a PRIVATE dict merged only after a successful join — an
    abandoned thread that later unwedges must not race the shared extras
    (or the final json.dumps)."""
    import threading
    box: dict = {}
    scratch: dict = {}

    def run():
        try:
            box["result"] = fn(scratch)
        except Exception:
            box["error"] = traceback.format_exc()[-1500:]

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = timeout_s * _timeout_scale()
    t.join(deadline)
    if t.is_alive():
        extras[f"error_{key}"] = (
            f"timed out after {deadline:.0f}s (wedged backend?)")
        return None
    extras.update(scratch)
    if "error" in box:
        extras[f"error_{key}"] = box["error"]
        _bank(extras, 0.0, _PLATFORM)  # partial extras are still real
        return None
    # bank after EVERY sub-bench (no per-site call to forget): a later
    # wedge must not erase what this one measured
    _bank(extras, 0.0, _PLATFORM)
    return box.get("result")


@functools.lru_cache(maxsize=None)
def _phase_hist():
    """The obs registry's bench histogram — lazy so importing bench.py
    (harness smoke, --help) stays free of mmlspark_tpu imports."""
    from mmlspark_tpu.obs import registry
    return registry.histogram(
        "bench_phase_seconds",
        "bench timed-region wall seconds, by phase")


def _timed(phase: str):
    """THE bench stopwatch: ``with _timed("x") as t: ...`` then read
    ``t.seconds``. Every timed region lands in the process-wide obs
    registry (``bench_phase_seconds{phase=...}``) so bench timings sit
    on the same scrape surface as serving/training series instead of
    dying in paired ``perf_counter`` reads."""
    return _phase_hist().time(phase=phase)


def _t_block(f, x):
    """Wall seconds of one blocking call — the null-dispatch floor."""
    import jax
    with _timed("block") as t:
        jax.block_until_ready(f(x))
    return t.seconds


def _diff_timed(run_loop, iters, short, reps=2):
    """Difference two loop lengths: ``run_loop(n)`` -> blocking wall
    seconds for n chained iterations. Returns per-iteration seconds
    with the constant per-call overhead (the tunnel's pipeline-fill
    RTT) cancelled, or None when noise swamps the delta — callers must
    DISCARD such points (clamping a non-positive delta would publish
    absurd throughput)."""
    t_short = min(run_loop(short) for _ in range(reps))
    t_long = min(run_loop(short + iters) for _ in range(reps))
    dt = (t_long - t_short) / iters
    return dt if dt > 0 else None


def _mfu_sweep(module, variables, make_input, batches, *, iters=20,
               fallback_flops_per_item=0.0, output_key=None,
               force_fallback_flops=False):
    """Best-of-batch-sweep inference throughput + MFU for one model.

    Weights are cast to bf16 (inference-only: halves the HBM weight
    traffic that bounds the small-batch regime) and live on device; the
    timed loop re-dispatches a resident input, so the number is the
    compute path, not host→device transfer. Returns
    (items/sec, mfu, best_batch, flops_per_item)."""
    import jax
    import jax.numpy as jnp

    device = jax.devices()[0]
    variables = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16)
        if hasattr(a, "dtype") and a.dtype == jnp.float32 else a,
        variables)
    variables = jax.device_put(variables, device)

    @jax.jit
    def forward(x):
        out = module.apply(variables, x, False)
        return out[output_key] if output_key else out

    best = (0.0, 0.0, 0, 0.0)
    per_batch: dict[int, float] = {}
    for batch in batches:
        # one failing point (e.g. the largest batch OOMing HBM) must not
        # discard the measurements already banked
        try:
            x = jax.device_put(make_input(batch), device)
            # ONE compile per point: the AOT executable serves cost
            # analysis, warmup and the timed loop (re-jitting the same
            # computation doubles the remote-compiler round trips)
            compiled = forward.lower(x).compile()
            if force_fallback_flops:
                # cross-impl MFU comparability: XLA's cost analysis
                # does not see inside a Pallas custom call, so impls
                # sharing one model must share one analytic yardstick
                # (round-5: pallas beat dense on seqs/sec yet lost on
                # cost-analysis MFU by ~40% uncounted kernel flops)
                flops_per_batch = fallback_flops_per_item * batch
            else:
                from mmlspark_tpu.parallel.compat import cost_analysis
                cost = cost_analysis(compiled)
                flops_per_batch = (cost["flops"] if cost else 0.0) or \
                    fallback_flops_per_item * batch
            compiled(x).block_until_ready()
            for _ in range(3):
                compiled(x).block_until_ready()

            # an async dispatch loop pays the tunnel's pipeline-fill
            # RTT (~69 ms banked) once per BLOCKING call, which at
            # iters=10-20 inflates per-iter time by several ms and
            # understated every MFU row — difference it out
            def loop(n):
                with _timed("mfu_loop") as t:
                    for _ in range(n):
                        out = compiled(x)
                    out.block_until_ready()
                return t.seconds

            per_iter = _diff_timed(loop, iters, max(iters // 5, 2))
            if per_iter is None:
                continue                  # noise swamped the delta
        except Exception:
            continue
        ips = batch / per_iter
        per_batch[batch] = round(ips, 1)
        mfu = ips / batch * flops_per_batch / V5E_PEAK_BF16_FLOPS
        if ips > best[0]:
            best = (ips, mfu, batch, flops_per_batch / batch)
    if not per_batch:
        raise RuntimeError(f"every batch size in {batches} failed")
    return best, per_batch


def bench_resnet(extras: dict) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.models import ModelDownloader

    loaded = ModelDownloader().download_by_name(
        "ResNet50", allow_random_init=True)  # weights init on host CPU

    rng = np.random.default_rng(0)

    def make_input(batch):
        return jnp.asarray(rng.normal(size=(batch, 224, 224, 3)),
                           jnp.bfloat16)

    raw = os.environ.get("MMLSPARK_TPU_BENCH_RESNET_BATCHES",
                         "128,256,512")
    try:
        batches = tuple(int(b) for b in raw.split(",") if b.strip())
        assert batches
    except (ValueError, AssertionError):
        batches = (128, 256, 512)  # a bad knob must never cost the line
    (ips, mfu, batch, fpi), per_batch = _mfu_sweep(
        loaded.module, loaded.variables, make_input, batches,
        fallback_flops_per_item=RESNET50_FLOPS_PER_IMAGE,
        output_key="pooled")
    extras["resnet50_mfu"] = round(mfu, 4)
    extras["resnet50_best_batch"] = batch
    extras["resnet50_ips_by_batch"] = per_batch
    extras["resnet50_flops_per_image"] = fpi
    extras["platform"] = jax.devices()[0].platform
    # the headline vs_baseline stays the batch-128 point (the A100
    # figure is a batch~128 number and earlier rounds measured 128);
    # the sweep best is in extras
    extras["resnet50_best_images_per_sec"] = round(ips, 1)

    # end-to-end ImageFeaturizer: HOST-resident images → device →
    # pooled features, exercising TPUModel's double-buffered dispatch
    # (the number a user's featurize pipeline actually sees). Fault-
    # isolated: a failure here must not zero the already-banked headline.
    try:
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.image import ImageFeaturizer
        n_img = 512
        imgs = rng.normal(size=(n_img, 224, 224, 3)).astype(np.float32)
        feat = ImageFeaturizer(model=loaded, cutOutputLayers=1,
                               inputCol="image", outputCol="features",
                               autoResize=False, miniBatchSize=128)
        df = DataFrame({"image": imgs})
        feat.transform(df)  # warm the (now per-instance-cached) compile
        t0 = time.perf_counter()
        feat.transform(df)
        extras["featurizer_e2e_images_per_sec"] = round(
            n_img / (time.perf_counter() - t0), 1)
        # realistic ingest: decoded JPEGs are uint8 — the wire keeps
        # them uint8 (4x fewer host->device bytes than f32), so this is
        # the number a real image pipeline sees
        imgs_u8 = (imgs - imgs.min()) / (np.ptp(imgs) + 1e-6)
        df_u8 = DataFrame(
            {"image": (imgs_u8 * 255).astype(np.uint8)})
        # depth 4: over the ~69 ms tunnel the double-buffer serializes
        # on each round trip; more in-flight batches overlap the RTTs
        feat_u8 = ImageFeaturizer(model=loaded, cutOutputLayers=1,
                                  inputCol="image", outputCol="features",
                                  autoResize=False, miniBatchSize=128,
                                  pipelineDepth=4)
        feat_u8.transform(df_u8)  # warm
        t0 = time.perf_counter()
        feat_u8.transform(df_u8)
        extras["featurizer_e2e_u8_images_per_sec"] = round(
            n_img / (time.perf_counter() - t0), 1)
        # the u8 row runs depth 4 (vs the f32 row's default 2) — record
        # it so cross-round deltas aren't misread as framework changes
        extras["featurizer_e2e_u8_pipeline_depth"] = 4
        # attribution: host prep vs async submit (incl. transfer
        # enqueue) vs device-wait+pull — so tunnel RTT can't masquerade
        # as framework overhead (VERDICT r3 Weak #6)
        if feat_u8.last_transform_stats:
            extras["featurizer_e2e_breakdown_ms"] = \
                feat_u8.last_transform_stats
    except Exception:
        extras["error_featurizer"] = traceback.format_exc()[-800:]

    # int8 post-training quantization (models/quantize.py): the v5e
    # MXU runs int8 at 2x the bf16 rate — measure what that buys the
    # featurizer's scoring path, with the fidelity number alongside so
    # the speedup is never quoted without its accuracy cost. Fault-
    # isolated; skipped off-accelerator (int8 conv on CPU crawls).
    try:
        if _PLATFORM not in ("tpu", "axon"):
            extras["resnet50_int8_skipped"] = \
                f"no accelerator ({_PLATFORM})"
        else:
            from mmlspark_tpu.models.quantize import (
                quantization_fidelity, quantize_resnet)
            qf, qp = quantize_resnet(loaded.module, loaded.variables)
            qp = jax.device_put(qp, jax.devices()[0])
            q_compiled = jax.jit(qf)
            xb = jax.device_put(
                jnp.asarray(rng.normal(size=(batch, 224, 224, 3)),
                            jnp.float32), jax.devices()[0])
            jax.block_until_ready(q_compiled(qp, xb))

            def loop(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    out = q_compiled(qp, xb)
                out.block_until_ready()
                return time.perf_counter() - t0

            per_iter = _diff_timed(loop, 20, 4)
            if per_iter is not None:
                q_ips = batch / per_iter
                extras["resnet50_int8_images_per_sec"] = round(q_ips, 1)
                extras["resnet50_int8_vs_bf16"] = round(
                    q_ips / max(ips, 1e-9), 3)
            small = np.asarray(rng.normal(size=(8, 224, 224, 3)),
                               np.float32)
            extras["resnet50_int8_fidelity_cos"] = round(
                quantization_fidelity(loaded.module, loaded.variables,
                                      q_compiled, qp, small), 5)
    except Exception:
        extras["error_resnet_int8"] = traceback.format_exc()[-600:]
    return per_batch.get(128, ips)


def bench_train(extras: dict) -> None:
    """ResNet-50 TRAINING throughput (SGD, bf16 activations) — the
    transfer-learning north star is a training workload; inference-only
    coverage was the r2 gap. FLOPs from XLA cost analysis of the
    COMPILED step (fwd+bwd+update), the same accounting bench_resnet
    uses — the round-3 analytic 3×fwd estimate undercounted the real
    conv FLOPs ~2× and made train MFU incomparable with inference MFU.
    Knobs: MMLSPARK_TPU_BENCH_TRAIN_REMAT=1 (block rematerialization),
    MMLSPARK_TPU_BENCH_TRAIN_OPT_BF16=1 (bf16 momentum buffer — halves
    the optimizer-state HBM traffic per step)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from mmlspark_tpu.dl.train import (init_train_state, make_train_step,
                                       train_epoch)
    from mmlspark_tpu.models import ModelDownloader

    remat = os.environ.get("MMLSPARK_TPU_BENCH_TRAIN_REMAT") == "1"
    opt_bf16 = os.environ.get("MMLSPARK_TPU_BENCH_TRAIN_OPT_BF16") == "1"
    loaded = ModelDownloader().download_by_name(
        "ResNet50", num_classes=100, allow_random_init=True,
        remat=remat or None)
    if remat:
        extras["train_remat"] = True
    tx = optax.sgd(1e-2, momentum=0.9,
                   accumulator_dtype=jnp.bfloat16 if opt_bf16 else None)
    if opt_bf16:
        extras["train_opt_bf16"] = True
    rng = np.random.default_rng(3)
    raw = os.environ.get("MMLSPARK_TPU_BENCH_TRAIN_BATCHES", "128,256")
    try:
        batches = tuple(int(b) for b in raw.split(",") if b.strip())
        assert batches
    except (ValueError, AssertionError):
        batches = (128, 256)
    device = jax.devices()[0]
    step = make_train_step(loaded.module, tx)
    per_batch: dict[int, float] = {}
    flops_per_image = 0.0
    e2e_step, e2e_batch = None, 0  # first SUCCESSFUL point's executable
    iters = 10
    loss = None
    for batch in batches:
        try:
            # fresh state per point: the step donates its input state,
            # and a larger batch must not inherit a donated-away buffer
            state = jax.device_put(
                init_train_state(loaded.module, jax.random.PRNGKey(0),
                                 np.zeros((1, 224, 224, 3), np.float32),
                                 tx),
                device)
            x = jax.device_put(jnp.asarray(
                rng.normal(size=(batch, 224, 224, 3)), jnp.float32),
                device)
            y = jax.device_put(jnp.asarray(
                rng.integers(0, 100, size=batch), jnp.int32), device)
            # ONE compile per point (AOT), serving cost analysis too
            compiled = step.lower(state, x, y).compile()
            if not flops_per_image:  # any successful point serves it
                from mmlspark_tpu.parallel.compat import cost_analysis
                cost = cost_analysis(compiled)
                flops_per_image = \
                    (cost["flops"] if cost else 0.0) / batch
            state, loss = compiled(state, x, y)   # warm
            jax.block_until_ready(loss)

            # RTT-cancelling differencing (same as _mfu_sweep): the
            # async loop pays the tunnel's pipeline-fill RTT once per
            # blocking call — at iters=10 that understated train MFU
            # by ~13%. The donated train state threads through a box.
            box = {"s": state, "loss": loss}

            def loop(n):
                s = box["s"]
                t0 = time.perf_counter()
                for _ in range(n):
                    s, lo = compiled(s, x, y)
                jax.block_until_ready(lo)
                box["s"], box["loss"] = s, lo
                return time.perf_counter() - t0

            per_iter = _diff_timed(loop, iters, 2)
            if per_iter is None:
                raise RuntimeError("timing noise swamped the delta")
            per_batch[batch] = round(batch / per_iter, 1)
            state, loss = box["s"], box["loss"]
            assert np.isfinite(float(loss))
            if e2e_step is None:  # first point that RAN successfully
                e2e_step, e2e_batch = compiled, batch
            del state, x, y
        except Exception:
            # one failing point (e.g. the largest batch OOMing HBM)
            # must not discard the measurements already banked
            extras[f"error_train_batch_{batch}"] = \
                traceback.format_exc()[-400:]
    if not per_batch:
        raise RuntimeError("every train batch size failed")
    if not flops_per_image:  # cost analysis unavailable: analytic 3×fwd
        flops_per_image = 3 * RESNET50_FLOPS_PER_IMAGE
    # headline stays the FIRST (=128 by default) point for cross-round
    # comparability, like bench_resnet; the sweep best rides extras
    headline = per_batch.get(batches[0], next(iter(per_batch.values())))
    best_batch = max(per_batch, key=per_batch.get)
    extras["train_images_per_sec"] = round(headline, 1)
    extras["train_best_batch"] = best_batch
    extras["train_best_images_per_sec"] = per_batch[best_batch]
    extras["train_ips_by_batch"] = per_batch
    extras["train_flops_per_image"] = flops_per_image
    # under remat the cost analysis counts recompute FLOPs, so the
    # ratio is hardware-FLOPs utilization (HFU), not MFU — bank it
    # under a distinct key so remat/non-remat runs stay comparable
    util_key = "train_hfu_est" if remat else "train_mfu_est"
    extras[util_key] = round(
        headline * flops_per_image / V5E_PEAK_BF16_FLOPS, 4)
    extras[util_key.replace("_est", "_best")] = round(
        per_batch[best_batch] * flops_per_image / V5E_PEAK_BF16_FLOPS, 4)

    # e2e: HOST-resident batches through the overlapped-transfer loop
    # (dl.train.train_epoch) — the number a fine-tune pipeline sees,
    # fault-isolated like the featurizer e2e. Reuses the batch[0] AOT
    # executable: lower().compile() bypasses step's jit cache, so
    # calling `step` here would re-trace + re-compile the whole graph.
    try:
        eb = e2e_batch
        state = jax.device_put(
            init_train_state(loaded.module, jax.random.PRNGKey(0),
                             np.zeros((1, 224, 224, 3), np.float32), tx),
            device)
        host_batches = [
            (rng.normal(size=(eb, 224, 224, 3)).astype(np.float32),
             rng.integers(0, 100, size=eb).astype(np.int32))
            for _ in range(4)]
        state, _ = train_epoch(e2e_step, state, host_batches[:1])  # warm
        t0 = time.perf_counter()
        state, losses = train_epoch(e2e_step, state, host_batches)
        extras["train_e2e_images_per_sec"] = round(
            eb * len(host_batches) / (time.perf_counter() - t0), 1)
    except Exception:
        extras["error_train_e2e"] = traceback.format_exc()[-400:]


def bench_vit(extras: dict) -> None:
    """ViT-B/16 inference MFU: transformer blocks are pure matmuls, the
    cleanest MXU utilization read the zoo offers."""
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.models import ModelDownloader

    loaded = ModelDownloader().download_by_name(
        "ViT_B_16", allow_random_init=True)
    rng = np.random.default_rng(1)

    def make_input(batch):
        return jnp.asarray(rng.normal(size=(batch, 224, 224, 3)),
                           jnp.bfloat16)

    # analytic fallback when XLA cost analysis is unavailable:
    # ViT-B/16 at 224² is ~17.6 GFLOPs/image (the published figure)
    (ips, mfu, batch, _), per_batch = _mfu_sweep(
        loaded.module, loaded.variables, make_input, (64, 128, 256),
        fallback_flops_per_item=17.6e9, output_key="pooled")
    extras["vit_images_per_sec"] = round(ips, 1)
    extras["vit_mfu"] = round(mfu, 4)
    extras["vit_best_batch"] = batch
    extras["vit_ips_by_batch"] = per_batch


def make_bench_encoder(impl: str):
    """TextEncoder forward MFU at a long-context shape, one attention
    impl per sub-bench (XLA dense vs the fused Pallas flash kernel,
    ``dl/pallas_attention.py``). Separate watchdog keys: a slow pallas
    compile must not discard a completed dense measurement."""

    def bench(extras: dict) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from mmlspark_tpu.dl.text_encoder import TextEncoder, \
            make_attention_fn

        raw_shape = os.environ.get("MMLSPARK_TPU_BENCH_ENCODER_SHAPE",
                                   "512,8,2048,2048")
        try:
            W, depth, mlp, T = (int(x) for x in raw_shape.split(","))
        except ValueError:
            W, depth, mlp, T = 512, 8, 2048, 2048
        rng = np.random.default_rng(2)
        ids0 = jnp.asarray(rng.integers(1, 32768, size=(1, T)),
                           jnp.int32)

        def make_input(batch):
            return jnp.asarray(rng.integers(1, 32768, size=(batch, T)),
                               jnp.int32)

        # analytic transformer-FLOPs fallback: per token per block,
        # qkv+out 8W², mlp 4·W·mlp, attention 4·T·W
        flops_per_seq = depth * T * (8 * W * W + 4 * W * mlp
                                     + 4 * T * W)
        module = TextEncoder(vocab=32768, width=W, depth=depth, heads=8,
                             mlp_dim=mlp,
                             attention_fn=make_attention_fn(impl))
        # init traces the forward: do it with the dense attention_fn
        # (attention has no params, so the variables are identical) —
        # tracing the Pallas kernel under a CPU default_device would
        # either fail to lower or crawl through the interpreter
        init_module = TextEncoder(vocab=32768, width=W, depth=depth,
                                  heads=8, mlp_dim=mlp,
                                  attention_fn=make_attention_fn("dense"))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            variables = init_module.init(jax.random.PRNGKey(0), ids0,
                                         False)
        (ips, mfu, batch, _), per_batch = _mfu_sweep(
            module, variables, make_input, (8, 16, 32), iters=10,
            fallback_flops_per_item=float(flops_per_seq),
            output_key="pooled", force_fallback_flops=True)
        extras[f"encoder_mfu_{impl}"] = round(mfu, 4)
        extras[f"encoder_ips_by_batch_{impl}"] = per_batch
        extras[f"encoder_seqs_per_sec_{impl}"] = round(ips, 1)
        extras[f"encoder_best_batch_{impl}"] = batch

        # train-step pace at the same long-context shape: exercises the
        # backward (pallas = fused FA2-style dq/dkv kernels; dense = XLA
        # autodiff through the materialized scores). Fault-isolated: a
        # bwd OOM must not discard the banked forward numbers.
        try:
            import optax

            from mmlspark_tpu.dl.train import (init_train_state,
                                               make_train_step)
            tb = 8
            tx = optax.sgd(1e-3)
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                state0 = init_train_state(
                    init_module, jax.random.PRNGKey(1), ids0, tx)
            state = jax.device_put(state0, jax.devices()[0])
            del state0
            xb = make_input(tb)
            yb = jnp.asarray(rng.integers(0, 2, size=tb), jnp.int32)
            step = make_train_step(
                module, tx, fetch="pooled",
                loss_fn=lambda pooled, y: jnp.mean(
                    (pooled.mean(-1) - y) ** 2))
            state, loss = step(state, xb, yb)     # compile + warm
            jax.block_until_ready(loss)

            # same RTT-cancelling differencing as _mfu_sweep; the
            # train state threads through a mutable box so each timed
            # loop continues from the last
            box = {"state": state}

            def loop(n):
                s = box["state"]
                t0 = time.perf_counter()
                for _ in range(n):
                    s, loss = step(s, xb, yb)
                jax.block_until_ready(loss)
                box["state"] = s
                return time.perf_counter() - t0

            per_iter = _diff_timed(loop, 5, 2)
            if per_iter is None:
                raise RuntimeError("timing noise swamped the delta")
            extras[f"encoder_train_seqs_per_sec_{impl}"] = round(
                tb / per_iter, 1)
        except Exception:
            extras[f"error_encoder_train_{impl}"] = \
                traceback.format_exc()[-500:]

    return bench


_ENCODER_IMPLS = ("dense", "pallas", "blockwise")


def _finalize_encoder(extras: dict, impls=_ENCODER_IMPLS) -> None:
    """Promote the fastest impl's numbers to the headline encoder keys."""
    best = None
    for impl in impls:
        ips = extras.get(f"encoder_seqs_per_sec_{impl}")
        if ips is not None and (best is None
                                or ips > extras[
                                    f"encoder_seqs_per_sec_{best}"]):
            best = impl
    if best is None:
        return  # every impl errored/timed out; error_* keys tell why
    extras["encoder_seqs_per_sec"] = extras[f"encoder_seqs_per_sec_{best}"]
    extras["encoder_mfu"] = extras[f"encoder_mfu_{best}"]
    extras["encoder_best_batch"] = extras[f"encoder_best_batch_{best}"]
    extras["encoder_ips_by_batch"] = extras[
        f"encoder_ips_by_batch_{best}"]
    extras["encoder_best_impl"] = best


def bench_encoder_int8(extras: dict) -> None:
    """int8 (w8a8-dynamic) TextEncoder vs the bf16 pallas path at the
    same long-context shape — what the 2x int8 MXU rate buys the
    embedding/scoring path, with fidelity alongside."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.dl.text_encoder import TextEncoder
    from mmlspark_tpu.models.quantize import quantize_text_encoder

    if _PLATFORM not in ("tpu", "axon"):
        extras["encoder_int8_skipped"] = f"no accelerator ({_PLATFORM})"
        return
    raw_shape = os.environ.get("MMLSPARK_TPU_BENCH_ENCODER_SHAPE",
                               "512,8,2048,2048")
    try:
        W, depth, mlp, T = (int(x) for x in raw_shape.split(","))
    except ValueError:
        W, depth, mlp, T = 512, 8, 2048, 2048
    rng = np.random.default_rng(2)
    module = TextEncoder(vocab=32768, width=W, depth=depth, heads=8,
                         mlp_dim=mlp)
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        variables = module.init(
            jax.random.PRNGKey(0),
            jnp.asarray(rng.integers(1, 32768, size=(1, T)),
                        jnp.int32), False)
    qf, qp = quantize_text_encoder(module, variables)
    qp = jax.device_put(qp, jax.devices()[0])
    f = jax.jit(qf)
    B = 8
    ids = jax.device_put(
        jnp.asarray(rng.integers(1, 32768, size=(B, T)), jnp.int32),
        jax.devices()[0])
    jax.block_until_ready(f(qp, ids))

    def loop(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(qp, ids)
        out.block_until_ready()
        return time.perf_counter() - t0

    per_iter = _diff_timed(loop, 10, 2)
    if per_iter is None:
        raise RuntimeError("timing noise swamped the delta")
    extras["encoder_int8_seqs_per_sec"] = round(B / per_iter, 1)
    # the int8-vs-bf16 ratio is computed in main() AFTER this
    # sub-bench merges: _watchdog hands each sub-bench a private
    # scratch dict, so the encoder rows are not visible from here
    from mmlspark_tpu.models.quantize import quantization_fidelity
    small = jnp.asarray(rng.integers(1, 32768, size=(2, 256)),
                        jnp.int32)
    extras["encoder_int8_fidelity_cos"] = round(
        quantization_fidelity(module, variables, f, qp, small), 5)


def bench_flash_causal(extras: dict) -> None:
    """Causal-vs-full flash attention timing at T=2048 (VERDICT r4 task
    1b): the pruned-grid causal kernel should approach the ~2x saving
    the lower-triangular structure implies. Also times the packed
    kernel against the pl.when streaming formulation so the pruning
    claim is measured, not asserted."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.dl.pallas_attention import flash_attention

    if _PLATFORM not in ("tpu", "axon"):
        # off-TPU the kernel would crawl through the Pallas interpreter
        # at T=2048 and burn the whole watchdog (same reasoning as the
        # encoder bench's dense-path fallback)
        extras["flash_causal_skipped"] = f"no accelerator ({_PLATFORM})"
        return

    rng = np.random.default_rng(0)
    B, H, T, D = 2, 8, 2048, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
               for _ in range(3))
    q, k, v = (jax.device_put(a, jax.devices()[0]) for a in (q, k, v))

    # at this shape one kernel run is tens of µs — far below the
    # tunnel's dispatch noise, which made an async re-dispatch loop
    # report anywhere from 0.9x to 9.7x run-to-run. Chain the kernel
    # on-device (one jit whose scan feeds each output back as the next
    # query) so executions serialize, AND difference two scan lengths
    # so the single blocking call's dispatch RTT (~69 ms through the
    # tunnel — RTT/iters would otherwise dominate a µs kernel and
    # compress every ratio toward 1) cancels out.
    # iters must be large enough that the kernel delta (iters × tens
    # of µs) dwarfs the tunnel's call-to-call RTT JITTER (~0.5-1 ms
    # even after min-of-reps): iters=50 produced negative differences
    def timed(causal, iters=400, base=50, reps=5):
        progs: dict = {}

        def run_loop(n):
            f = progs.get(n)
            if f is None:
                @jax.jit
                def chained(q0, _n=n):
                    def body(qc, _):
                        return flash_attention(qc, k, v,
                                               causal=causal), None
                    return jax.lax.scan(body, q0, None, length=_n)[0]
                jax.block_until_ready(chained(q))  # compile + warm
                progs[n] = f = chained
            t0 = time.perf_counter()
            jax.block_until_ready(f(q))
            return time.perf_counter() - t0

        per_iter = _diff_timed(run_loop, iters, base, reps=reps)
        if per_iter is None:
            raise RuntimeError("timing noise swamped the delta")
        return per_iter

    t_full = timed(False)
    t_causal = timed(True)
    extras["flash_full_ms_t2048"] = round(t_full * 1e3, 3)
    extras["flash_causal_ms_t2048"] = round(t_causal * 1e3, 3)
    extras["flash_causal_speedup_t2048"] = round(t_full / t_causal, 3)

    # the causal saving is the pruned-cell fraction, which approaches
    # the triangle's 2x only when T >> block: ~37% of cells prune at
    # T=2048 (bq=256, bk=512) vs ~47% at T=8192 — so also measure a
    # genuinely long sequence (B=1 keeps it inside the packed-KV VMEM
    # budget)
    B, H, T = 1, 8, 8192
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
               for _ in range(3))
    q, k, v = (jax.device_put(a, jax.devices()[0]) for a in (q, k, v))
    t_full = timed(False)
    t_causal = timed(True)
    extras["flash_full_ms_t8192"] = round(t_full * 1e3, 3)
    extras["flash_causal_ms_t8192"] = round(t_causal * 1e3, 3)
    extras["flash_causal_speedup_t8192"] = round(t_full / t_causal, 3)


def bench_gen(extras: dict) -> None:
    """Autoregressive decode throughput over the causal LM: batched
    prefill + KV-cached scan (``dl/generate.py``). Rows: prefill
    tokens/sec (one causal forward seeding the caches — MXU-batched),
    per-step decode latency/throughput, a batch sweep, and the
    cached-vs-re-encode speedup the KV cache exists to buy. No
    reference counterpart (text generation is the framework's
    extension axis, SURVEY §5)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.dl import MaskedLMModel, TextEncoder
    from mmlspark_tpu.dl.generate import generate
    from mmlspark_tpu.dl.text_encoder import make_attention_fn

    rng = np.random.default_rng(0)
    vocab, W, depth, mlp = 32768, 512, 8, 2048
    enc = TextEncoder(vocab=vocab, width=W, depth=depth, heads=8,
                      mlp_dim=mlp,
                      attention_fn=make_attention_fn("dense",
                                                     causal=True))
    module = MaskedLMModel(enc)
    # random weights: throughput does not depend on what the model
    # learned, and init on the host CPU keeps the remote compiler out
    # of weight initialization (same stance as bench_encoder)
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        variables = {"params": module.init(
            jax.random.PRNGKey(0),
            jnp.ones((1, 8), jnp.int32))["params"]}
    variables = jax.device_put(variables, jax.devices()[0])

    # 129 so the prefill bucket (multiples of 64) covers all but the
    # last prompt position — the split below then measures a FULL
    # batched prefill, not a half-streamed one
    Tp, new = 129, 128

    def timed(ids, n_new, use_cache=True, iters=3, max_len=None):
        generate(module, variables, ids, max_new_tokens=n_new,
                 use_cache=use_cache, max_len=max_len)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            generate(module, variables, ids, max_new_tokens=n_new,
                     use_cache=use_cache, max_len=max_len)
        return (time.perf_counter() - t0) / iters

    def prompts(B, T=Tp):
        return rng.integers(2, vocab, size=(B, T)).astype(np.int32)

    # prefill/decode split: new=1 is prefill + one scan step; the
    # difference to new=1+N spreads over exactly N more scan steps.
    # max_len is pinned so both programs run the same buffer/cache
    # shapes — the difference is then exactly N scan steps (and the
    # per-call dispatch RTT cancels)
    B = 32
    ids = prompts(B)
    L = Tp + new + 1
    t_one = timed(ids, 1, max_len=L)
    t_full = timed(ids, new + 1, max_len=L)
    per_step = (t_full - t_one) / new
    # t_one still contains one full blocking-dispatch RTT (the
    # differencing above only cancels it out of per_step) — measure
    # the null-dispatch floor explicitly and take it out of the
    # prefill, which is otherwise a few ms of compute under ~69 ms of
    # tunnel latency. Discard the row if noise leaves nothing.
    nul = jax.jit(lambda a: a + 1)
    z = jnp.zeros((8,), jnp.int32)
    jax.block_until_ready(nul(z))
    t_rtt = min(_t_block(nul, z) for _ in range(5))
    t_prefill = t_one - per_step - t_rtt
    if t_prefill > 0:
        extras["gen_prefill_tokens_per_sec"] = round(
            B * Tp / t_prefill, 1)
    extras["gen_decode_ms_per_step"] = round(per_step * 1000, 3)
    extras["gen_decode_tokens_per_sec"] = round(B / per_step, 1)
    extras["gen_tokens_per_sec"] = round(B * (new + 1) / t_full, 1)

    by_batch = {}
    for b in (1, 8, 32):
        by_batch[str(b)] = round(
            b * (new + 1) / timed(prompts(b), new + 1), 1)
    extras["gen_tokens_per_sec_by_batch"] = by_batch

    # what the KV cache buys: the re-encode reference recomputes the
    # whole O(L²·W) forward every step. Two traps fixed here (round-5
    # bench saw 0.91x): the comparison must run at a length where the
    # quadratic term is visible (at L ≤ 64 both paths are launch-bound
    # scans and the ratio measures cache-update overhead), and the
    # per-call dispatch RTT (~69 ms tunnel) must not pad both sides of
    # the ratio — so compare PER-STEP costs by differencing 1 vs 64
    # new tokens at a pinned max_len.
    ids2 = prompts(8, 257)
    L2 = 257 + 65

    def per_step(use_cache):
        t1 = timed(ids2, 1, use_cache=use_cache, max_len=L2)
        t64 = timed(ids2, 64, use_cache=use_cache, max_len=L2)
        return max((t64 - t1) / 63, 1e-9)

    extras["gen_cached_vs_reencode_speedup"] = round(
        per_step(False) / per_step(True), 2)

    # speculative decode, B=1 (the launch-latency-bound case): draft =
    # target is the acceptance UPPER BOUND (every proposal accepted,
    # k+1 tokens per verify pass) — random weights give a real draft
    # no way to agree, so this row measures what the machinery buys at
    # full acceptance, labeled as such. Output equality with plain
    # greedy is pinned by test regardless.
    try:
        from mmlspark_tpu.dl.speculative import generate_speculative
        ids1 = prompts(1)
        new1 = 64

        def timed_spec(iters=3):
            generate_speculative(module, variables, module, variables,
                                 ids1, max_new_tokens=new1, k=4)
            t0 = time.perf_counter()
            rate = 0.0
            for _ in range(iters):
                _, rate = generate_speculative(
                    module, variables, module, variables, ids1,
                    max_new_tokens=new1, k=4)
            return (time.perf_counter() - t0) / iters, rate

        t_spec, rate = timed_spec()
        t_plain = timed(ids1, new1, max_len=Tp + new1)
        extras["gen_spec_tokens_per_sec_b1"] = round(new1 / t_spec, 1)
        extras["gen_spec_tokens_per_pass"] = round(rate, 2)
        extras["gen_spec_vs_plain_b1"] = round(t_plain / t_spec, 2)

        # batched greedy speculation (sync-on-min): B=8 self-draft
        ids8 = prompts(8)
        generate_speculative(module, variables, module, variables,
                             ids8, max_new_tokens=new1, k=4)
        t0 = time.perf_counter()
        for _ in range(3):
            _, rate8 = generate_speculative(
                module, variables, module, variables, ids8,
                max_new_tokens=new1, k=4)
        t_spec8 = (time.perf_counter() - t0) / 3
        extras["gen_spec_tokens_per_sec_b8"] = round(
            8 * new1 / t_spec8, 1)
        extras["gen_spec_b8_tokens_per_pass"] = round(rate8, 2)
    except Exception:
        extras["error_gen_spec"] = traceback.format_exc()[-500:]


def bench_gbdt(extras: dict) -> None:
    """LightGBM-equivalent training throughput, Higgs-shaped synthetic
    (28 features, the dataset of the reference's speed claim)."""
    import numpy as np

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    n_rows = int(os.environ.get("MMLSPARK_TPU_BENCH_GBDT_ROWS", 500_000))
    n_iters = int(os.environ.get("MMLSPARK_TPU_BENCH_GBDT_ITERS", 20))
    rng = np.random.default_rng(7)
    feats = rng.normal(size=(n_rows, 28)).astype(np.float32)
    margin = feats[:, :4].sum(1) + feats[:, 4] * feats[:, 5]
    labels = (margin + rng.normal(size=n_rows) > 0).astype(np.float32)
    df = DataFrame({"features": feats, "label": labels})

    clf = LightGBMClassifier(numIterations=n_iters, numLeaves=31,
                             learningRate=0.1)
    clf.fit(df)  # warm the compile cache (binning + tree growth kernels)
    t0 = time.perf_counter()
    model = clf.fit(df)
    dt = time.perf_counter() - t0

    rows_per_sec = n_rows * n_iters / dt
    extras["gbdt_rows_per_sec"] = round(rows_per_sec, 1)
    extras["gbdt_fit_seconds"] = round(dt, 3)
    extras["gbdt_vs_lightgbm_cpu"] = round(
        rows_per_sec / GBDT_BASELINE_ROW_ITERS, 3)

    # scoring pace (the serving-relevant half; the reference scores
    # per-row over JNI, LightGBMBooster.score — here one batched
    # dispatch routes all rows through all trees)
    model.transform(df)  # warm
    t0 = time.perf_counter()
    model.transform(df)
    extras["gbdt_score_rows_per_sec"] = round(
        n_rows / (time.perf_counter() - t0), 1)


def bench_ranker(extras: dict) -> None:
    """LightGBMRanker lambdarank training pace on MSLR-WEB30K-shaped data
    (100 docs/query, graded 0-4 relevance from a latent utility)."""
    import numpy as np

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.lightgbm import LightGBMRanker

    n_queries = int(os.environ.get("MMLSPARK_TPU_BENCH_RANKER_QUERIES",
                                   1000))
    docs, n_iters = 100, 10
    n = n_queries * docs
    rng = np.random.default_rng(5)
    x = rng.normal(size=(n, 32)).astype(np.float32)
    w_true = rng.normal(size=32).astype(np.float32)
    util = x @ w_true + rng.normal(scale=2.0, size=n).astype(np.float32)
    rel = np.digitize(util, np.quantile(util, [0.5, 0.75, 0.9, 0.97])) \
        .astype(np.float32)
    qid = np.repeat(np.arange(n_queries), docs)
    df = DataFrame({"features": x, "label": rel, "query": qid})
    kw = dict(groupCol="query", numIterations=n_iters, numLeaves=31,
              seed=0)
    LightGBMRanker(**kw).fit(df)  # warm the compile cache
    t0 = time.perf_counter()
    m = LightGBMRanker(**kw).fit(df)
    dt = time.perf_counter() - t0
    extras["ranker_rows_per_sec"] = round(n * n_iters / dt, 1)
    extras["ranker_fit_seconds"] = round(dt, 3)
    extras["ranker_ndcg10"] = round(m.evaluate_ndcg(df, k=10), 4)


def bench_gbdt_sparse(extras: dict) -> None:
    """Padded-COO GBDT training pace on hashed-text-shaped data (high
    logical width, few entries per row) — the sparse engine
    (``lightgbm/sparse.py``) had no perf number before this."""
    import numpy as np

    from mmlspark_tpu.lightgbm.sparse import SparseData
    from mmlspark_tpu.lightgbm.trainer import TrainConfig, train

    n_rows = int(os.environ.get("MMLSPARK_TPU_BENCH_SPARSE_ROWS",
                                200_000))
    width, F, n_iters = 32, 10_000, 10
    rng = np.random.default_rng(13)
    # unique indices per row (the SparseData invariant): draw a wide
    # permutation block-wise to stay cheap at bench scale
    idx = np.stack([rng.choice(F, size=width, replace=False)
                    for _ in range(512)])
    idx = np.tile(idx, (n_rows // 512 + 1, 1))[:n_rows].astype(np.int32)
    val = rng.normal(size=(n_rows, width)).astype(np.float32)
    w_sig = rng.normal(size=F).astype(np.float32)
    margin = (val * w_sig[idx]).sum(1)
    y = (margin > 0).astype(np.float32)
    sd = SparseData(idx, val, F)
    cfg = TrainConfig(objective="binary", num_iterations=n_iters,
                      num_leaves=31, learning_rate=0.1)
    train(sd, y, None, cfg)  # warm the compile cache
    t0 = time.perf_counter()
    train(sd, y, None, cfg)
    dt = time.perf_counter() - t0
    extras["gbdt_sparse_rows_per_sec"] = round(n_rows * n_iters / dt, 1)
    extras["gbdt_sparse_fit_seconds"] = round(dt, 3)


def bench_vw(extras: dict) -> None:
    """VowpalWabbit-equivalent online learning pace: murmur-hash
    featurization (native batch hasher) + AdaGrad sparse SGD on device —
    the reference's third engine (``vw/VowpalWabbitBase.scala``) had no
    bench row before this."""
    import numpy as np

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.vw import (VowpalWabbitClassifier,
                                 VowpalWabbitFeaturizer)

    n_rows = int(os.environ.get("MMLSPARK_TPU_BENCH_VW_ROWS", 200_000))
    rng = np.random.default_rng(9)
    feats = rng.normal(size=(n_rows, 30)).astype(np.float32)
    labels = (feats[:, :5].sum(1) > 0).astype(np.float32)
    df = DataFrame({"features": feats, "label": labels})

    featurizer = VowpalWabbitFeaturizer(inputCols=["features"],
                                        outputCol="vw_features")
    hashed = featurizer.transform(df)       # warm any native load
    t0 = time.perf_counter()
    hashed = featurizer.transform(df)
    extras["vw_featurize_rows_per_sec"] = round(
        n_rows / (time.perf_counter() - t0), 1)

    passes = 3
    clf = VowpalWabbitClassifier(featuresCol="vw_features",
                                 numPasses=passes, numBits=18)
    clf.fit(hashed)  # warm the compile cache
    t0 = time.perf_counter()
    model = clf.fit(hashed)
    dt = time.perf_counter() - t0
    extras["vw_rows_per_sec"] = round(n_rows * passes / dt, 1)
    extras["vw_fit_seconds"] = round(dt, 3)

    model.transform(hashed)  # warm
    t0 = time.perf_counter()
    model.transform(hashed)
    extras["vw_score_rows_per_sec"] = round(
        n_rows / (time.perf_counter() - t0), 1)


def bench_observability(extras: dict) -> None:
    """Tracing/profiler overhead guard (ISSUE 8): the synthetic serving
    pipeline's p99 with the full tracing+profiler stack ON must stay
    within 5% of OFF, and the seeded chaos run must yield complete
    cross-process span trees. Banks the measured overhead so the bench
    JSON records what continuous observability actually costs."""
    from mmlspark_tpu.testing.benchmarks import (chaos_scenario,
                                                 tracing_overhead_scenario)

    r = tracing_overhead_scenario()
    extras["tracing_p99_off_ms"] = round(r["p99_off_s"] * 1e3, 3)
    extras["tracing_p99_on_ms"] = round(r["p99_on_s"] * 1e3, 3)
    extras["tracing_overhead_pct"] = round(r["overhead_pct"], 2)
    extras["tracing_overhead_within_5pct"] = bool(r["within_bound"])
    extras["tracing_feature_records"] = int(r["feature_records"])

    # the chaos trace acceptance, bench-side: every answered request's
    # cross-process tree is complete (driver queue + worker execute +
    # device under one trace id)
    c = chaos_scenario(seed=11, n_requests=24, n_workers=3)
    extras["tracing_chaos_answered"] = int(c["answered_200"])
    extras["tracing_chaos_complete_traces"] = int(c["complete_traces"])
    if c["sampled_trace"] is not None:
        extras["tracing_chaos_sampled_trace"] = \
            c["sampled_trace"]["trace_id"]


def bench_elasticity(extras: dict) -> None:
    """Multi-tenant elasticity acceptance (ISSUE 9): the seeded
    mixed-workload chaos scenario — three SLO-tiered tenants under
    diurnal load, one worker kill, one persistent degradation, 5%%
    injected 503s — banked as per-tenant p99 / shed-rate, utilization,
    and autoscale event counts, with the contract flags alongside so a
    regression shows up as a flipped boolean, not a silently drifting
    number."""
    from mmlspark_tpu.testing.benchmarks import mixed_tenant_scenario

    r = mixed_tenant_scenario()
    for name, p in r["per_tenant"].items():
        extras[f"tenant_{name}_p99_ms"] = round(p["p99_s"] * 1e3, 2)
        extras[f"tenant_{name}_shed_rate"] = round(p["shed_rate"], 4)
    extras["tenant_gold_within_slo"] = bool(r["within_gold_slo"])
    extras["tenant_silver_within_slo"] = bool(r["within_silver_slo"])
    extras["tenant_be_absorbed_burst"] = bool(r["be_absorbed_burst"])
    extras["tenant_utilization"] = round(r["utilization"], 3)
    extras["tenant_lease_replays"] = int(r["lease_replays"])
    extras["autoscale_ups"] = int(r["autoscale_ups"])
    extras["autoscale_downs"] = int(r["autoscale_downs"])
    extras["autoscale_replaces"] = int(r["autoscale_replaces"])
    extras["autoscale_workers_peak"] = int(r["workers_peak"])
    extras["autoscale_cooldown_violations"] = \
        int(r["cooldown_violations"])
    extras["autoscale_tracked_diurnal"] = bool(r["scaled_with_diurnal"])


def bench_pipeline_fusion(extras: dict) -> None:
    """Whole-pipeline XLA compilation acceptance (ISSUE 10): fused vs
    per-stage e2e latency and dispatch count on the featurizer
    (clean→assemble→infer→postproc) and text (host-tokenize→encoder)
    pipelines. Contract flags bank alongside the raw numbers: the
    featurizer pipeline must collapse to ≤ 2 dispatches per request,
    run ≥ 3× faster than eager per-stage execution, and stay
    bit-equivalent (atol 1e-5) on every benchmarked pipeline."""
    from mmlspark_tpu.testing.benchmarks import pipeline_fusion_scenario

    r = pipeline_fusion_scenario(n_rows=256, width=128, reps=40)
    for name in ("featurizer", "text"):
        p = r[name]
        extras[f"pipeline_fusion_{name}_eager_ms"] = round(
            p["eager_ms"], 3)
        extras[f"pipeline_fusion_{name}_fused_ms"] = round(
            p["fused_ms"], 3)
        extras[f"pipeline_fusion_{name}_speedup"] = round(
            p["speedup"], 2)
        extras[f"pipeline_fusion_{name}_dispatches"] = int(
            p["dispatches"])
        extras[f"pipeline_fusion_{name}_segments"] = int(p["segments"])
        extras[f"pipeline_fusion_{name}_equivalent"] = bool(
            p["equivalent"])
    extras["pipeline_fusion_le_2_dispatches"] = bool(
        r["featurizer_fused_le_2_dispatches"])
    extras["pipeline_fusion_speedup_ge_3x"] = bool(
        r["featurizer_speedup_ge_3x"])
    extras["pipeline_fusion_all_equivalent"] = bool(
        r["all_equivalent"])


def bench_aot(extras: dict) -> None:
    """AOT executable-store acceptance (ISSUE 11): compilation as a
    build step, not a request-latency event. Banks the store build
    wall time, the cold-vs-warm scale-up first-request latencies
    against steady-state p99, store hit/miss counts, and the contract
    flags — an autoscaler-added worker must serve its first request
    with zero runtime compiles (``profile_runtime_compiles_total == 0``,
    ``aot_store_hit_total >= 1``) within 2x steady-state p99, with
    AOT-loaded output bit-equal to the runtime-compiled segments."""
    from mmlspark_tpu.testing.benchmarks import aot_scale_up_scenario

    r = aot_scale_up_scenario()
    extras["aot_build_wall_s"] = round(r["build_wall_s"], 3)
    extras["aot_store_entries"] = int(r["store_entries"])
    extras["aot_steady_p99_ms"] = round(r["steady_p99_s"] * 1e3, 3)
    extras["aot_cold_first_ms"] = round(r["cold_first_s"] * 1e3, 3)
    extras["aot_warm_first_ms"] = round(r["warm_first_s"] * 1e3, 3)
    extras["aot_cold_over_steady"] = round(r["cold_over_steady"], 1)
    extras["aot_warm_over_steady"] = round(r["warm_over_steady"], 2)
    extras["aot_store_hits"] = int(r["store_hits"])
    extras["aot_store_misses"] = int(r["store_misses"])
    extras["aot_runtime_compiles"] = int(r["runtime_compiles"])
    extras["aot_scale_decision"] = r["scale_decision"]
    extras["aot_warm_within_2x_steady"] = bool(
        r["warm_within_2x_steady"])
    extras["aot_zero_runtime_compiles"] = bool(
        r["zero_runtime_compiles"])
    extras["aot_warm_hit_ge_1"] = bool(r["warm_hit_ge_1"])
    extras["aot_equivalent"] = bool(r["equivalent"])


def bench_costmodel(extras: dict) -> None:
    """Learned-performance-loop acceptance (ISSUE 12). Banks: (1) the
    cost model's held-out MAE vs the per-bucket EWMA baseline on a
    synthetic FeatureLog stream — the model must win (it sees entity
    bytes and queue depth; the EWMA cannot); (2) the deterministic
    predictive-autoscaling lead/lag — ticks between load rise and
    scale-up, reactive vs predictive; (3) the mixed-tenant diurnal
    scenario re-run with predictive autoscaling — scale-up lag vs the
    diurnal rise banked with the PR 8 gold contract flags alongside
    (zero gold sheds must survive the new brain); (4) autotuned-vs-
    default GBDT-histogram kernel timings on the acquired backend
    (interpreter off-TPU — the numbers are then schedule-relative, not
    device-representative, and are flagged as such)."""
    from mmlspark_tpu.perf import autotune
    from mmlspark_tpu.testing.benchmarks import (autoscale_lead_scenario,
                                                 costmodel_scenario,
                                                 mixed_tenant_scenario)

    r = costmodel_scenario()
    extras["costmodel_model_mae_ms"] = round(r["model_mae_ms"], 4)
    extras["costmodel_ewma_mae_ms"] = round(r["ewma_mae_ms"], 4)
    extras["costmodel_beats_ewma"] = bool(r["model_beats_ewma"])
    extras["costmodel_holdout_rows"] = int(r["n_holdout"])
    extras["costmodel_cold_falls_back"] = bool(r["cold_falls_back"])

    ll = autoscale_lead_scenario()
    extras["autoscale_lag_reactive_ticks"] = ll["lag_reactive_ticks"]
    extras["autoscale_lag_predictive_ticks"] = \
        ll["lag_predictive_ticks"]
    extras["autoscale_predictive_leads"] = bool(ll["predictive_leads"])

    m = mixed_tenant_scenario(predictive=True)
    extras["costmodel_predictive_gold_sheds"] = int(m["gold_sheds"])
    extras["costmodel_predictive_gold_within_slo"] = bool(
        m["within_gold_slo"])
    if m["scale_up_lag_s"] is not None:
        extras["costmodel_predictive_scale_up_lag_s"] = round(
            m["scale_up_lag_s"], 3)

    # autotune the histogram kernel at a modest shape on the acquired
    # backend; off-TPU the Pallas interpreter measures the schedule,
    # not the silicon — flagged so nobody banks an interpreter number
    # as a device one. The in-process winner table is restored after:
    # an interpreter-derived winner must not steer the hist kernel in
    # later bench sections of this same process.
    from mmlspark_tpu.lightgbm.pallas_hist import (DEFAULT_BLOCK_ROWS,
                                                   FEAT_BLOCK)
    on_tpu = _PLATFORM in ("tpu", "axon")
    shape = dict(n=(1 << 16), F=32, num_bins=64) if on_tpu else \
        dict(n=1024, F=8, num_bins=16)
    import tempfile
    tune_path = os.path.join(tempfile.mkdtemp(prefix="mmlspark_tpu_tune_"),
                             "autotune.json")
    prev_winners = dict(autotune._WINNERS)
    try:
        rec = autotune.tune_hist(shape["n"], shape["F"],
                                 shape["num_bins"], reps=3,
                                 interpret=None if on_tpu else True,
                                 path=tune_path)
    finally:
        autotune._WINNERS.clear()
        autotune._WINNERS.update(prev_winners)
    extras["autotune_hist_device_representative"] = bool(on_tpu)
    extras["autotune_hist_candidates"] = int(rec["candidates"])
    extras["autotune_hist_valid"] = int(rec["valid"])
    if rec["winner"] is not None:
        default_ms = next(
            (t["ms"] for t in rec["trials"]
             if t.get("feat_block") == FEAT_BLOCK
             and t.get("block_rows") == DEFAULT_BLOCK_ROWS
             and t.get("ms") is not None), None)
        extras["autotune_hist_best_ms"] = rec["winner"]["ms"]
        extras["autotune_hist_winner"] = {
            k: rec["winner"][k] for k in ("feat_block", "block_rows")}
        if default_ms is not None:
            extras["autotune_hist_default_ms"] = default_ms
            extras["autotune_hist_speedup_vs_default"] = round(
                default_ms / max(rec["winner"]["ms"], 1e-9), 3)


def bench_fleet(extras: dict) -> None:
    """Fleet telemetry plane acceptance (ISSUE 15). Banks: (1) the
    cost of one federated ``/metrics?scope=fleet`` exposition (8 ranks
    x 200 samples merged with identity relabeling) against the
    per-process alternative (8 separate ``/metrics`` renders) — the
    overhead a pod operator pays for the single-scrape view; (2) the
    chaos trajectory: waves from an injected ``worker.slow`` to the
    ``fleet_straggler`` flip (detection latency), the straggler-sourced
    autoscaler replace, the healthz ok→degraded→ok walk, and the gold
    burn-rate staying under the page threshold."""
    from mmlspark_tpu.obs.fleet import FleetAggregator
    from mmlspark_tpu.obs.metrics import MetricsRegistry
    from mmlspark_tpu.testing.benchmarks import fleet_chaos_scenario

    n_ranks, n_samples, reps = 8, 200, 50
    src = MetricsRegistry()
    g = src.gauge("profile_step_seconds_sum", "per-stage wall seconds")
    c = src.gauge("serving_requests_total", "requests by route")
    for j in range(n_samples // 2):
        g.set(j * 0.01, stage=f"s{j}")
        c.set(float(j), route=f"/r{j}")
    snap = src.snapshot()
    agg = FleetAggregator(MetricsRegistry(), max_sources=n_ranks)
    for rank in range(n_ranks):
        agg.ingest_snapshot(dict(snap), process=str(rank),
                            channel="bench")
    t0 = time.perf_counter()
    for _ in range(reps):
        fleet_text = agg.exposition()
    fleet_ms = (time.perf_counter() - t0) / reps * 1e3
    t0 = time.perf_counter()
    for _ in range(reps):
        for _rank in range(n_ranks):
            src.exposition()
    per_proc_ms = (time.perf_counter() - t0) / reps * 1e3
    extras["fleet_scrape_ms"] = round(fleet_ms, 3)
    extras["fleet_per_process_scrape_ms"] = round(per_proc_ms, 3)
    extras["fleet_scrape_overhead_x"] = round(
        fleet_ms / max(per_proc_ms, 1e-9), 3)
    extras["fleet_scrape_samples"] = sum(
        1 for ln in fleet_text.splitlines()
        if ln and not ln.startswith("#"))

    r = fleet_chaos_scenario(seed=31)
    extras["fleet_ticks_to_flag"] = int(r["ticks_to_flag"] or -1)
    extras["fleet_flagged"] = bool(r["flagged"])
    extras["fleet_straggler_replaces"] = int(r["straggler_replaces"])
    extras["fleet_healthz_trajectory"] = "->".join(r["verdicts"])
    extras["fleet_healthz_flipped"] = bool(r["healthz_flipped"])
    extras["fleet_recovered"] = bool(r["recovered"])
    extras["fleet_recover_waves"] = int(r["recover_waves"])
    extras["fleet_gold_burn"] = round(r["gold_burn"], 3)
    extras["fleet_gold_under_page"] = bool(r["gold_under_page"])
    extras["fleet_be_burn"] = round(r["be_burn"], 3)
    extras["fleet_hbm_devices"] = int(r["hbm_devices"])
    extras["fleet_mem_gauges_present"] = bool(r["mem_gauges_present"])


def bench_deploy(extras: dict) -> None:
    """Zero-downtime model-lifecycle acceptance (ISSUE 19). Banks the
    rollout scenario's contract surface: a blue/green flip across the
    autoscaled mixed-tenant fleet with zero non-canary 5xx, zero
    dropped in-flight requests and zero runtime compiles
    (``rollout_zero_5xx``), the seeded bad canary auto-rolled-back
    from burn rate alone within a bounded number of controller ticks
    (``rollback_ticks``) with the gold tier untouched
    (``canary_gold_sheds``) — plus a same-seed double run asserting
    the realized fault schedule is identical (the deploy plane's
    chaos is reproducible, same contract as bench_elasticity)."""
    from mmlspark_tpu.testing.benchmarks import rollout_scenario

    r = rollout_scenario(seed=29)
    r2 = rollout_scenario(seed=29, service="rollout-bench2")
    extras["rollout_zero_5xx"] = bool(
        r["rollout_zero_5xx"] and r["drained_completed"]
        and r["zero_runtime_compiles"])
    extras["rollout_non_canary_5xx"] = int(r["non_canary_5xx"])
    extras["rollout_unanswered"] = int(r["unanswered"])
    extras["rollout_byte_identical"] = bool(r["byte_identical"])
    extras["rollout_draining_final"] = int(r["draining_inflight_final"])
    extras["rollout_runtime_compiles"] = int(r["runtime_compiles"])
    extras["rollout_worker_killed"] = bool(r["worker_killed"])
    extras["rollout_lease_replays"] = int(r["lease_replays"])
    extras["rollback_ticks"] = int(r["rollback_ticks"] or -1)
    extras["rollback_reason"] = str(r["rollback_reason"])
    extras["rollback_restored_active"] = str(r["active_after"])
    extras["canary_5xx"] = int(r["canary_5xx"])
    extras["canary_gold_sheds"] = int(r["canary_gold_sheds"])
    extras["rollout_gold_unharmed"] = bool(r["gold_unharmed"])
    extras["rollout_workers_peak"] = int(r["workers_peak"])
    extras["rollout_schedule_reproducible"] = bool(
        r["schedule"] == r2["schedule"] and r["schedule"])


def bench_attribution(extras: dict) -> None:
    """Cost-attribution acceptance (ISSUE 20). Banks the scenario's
    contract surface: per-program roofline placement off real compiled
    programs (the matmul reads compute-bound, the wide add
    memory-bound, every utilization share <= 1.0), the fleet
    ``goodput_ratio`` under seeded chaos with the waste taxonomy
    itemized and the per-tick trace reproducible by seed, and the
    cost model's v6 analytic columns at least matching the v5
    baseline on held-out MAE."""
    from mmlspark_tpu.testing.benchmarks import attribution_scenario

    r = attribution_scenario(seed=29)
    r2 = attribution_scenario(seed=29)
    extras["attr_rooflines"] = r["rooflines"]
    extras["attr_matmul_compute_bound"] = bool(
        r["matmul_compute_bound"])
    extras["attr_add_memory_bound"] = bool(r["add_memory_bound"])
    extras["attr_utilization_max"] = round(
        float(r["utilization_max"]), 6)
    extras["attr_utilization_bounded"] = bool(
        r["utilization_max"] <= 1.05)
    extras["goodput_ratio"] = round(float(r["goodput_ratio"]), 6)
    extras["goodput_waste_seconds"] = r["goodput_waste_seconds"]
    extras["goodput_waste_itemized"] = bool(r["goodput_waste_itemized"])
    extras["goodput_schedule_reproducible"] = bool(
        r["goodput_ratio_trace"] == r2["goodput_ratio_trace"]
        and r["goodput_ratio_trace"])
    extras["costmodel_v6_mae_ms"] = round(float(r["v6_mae_ms"]), 4)
    extras["costmodel_v5_mae_ms"] = round(float(r["v5_mae_ms"]), 4)
    extras["costmodel_v6_no_worse"] = bool(r["v6_no_worse"])


def bench_serving(extras: dict) -> None:
    """End-to-end HTTP request→jitted pipeline→response latency against
    the reference's ~1 ms continuous-mode figure."""
    import http.client

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.io.http.schema import HTTPResponseData
    from mmlspark_tpu.serving.server import serving_query

    # Score on the HOST CPU backend: in this harness the TPU sits behind
    # a network tunnel, so a per-request device round-trip measures
    # tunnel RTT (~70 ms), not the serving stack. A production TPU host
    # is colocated with its chips; the front-end + dispatch latency —
    # what the reference's ~1 ms continuous-mode claim covers — is the
    # framework-attributable number. extras records the tunnel RTT
    # separately for transparency.
    cpu = jax.local_devices(backend="cpu")[0]
    w = jax.device_put(
        jnp.asarray(np.random.default_rng(3).normal(size=(16, 16)),
                    jnp.float32), cpu)

    @jax.jit
    def score(x):
        return jnp.tanh(x @ w).sum(axis=-1)

    # precompile EVERY power-of-two bucket the dynamic batcher can
    # produce under the loaded rows (bucket_pad below maps batches onto
    # these shapes): production servers warm their buckets at startup,
    # and an unwarmed bucket's compile otherwise lands in the loaded
    # tail as a ~50 ms outlier. The max bucket derives from the SAME
    # env knob the loaded rows read, so raising the concurrency cannot
    # reintroduce a novel shape mid-measurement.
    try:
        conc = int(os.environ.get("MMLSPARK_TPU_BENCH_SERVING_CONC",
                                  "16"))
    except ValueError:
        conc = 16  # a malformed knob must not cost every serving row
    conc = max(1, min(conc, 256))
    b = 1
    while b < 2 * max(conc, 16):
        score(jax.device_put(np.zeros((b, 16), np.float32),
                             cpu)).block_until_ready()
        b *= 2

    # Record the accelerator dispatch RTT so the CPU-host choice above is
    # auditable. Only meaningful when an actual accelerator is present —
    # on a CPU-only host the probe would measure local dispatch and
    # mislabel it as tunnel RTT. Skipped entirely when backend
    # acquisition failed: jax.devices() on a wedged tunnel HANGS rather
    # than raising, and this sub-bench must report serving numbers even
    # then (the CPU scoring path below is tunnel-independent).
    try:
        accel = [] if not _BACKEND_OK else \
            [d for d in jax.devices() if d.platform != "cpu"]
        if accel:
            y = jax.device_put(jnp.ones((8, 8), jnp.float32), accel[0])
            f = jax.jit(lambda a: a @ a)
            f(y).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(20):
                f(y).block_until_ready()
            extras["device_dispatch_rtt_ms"] = round(
                (time.perf_counter() - t0) / 20 * 1e3, 3)
    except Exception:
        pass

    from mmlspark_tpu.serving import bucket_pad

    def transform(df):
        xs = np.stack([
            np.frombuffer(r.entity, np.float32) if r.entity and
            len(r.entity) == 64 else np.zeros(16, np.float32)
            for r in df["request"]])
        # power-of-two batch buckets: a dynamic batcher produces every
        # batch size up to the in-flight count, and each NOVEL shape
        # pays a jit compile at request latency — measured as the
        # entire 16-way loaded tail (~96 ms p99 → ~5 ms)
        xs, n_real = bucket_pad(xs)
        ys = np.asarray(score(jax.device_put(xs, cpu)))[:n_real]
        replies = np.empty(len(ys), object)
        replies[:] = [HTTPResponseData(
            status_code=200, entity=json.dumps(float(y)).encode())
            for y in ys]
        return df.with_column("reply", replies)

    def latency_loop(addr, payload, n=300, warmup=50):
        """One keep-alive connection, n sequential requests → (p50 ms,
        p99 ms, non-200 count). Shared by the toy and real-model rows
        so the measurement protocol cannot drift between them."""
        conn = http.client.HTTPConnection(*addr, timeout=10)
        lat, errors = [], 0
        for _ in range(n):
            t0 = time.perf_counter()
            conn.request("POST", "/", body=payload)
            resp = conn.getresponse()
            resp.read()
            if resp.status != 200:
                errors += 1
            lat.append((time.perf_counter() - t0) * 1e3)
        conn.close()
        lat = np.sort(np.asarray(lat[warmup:]))
        return (float(np.percentile(lat, 50)),
                float(np.percentile(lat, 99)), errors)

    def measure(backend: str, suffix: str, *, transform_fn=None,
                payload=None, n=300, warmup=50, prefix="serving",
                conc=1):
        """Spin a query, run the latency loop, bank results under
        ``{prefix}{suffix}_*`` — ONE measurement protocol for the toy,
        real-model, and concurrency rows. ``conc > 1`` fans the loop
        out over that many keep-alive connections and banks aggregate
        throughput + worst per-connection tail latency instead of
        single-connection percentiles."""
        import threading

        query = serving_query(f"bench{prefix}{suffix}",
                              transform_fn or transform,
                              reply_timeout=10.0, backend=backend)
        try:
            if payload is None:
                payload = np.zeros(16, np.float32).tobytes()
            addr = query.server.address
            if conc == 1:
                p50, p99, errors = latency_loop(addr, payload, n=n,
                                                warmup=warmup)
                if errors:
                    raise RuntimeError(
                        f"{errors}/{n} serving requests returned "
                        "non-200 — latency figures would be "
                        "meaningless")
                extras[f"{prefix}{suffix}_p50_ms"] = round(p50, 3)
                extras[f"{prefix}{suffix}_p99_ms"] = round(p99, 3)
                return
            latency_loop(addr, payload, n=20, warmup=10)  # warm
            # loaded rows drive the closed loop from the NATIVE load
            # generator when it builds: a Python http.client worker
            # burns ~0.25 ms of GIL per request, capping the CLIENT at
            # ~4k req/s and stealing cycles from the server under test
            # (the native client measured the same native front at
            # 10k req/s where the python client reported 4k)
            try:
                import gc

                from mmlspark_tpu.serving.loadgen import run_load

                # the bench process carries models/arrays from earlier
                # rows; a GC pass mid-loop lands straight in the tail.
                # Collect first, hold GC off for the loop (the server
                # threads live in THIS process), and take the better
                # of two runs — a single p99 estimate at n=300 is
                # noisy and the first run double-serves as bucket
                # warmup under real concurrency.
                runs = []
                for _ in range(2):
                    gc.collect()
                    was = gc.isenabled()
                    gc.disable()
                    try:
                        runs.append(run_load(addr[0], addr[1], payload,
                                             nconn=conc, nreq=n))
                    finally:
                        if was:
                            gc.enable()
                r = min(runs, key=lambda x: x["loaded_p99_ms"])
                if r["errors"]:
                    raise RuntimeError(
                        f"{r['errors']} non-200s under {conc}-way "
                        "native-client load")
                extras[f"{prefix}{suffix}_concurrency"] = conc
                extras[f"{prefix}{suffix}_throughput_rps"] = round(
                    r["throughput_rps"], 1)
                extras[f"{prefix}{suffix}_loaded_p99_ms"] = round(
                    r["loaded_p99_ms"], 3)
                extras[f"{prefix}{suffix}_load_client"] = "native"
                if r.get("slowest"):
                    # flight-recorder lookup keys for the loaded tail:
                    # these trace ids resolve at GET /debug/trace on
                    # the server under test (ISSUE 8)
                    extras[f"{prefix}{suffix}_p99_slowest_traces"] = \
                        [s["trace_id"] for s in r["slowest"][:4]]
                return
            except Exception:
                # record WHY before falling back — a server failing
                # only at native-client rates must not silently bank
                # clean python-client numbers (and a loadgen build
                # failure must be distinguishable from a server error)
                extras[f"error_{prefix}{suffix}_loadgen"] = \
                    traceback.format_exc()[-500:]
                extras[f"{prefix}{suffix}_load_client"] = "python"
            results: list = [None] * conc

            def worker(i):
                # store failures — a thread exception would otherwise
                # vanish to stderr and surface only as a NoneType error
                try:
                    results[i] = latency_loop(addr, payload, n=n,
                                              warmup=0)
                except Exception as e:
                    results[i] = e

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(conc)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            failed = [r for r in results if isinstance(r, Exception)]
            if failed:
                raise RuntimeError(
                    f"{len(failed)}/{conc} connections failed under "
                    f"load; first: {failed[0]!r}")
            errors = sum(r[2] for r in results)
            if errors:
                raise RuntimeError(
                    f"{errors} non-200s under {conc}-way load")
            extras[f"{prefix}{suffix}_concurrency"] = conc
            extras[f"{prefix}{suffix}_throughput_rps"] = round(
                conc * n / dt, 1)
            extras[f"{prefix}{suffix}_loaded_p99_ms"] = round(
                max(r[1] for r in results), 3)
        finally:
            query.stop()

    measure("python", "")
    extras["serving_vs_1ms_target"] = round(
        SERVING_TARGET_MS / extras["serving_p99_ms"], 3)

    # concurrency throughput (the reference's serving story includes
    # sustained load, docs/mmlspark-serving.md; round-2 measured ~9k
    # req/s at 32-way by hand — this banks it). Same python front as
    # the baseline p50/p99 rows so loaded-vs-unloaded compares like
    # with like. Fault-isolated.
    try:
        measure("python", "", n=200, conc=conc)  # conc: warm-loop knob
    except Exception:
        extras["error_serving_throughput"] = \
            traceback.format_exc()[-500:]

    # REAL-model serving (VERDICT r3 Missing #5 / BASELINE configs[5]):
    # a FITTED LightGBM pipeline behind the front — request = one
    # feature row, reply = probability. This is the reference's actual
    # serving story ("the same ML pipeline as a web service",
    # docs/mmlspark-serving.md:9-12), not a toy matmul. Fault-isolated
    # and BEFORE the native measure: that one intentionally propagates
    # failures, and a native regression must not drop this row.
    try:
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.lightgbm import LightGBMClassifier
        rng2 = np.random.default_rng(17)
        xm = rng2.normal(size=(5000, 28)).astype(np.float32)
        ym = (xm[:, :4].sum(1) > 0).astype(np.float32)
        model = LightGBMClassifier(numIterations=5, numLeaves=15,
                                   seed=0).fit(
            DataFrame({"features": xm, "label": ym}))
        prob_col = model.getProbabilityCol()
        row_bytes = 28 * 4

        def model_transform(df):
            rows = np.stack([
                np.frombuffer(r.entity, np.float32)
                if r.entity and len(r.entity) == row_bytes
                else np.zeros(28, np.float32) for r in df["request"]])
            rows, n_real = bucket_pad(rows)  # same novel-shape guard
            probs = model.transform(
                DataFrame({"features": rows}))[prob_col][:n_real]
            replies = np.empty(len(df), object)
            replies[:] = [HTTPResponseData(
                status_code=200, entity=np.float32(p[1]).tobytes())
                for p in probs]
            return df.with_column("reply", replies)

        # the fitted GBDT scores on whatever backend is live: with the
        # chip up, every request pays a device dispatch THROUGH THE
        # TUNNEL inside the handler (~69 ms RTT dominates the row) —
        # mark it so a 68 ms model row next to a 1 ms cpu-host run is
        # read as tunnel placement, not a serving regression
        if _BACKEND_OK and _PLATFORM in ("tpu", "axon"):
            extras["serving_model_includes_tunnel_dispatch"] = True

        from mmlspark_tpu.native.loader import get_httpfront
        backends = [("python", "")]
        if get_httpfront() is not None:
            backends.append(("native", "_native"))
        # per-backend fault isolation: a python-leg failure must not
        # skip the native leg, and a native regression here gets its
        # own error key rather than vanishing into the python leg's
        for backend, suffix in backends:
            try:
                measure(backend, suffix, transform_fn=model_transform,
                        payload=xm[0].tobytes(), n=250,
                        prefix="serving_model")
            except Exception:
                extras[f"error_serving_model{suffix}"] = \
                    traceback.format_exc()[-500:]
    except Exception:
        extras["error_serving_model"] = traceback.format_exc()[-500:]

    # ResNet endpoint (BASELINE configs[5] names one): device-resident
    # zoo weights scoring one image per request — only meaningful with
    # an accelerator (on this harness the ~69 ms tunnel RTT rides the
    # latency; device_dispatch_rtt_ms above attributes it).
    try:
        if _BACKEND_OK and any(d.platform != "cpu" for d in jax.devices()):
            from mmlspark_tpu.core import DataFrame
            from mmlspark_tpu.image import ImageFeaturizer
            from mmlspark_tpu.models import ModelDownloader
            loaded = ModelDownloader().download_by_name(
                "ResNet50", allow_random_init=True)
            feat = ImageFeaturizer(model=loaded, cutOutputLayers=1,
                                   inputCol="image", outputCol="features",
                                   autoResize=False, miniBatchSize=8)
            img_bytes = 224 * 224 * 3 * 4

            def resnet_transform(df):
                imgs = np.stack([
                    np.frombuffer(r.entity, np.float32)
                    .reshape(224, 224, 3)
                    if r.entity and len(r.entity) == img_bytes
                    else np.zeros((224, 224, 3), np.float32)
                    for r in df["request"]])
                out = feat.transform(DataFrame({"image": imgs}))
                replies = np.empty(len(df), object)
                replies[:] = [HTTPResponseData(
                    status_code=200, entity=np.asarray(f).tobytes())
                    for f in out["features"]]
                return df.with_column("reply", replies)

            # warm the fixed-shape compile outside the timed loop
            probe = np.zeros((1, 224, 224, 3), np.float32)
            feat.transform(DataFrame({"image": probe}))
            payload = np.random.default_rng(23).normal(
                size=(224, 224, 3)).astype(np.float32).tobytes()
            measure("python", "", transform_fn=resnet_transform,
                    payload=payload, n=120, warmup=20,
                    prefix="serving_resnet")
        else:
            # explicit marker: "intentionally skipped" must be
            # distinguishable from "silently lost" in the artifact
            extras["serving_resnet_skipped"] = "no accelerator"
    except Exception:
        extras["error_serving_resnet"] = traceback.format_exc()[-500:]

    from mmlspark_tpu.native.loader import get_httpfront
    if get_httpfront() is not None:
        # a failure here is a native-front regression and must surface
        # (the watchdog records it as error_serving)
        measure("native", "_native")
        # native front under the SAME 16-way load as the python row:
        # the loaded-tail comparison is the whole point of having two
        # fronts. Fault-isolated like the python concurrency row.
        try:
            measure("native", "_native", n=200, conc=conc)
        except Exception:
            extras["error_serving_native_throughput"] = \
                traceback.format_exc()[-500:]
        # moderate (non-saturating) load: closed-loop saturation makes
        # latency = conc/throughput (Little's law), so the tail claim
        # needs a row where the server is NOT the bottleneck
        try:
            measure("native", "_native", n=400, conc=4,
                    prefix="serving_moderate")
        except Exception:
            extras["error_serving_moderate"] = \
                traceback.format_exc()[-500:]


def _serving_fallback(extras: dict) -> None:
    """Wedged-tunnel path: the serving stack is tunnel-independent, but
    ANY jax backend init in this process hangs on the axon site-hook
    (JAX_PLATFORMS env alone does not override it) — so re-exec just the
    serving sub-bench with the hook scrubbed from PYTHONPATH and the
    platform pinned to cpu, then merge its extras. Keeps the serving
    numbers on the scoreboard even when the accelerator is unreachable."""
    import subprocess
    import sys
    if os.environ.get("MMLSPARK_TPU_BENCH_FORCE_CPU") == "1":
        # already the scrubbed child — if backend init failed even here,
        # record it rather than recursing into more children
        extras["error_serving_fallback"] = \
            "backend init failed in the scrubbed child too"
        return
    from mmlspark_tpu.core.utils import scrubbed_cpu_env
    env = scrubbed_cpu_env()
    env["MMLSPARK_TPU_BENCH_FORCE_CPU"] = "1"
    env["MMLSPARK_TPU_BENCH_ONLY"] = "serving"
    proc = None
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, capture_output=True, text=True,
                              timeout=600)
        line = proc.stdout.strip().splitlines()[-1]
        child = json.loads(line).get("extras", {})
        merged_serving = False
        for k, v in child.items():
            if k.startswith("error"):
                extras.setdefault(f"serving_fallback_{k}", v)
            elif extras.setdefault(k, v) is v and k.startswith("serving"):
                merged_serving = True
        if merged_serving:
            extras["serving_measured_on"] = "cpu-host (tunnel down)"
    except Exception:
        # keep the child's actual cause, not just the parent-side parse
        # failure (diagnosability is the whole point of this suite)
        detail = traceback.format_exc()[-400:]
        if proc is not None:
            detail += (f"\nchild rc={proc.returncode}"
                       f"\nchild stderr: {(proc.stderr or '')[-800:]}")
        extras["error_serving_fallback"] = detail


def bench_multichip(extras: dict) -> None:
    """Sharded BERT train step + LightGBM histogram build on ALL local
    devices (the partition-rule engine end to end): throughput, weak-
    scaling efficiency vs 1 device, per-device MFU. Every earlier round
    benched single-host only — this is the row the pod-scale trajectory
    tracks.

    Runs in a scrubbed subprocess on a virtual 8-device CPU platform
    (the ``dryrun_multichip`` contract: the session environment pins
    JAX to the single-chip tunnel, which can never yield 8 devices and
    hangs when wedged); on a real multi-chip host the same body runs on
    the chips and these keys become chip numbers. The platform rides
    in ``multichip_platform`` so nobody mistakes host-CPU scaling
    numbers for TPU MFU."""
    import subprocess
    import sys

    from mmlspark_tpu.core.utils import scrubbed_cpu_env

    n = 8
    repo = os.path.dirname(os.path.abspath(__file__))
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from mmlspark_tpu.testing.multichip_bench import main; "
            f"main({n})")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=scrubbed_cpu_env(n, extra_path=repo), cwd=repo,
        capture_output=True, text=True,
        # the crosshost section spawns 2-process pods (each booting its
        # own jax + gloo + compiles) inside this subprocess — roughly a
        # second full bench body on top of the single-host sections
        timeout=1080 * _timeout_scale())
    parsed = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            candidate = json.loads(line)
        except ValueError:
            continue
        if isinstance(candidate, dict):  # skip stray scalar JSON lines
            parsed = candidate
            break
    if proc.returncode != 0 or not isinstance(parsed, dict):
        raise RuntimeError(
            f"multichip bench subprocess failed (rc={proc.returncode}):\n"
            f"{((proc.stdout or '') + (proc.stderr or ''))[-2000:]}")
    extras.update(parsed)


def bench_llm_serving(extras: dict) -> None:
    """Multi-host LLM serving bench: N independent scrubbed-subprocess
    "hosts" each run the paged-KV serving engine
    (``testing.benchmarks.llm_serving_scenario``: warmed prefill/decode
    programs, repeated-prefix workload, CompileTracker steady state)
    and report their registry-backed numbers as one JSON line; the
    parent aggregates them the way a fleet scoreboard would — summed
    tokens/sec across hosts, worst-host TTFT p99, mean prefix-cache
    hit rate. Host 0 additionally runs the speculative variant
    (self-draft ⇒ acceptance upper bound, labeled as such — same
    stance as bench_gen's spec rows).

    Scrubbed subprocesses for the same reason as bench_multichip: the
    session environment pins jax to the single-chip tunnel, and a
    wedged tunnel must not hang the parent. The platform rides in
    ``llm_platform`` so host-CPU numbers are never mistaken for TPU
    serving throughput."""
    import subprocess
    import sys

    from mmlspark_tpu.core.utils import scrubbed_cpu_env

    hosts = 2
    repo = os.path.dirname(os.path.abspath(__file__))

    def run_host(rank: int) -> dict:
        spec = ("out['spec'] = {k: v for k, v in llm_serving_scenario("
                f"service='llm-bench-spec{rank}', "
                "registry=MetricsRegistry(), spec_k=2, seed=29).items() "
                "if k != 'outputs'}; " if rank == 0 else "")
        code = (
            "import json; "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from mmlspark_tpu.obs.metrics import MetricsRegistry; "
            "from mmlspark_tpu.testing.benchmarks import "
            "llm_serving_scenario; "
            f"out = llm_serving_scenario(service='llm-bench{rank}', "
            f"registry=MetricsRegistry(), seed=17 + {rank}); "
            "out.pop('outputs'); "
            + spec +
            "print(json.dumps(out), flush=True)")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=scrubbed_cpu_env(extra_path=repo), cwd=repo,
            capture_output=True, text=True,
            timeout=420 * _timeout_scale())
        parsed = None
        for line in reversed((proc.stdout or "").splitlines()):
            try:
                candidate = json.loads(line)
            except ValueError:
                continue
            if isinstance(candidate, dict):
                parsed = candidate
                break
        if proc.returncode != 0 or not isinstance(parsed, dict):
            raise RuntimeError(
                f"llm serving host {rank} failed "
                f"(rc={proc.returncode}):\n"
                f"{((proc.stdout or '') + (proc.stderr or ''))[-2000:]}")
        return parsed

    results = [run_host(r) for r in range(hosts)]
    spec = results[0].pop("spec", None)
    extras["llm_hosts"] = hosts
    extras["llm_platform"] = "cpu-host (scrubbed subprocess)"
    extras["llm_tokens_per_sec"] = round(
        sum(r["tokens_per_s"] for r in results), 1)
    # the banked TTFT row the loadgen generation mode mirrors
    # client-side: worst host, p99, milliseconds
    extras["gen_ttft_p99_ms"] = round(
        max(r["ttft_p99_ms"] for r in results), 3)
    extras["llm_ttft_cold_p50_ms"] = round(
        max(r["ttft_cold_p50_ms"] for r in results), 3)
    extras["llm_ttft_warm_p50_ms"] = round(
        max(r["ttft_warm_p50_ms"] for r in results), 3)
    extras["llm_prefix_hit_rate"] = round(
        sum(r["prefix_hit_rate"] for r in results) / hosts, 3)
    extras["llm_ttft_warm_vs_cold"] = round(
        extras["llm_ttft_cold_p50_ms"]
        / max(extras["llm_ttft_warm_p50_ms"], 1e-9), 2)
    extras["llm_steady_state_ok"] = all(
        r.get("steady_state_ok") for r in results)
    extras["llm_aot_fingerprints"] = sum(
        r.get("aot_fingerprints", 0) for r in results)
    if spec is not None:
        extras["llm_spec_tokens_per_sec"] = round(
            spec["tokens_per_s"], 1)
        extras["llm_spec_accept_ratio"] = spec["spec_accept_ratio"]


def bench_llm_decode(extras: dict) -> None:
    """Long-context decode throughput, paged kernel vs the dense
    re-gather fallback, banked side by side
    (``testing.benchmarks.llm_decode_scenario``: >=4k tokens of
    resident KV, decode-only timed window, CompileTracker steady
    state). Two scrubbed subprocesses run the IDENTICAL scenario — the
    second with ``MMLSPARK_TPU_PAGED_ATTN=0`` — so the banked pair
    isolates the kernel swap: ``llm_decode_tokens_per_sec`` (paged,
    higher-good) against ``llm_decode_tokens_per_sec_dense``, and the
    per-run ``kv_dense_gather_bytes_total`` readings
    (``llm_decode_paged_gather_bytes`` must be exactly 0 — steady
    paged decode never re-materialises the dense cache;
    ``llm_decode_dense_gather_bytes`` is the bytes/run the old path
    pays). The RegressionGate reads direction from the names. The
    platform rides in ``llm_decode_platform`` so host-CPU numbers are
    never mistaken for TPU decode throughput."""
    import subprocess
    import sys

    from mmlspark_tpu.core.utils import scrubbed_cpu_env

    repo = os.path.dirname(os.path.abspath(__file__))

    def run_variant(paged: bool) -> dict:
        code = (
            "import json; "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from mmlspark_tpu.obs.metrics import MetricsRegistry; "
            "from mmlspark_tpu.testing.benchmarks import "
            "llm_decode_scenario; "
            "out = llm_decode_scenario("
            f"service='llm-decode-{'paged' if paged else 'dense'}', "
            "registry=MetricsRegistry()); "
            "out.pop('outputs'); "
            "print(json.dumps(out), flush=True)")
        env = scrubbed_cpu_env(extra_path=repo)
        if not paged:
            env["MMLSPARK_TPU_PAGED_ATTN"] = "0"
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, cwd=repo,
            capture_output=True, text=True,
            timeout=420 * _timeout_scale())
        parsed = None
        for line in reversed((proc.stdout or "").splitlines()):
            try:
                candidate = json.loads(line)
            except ValueError:
                continue
            if isinstance(candidate, dict):
                parsed = candidate
                break
        if proc.returncode != 0 or not isinstance(parsed, dict):
            raise RuntimeError(
                f"llm decode bench ({'paged' if paged else 'dense'}) "
                f"failed (rc={proc.returncode}):\n"
                f"{((proc.stdout or '') + (proc.stderr or ''))[-2000:]}")
        return parsed

    paged = run_variant(True)
    dense = run_variant(False)
    extras["llm_decode_platform"] = "cpu-host (scrubbed subprocess)"
    extras["llm_decode_context_tokens"] = paged["context_tokens"]
    extras["llm_decode_tokens_per_sec"] = round(
        paged["tokens_per_s"], 1)
    extras["llm_decode_tokens_per_sec_dense"] = round(
        dense["tokens_per_s"], 1)
    extras["llm_decode_paged_vs_dense"] = round(
        paged["tokens_per_s"] / max(dense["tokens_per_s"], 1e-9), 3)
    extras["llm_decode_paged_gather_bytes"] = paged[
        "dense_gather_bytes"]
    extras["llm_decode_dense_gather_bytes"] = dense[
        "dense_gather_bytes"]
    extras["llm_decode_attn_ms_per_step"] = round(
        paged["attn_ms_per_step"], 3)
    extras["llm_decode_steady_state_ok"] = bool(
        paged["steady_state_ok"] and dense["steady_state_ok"])


def _emit(images_per_sec: float, extras: dict) -> None:
    print(json.dumps({
        "metric": "imagefeaturizer_resnet50_inference",
        "value": round(images_per_sec, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(images_per_sec / A100_IMAGES_PER_SEC, 3),
        "extras": extras,
    }), flush=True)


def _compare_main(argv) -> int:
    """``bench.py --compare OLD.json [NEW.json]``: diff a banked run
    against another (NEW defaults to the newest committed BENCH_r0*
    trajectory file) through the obs.regression trajectory gate, print
    the table, and append the one-line verdict to BENCH_NOTES.md so
    the bank's narrative carries the diff. Host-side only — no
    backend, no jax."""
    from mmlspark_tpu.obs.regression import (compare_benches, format_table,
                                             gate_verdict,
                                             history_from_files, load_bench)
    args = [a for a in argv if a != "--compare"]
    if not args:
        print("usage: bench.py --compare OLD.json [NEW.json]")
        return 2
    trajectory = sorted(glob.glob("BENCH_r0*.json"))
    old_p = args[0]
    new_p = args[1] if len(args) > 1 else (
        trajectory[-1] if trajectory else None)
    if new_p is None:
        print("--compare: no NEW.json given and no BENCH_r0*.json found")
        return 2
    rows = compare_benches(load_bench(old_p), load_bench(new_p),
                           history_from_files(trajectory))
    print(f"{old_p} -> {new_p}")
    print(format_table(rows))
    verdict = gate_verdict(rows)
    print(verdict)
    with open("BENCH_NOTES.md", "a", encoding="utf-8") as f:
        f.write(f"\n- `--compare {os.path.basename(old_p)} -> "
                f"{os.path.basename(new_p)}`: {verdict}\n")
    return 1 if verdict.startswith("REGRESSION") else 0


def main():
    _ensure_cpu_backend_available()
    extras: dict = {}
    images_per_sec = 0.0
    only = os.environ.get("MMLSPARK_TPU_BENCH_ONLY", "")

    # the driver's patience is unknown and the full suite can run for
    # over an hour through the tunnel: a SIGTERM/SIGINT must still
    # produce the one-line JSON with whatever was measured (and banked)
    # so far, instead of dying silently mid-suite
    import signal

    def _on_term(signum, frame):
        try:
            # "error_" prefix so the tunnel watcher's error grep treats
            # a killed partial run as incomplete and keeps retrying
            extras.setdefault(
                "error_killed", f"signal {signum} mid-suite; partial results")
            # stale/last_measured_* is the WEDGED-tunnel contract only:
            # freshly measured numbers must never be stamped stale
            if "error_backend" in extras:
                _merge_banked_into(extras)
            _emit(images_per_sec, extras)
        finally:
            # 128+signum: a killed partial run must not look like a
            # clean one to drivers/shells checking the exit status
            os._exit(128 + signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_term)
        except (ValueError, OSError):
            pass  # non-main thread / unsupported platform

    def want(name: str) -> bool:
        return not only or name in only.split(",")

    # load-average guard (VERDICT r3 Weak #3: round 3's only GBDT number
    # was taken while pytest saturated the host) — timings taken on a
    # contended host are stamped, never passed off as clean
    try:
        load1 = os.getloadavg()[0]
        extras["load_avg_start"] = round(load1, 2)
        if load1 > 0.5 * (os.cpu_count() or 1):
            extras["contended"] = True
    except OSError:
        pass

    try:
        import jax
        if os.environ.get("MMLSPARK_TPU_BENCH_FORCE_CPU") == "1":
            # harness smoke / fallback mode: only the config update
            # reliably pins the platform (the axon hook ignores env)
            jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/mmlspark_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        devices = _acquire_backend()
        global _BACKEND_OK, _PLATFORM
        _PLATFORM = devices[0].platform
        _BACKEND_OK = True
    except Exception:
        extras["error_backend"] = traceback.format_exc()[-1500:]

    if "error_backend" not in extras:
        # ordered by banking priority: the known failure mode is the
        # tunnel wedging MID-suite, killing whatever is queued late —
        # headline first, then the trainer numbers, then the sweeps
        # (serving last: it alone has a cpu-host fallback). _watchdog
        # banks after every sub-bench (committed BENCH_TPU_BANKED.json)
        # so a later wedge can't erase what this one measured.
        if want("resnet"):
            images_per_sec = _watchdog(bench_resnet, extras, "resnet",
                                       600.0) or 0.0
            _bank(extras, images_per_sec, _PLATFORM)  # headline value
        if want("gbdt"):
            _watchdog(bench_gbdt, extras, "gbdt", 420.0)
        if want("ranker"):
            _watchdog(bench_ranker, extras, "ranker", 420.0)
        if want("vw"):
            _watchdog(bench_vw, extras, "vw", 300.0)
        if want("gbdt_sparse"):
            _watchdog(bench_gbdt_sparse, extras, "gbdt_sparse", 300.0)
        if want("train"):
            _watchdog(bench_train, extras, "train", 600.0)
        if want("vit"):
            _watchdog(bench_vit, extras, "vit", 600.0)
        if want("encoder"):
            raw_impls = os.environ.get("MMLSPARK_TPU_BENCH_ENCODER_IMPLS",
                                       ",".join(_ENCODER_IMPLS))
            impls = tuple(i.strip() for i in raw_impls.split(",")
                          if i.strip()) or _ENCODER_IMPLS
            for impl in impls:
                _watchdog(make_bench_encoder(impl), extras,
                          f"encoder_{impl}", 420.0)
            _finalize_encoder(extras, impls)
            _bank(extras, images_per_sec, _PLATFORM)  # encoder_* heads
        if want("encoder_int8"):
            _watchdog(bench_encoder_int8, extras, "encoder_int8",
                      420.0)
            # like-for-like ratio: int8 runs at B=8, so compare the
            # best bf16 impl's B=8 point (not its best-of-batch)
            by_batch = extras.get("encoder_ips_by_batch") or {}
            bf16_b8 = by_batch.get("8") or by_batch.get(8)
            int8 = extras.get("encoder_int8_seqs_per_sec")
            if int8 and bf16_b8:
                extras["encoder_int8_vs_bf16_b8"] = round(
                    int8 / bf16_b8, 3)
        if want("flashcausal"):
            _watchdog(bench_flash_causal, extras, "flashcausal", 300.0)
        if want("gen"):
            _watchdog(bench_gen, extras, "gen", 420.0)
        if want("multichip"):
            # scrubbed-subprocess bench: immune to a wedged tunnel, so
            # it can run even late in the suite
            _watchdog(bench_multichip, extras, "multichip", 600.0)
        if want("llm_serving"):
            # multi-host generation bench (paged KV + prefill/decode
            # executors): scrubbed subprocesses, tunnel-immune
            _watchdog(bench_llm_serving, extras, "llm_serving", 600.0)
        if want("llm_decode"):
            # long-context decode throughput, paged kernel vs dense
            # re-gather fallback banked side by side: scrubbed
            # subprocesses, tunnel-immune
            _watchdog(bench_llm_decode, extras, "llm_decode", 900.0)
        if want("observability"):
            # pure host-side (scheduler + in-thread mesh): tunnel-immune
            _watchdog(bench_observability, extras, "observability",
                      240.0)
        if want("elasticity"):
            # pure host-side (synthetic tenants + autoscaled pool):
            # tunnel-immune like observability
            _watchdog(bench_elasticity, extras, "elasticity", 240.0)
        if want("pipeline_fusion"):
            # fused vs per-stage pipelines on whatever backend the
            # suite acquired (devices already up by this point)
            _watchdog(bench_pipeline_fusion, extras, "pipeline_fusion",
                      240.0)
        if want("aot"):
            # build-step compilation vs request-latency compilation on
            # the acquired backend (store in a scenario-owned tmp dir)
            _watchdog(bench_aot, extras, "aot", 240.0)
        if want("costmodel"):
            # learned cost model vs EWMA, predictive-autoscale lead/lag,
            # and the kernel autotuner (host-side except the tune run)
            _watchdog(bench_costmodel, extras, "costmodel", 240.0)
        if want("fleet"):
            # fleet federation + chaos health trajectory (in-thread
            # mesh + synthetic snapshots: tunnel-immune)
            _watchdog(bench_fleet, extras, "fleet", 240.0)
        if want("deploy"):
            # blue/green flip + seeded-bad-canary rollback across the
            # synthetic fleet (host-side only: tunnel-immune)
            _watchdog(bench_deploy, extras, "deploy", 240.0)
        if want("attribution"):
            # roofline placement + goodput ledger + v6 cost-model value
            # (compiles two tiny programs on the acquired backend; the
            # rest is host-side)
            _watchdog(bench_attribution, extras, "attribution", 240.0)
        if want("serving"):
            # includes a small GBDT fit for the real-model row
            _watchdog(bench_serving, extras, "serving", 360.0)
    else:
        # with the backend wedged, even the CPU-scored serving bench
        # would hang in backend init here — run it in a scrubbed child
        _serving_fallback(extras)
        _merge_banked_into(extras)

    # disarm before the final print: a signal landing between _emit and
    # _exit would otherwise print a SECOND JSON line
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, signal.SIG_DFL)
        except (ValueError, OSError):
            pass
    _emit(images_per_sec, extras)
    # hard exit: a timed-out backend-acquisition thread is non-daemon and
    # would otherwise block interpreter shutdown after the line printed
    os._exit(0)


if __name__ == "__main__":
    import sys
    if "--compare" in sys.argv[1:]:
        sys.exit(_compare_main(sys.argv[1:]))
    main()
