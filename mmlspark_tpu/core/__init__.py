# NB: the bindings() sugar stays submodule-only — re-exporting it here
# would shadow the mmlspark_tpu.core.bindings module attribute
from .bindings import ColumnMetadata, DataclassBindings
from .dataframe import DataFrame, Row, GroupedData
from .param import (Param, Params, ComplexParam, TypeConverters, StageParam,
                    StageListParam, DataFrameParam, ArrayParam, UDFParam,
                    ServiceParam)
from .pipeline import (PipelineStage, Transformer, Estimator, Model, Pipeline,
                       PipelineModel, ml_transform, ml_fit)
from .compile import CompiledPipeline, compile_pipeline
from .serialize import load_stage, register_stage
from .utils import (ClusterUtil, StopWatch, retry_with_timeout,
                    find_unused_column_name, as_2d_features)
from . import contracts

__all__ = [
    "ColumnMetadata", "DataclassBindings",
    "DataFrame", "Row", "GroupedData",
    "Param", "Params", "ComplexParam", "TypeConverters", "StageParam",
    "StageListParam", "DataFrameParam", "ArrayParam", "UDFParam",
    "ServiceParam",
    "PipelineStage", "Transformer", "Estimator", "Model", "Pipeline",
    "PipelineModel", "ml_transform", "ml_fit",
    "CompiledPipeline", "compile_pipeline",
    "load_stage", "register_stage",
    "ClusterUtil", "StopWatch", "retry_with_timeout",
    "find_unused_column_name", "as_2d_features", "contracts",
]
