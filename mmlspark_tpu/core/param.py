"""Typed parameter system — the config backbone of every pipeline stage.

Mirrors the capability of SparkML ``Params`` plus the reference's complex-param
extensions (reference ``core/serialize/ComplexParam.scala``,
``org/apache/spark/ml/param/`` — 20 param types,
``org/apache/spark/ml/Serializer.scala:1-147``): parameters whose values are
not JSON-encodable (fitted models, functions, DataFrames, arrays) serialize
alongside pipeline metadata so whole pipelines round-trip through save/load.

Design: params are class-level ``Param`` descriptors on a ``Params`` subclass.
Setter/getter methods (``setFoo``/``getFoo``) are synthesized automatically,
which is what makes the binding/codegen layer (reference
``codegen/Wrappable.scala``) nearly free here.
"""

from __future__ import annotations

import json
import os
import pickle
import numpy as np
from typing import Any, Callable


class TypeConverters:
    """Value coercion/validation, analogous to pyspark's TypeConverters."""

    @staticmethod
    def identity(v):
        return v

    @staticmethod
    def toString(v):
        if v is None or isinstance(v, str):
            return v
        raise TypeError(f"expected str, got {type(v).__name__}")

    @staticmethod
    def toInt(v):
        if isinstance(v, bool):
            raise TypeError("expected int, got bool")
        if isinstance(v, (int, np.integer)):
            return int(v)
        if isinstance(v, float) and v.is_integer():
            return int(v)
        raise TypeError(f"expected int, got {type(v).__name__}")

    @staticmethod
    def toFloat(v):
        if isinstance(v, bool):
            raise TypeError("expected float, got bool")
        if isinstance(v, (int, float, np.integer, np.floating)):
            return float(v)
        raise TypeError(f"expected float, got {type(v).__name__}")

    @staticmethod
    def toBoolean(v):
        if isinstance(v, (bool, np.bool_)):
            return bool(v)
        raise TypeError(f"expected bool, got {type(v).__name__}")

    @staticmethod
    def toListString(v):
        if isinstance(v, (list, tuple, np.ndarray)):
            return [TypeConverters.toString(x) for x in v]
        raise TypeError(f"expected list[str], got {type(v).__name__}")

    @staticmethod
    def toListInt(v):
        if isinstance(v, (list, tuple, np.ndarray)):
            return [TypeConverters.toInt(x) for x in v]
        raise TypeError(f"expected list[int], got {type(v).__name__}")

    @staticmethod
    def toListFloat(v):
        if isinstance(v, (list, tuple, np.ndarray)):
            return [TypeConverters.toFloat(x) for x in v]
        raise TypeError(f"expected list[float], got {type(v).__name__}")

    @staticmethod
    def toDict(v):
        if isinstance(v, dict):
            return dict(v)
        raise TypeError(f"expected dict, got {type(v).__name__}")


class Param:
    """A typed, documented parameter slot. JSON-serializable values only."""

    complex = False

    def __init__(self, name: str, doc: str = "",
                 converter: Callable[[Any], Any] = TypeConverters.identity,
                 default: Any = None, has_default: bool | None = None):
        self.name = name
        self.doc = doc
        self.converter = converter
        self.default = default
        self.has_default = (default is not None) if has_default is None \
            else has_default

    def __set_name__(self, owner, attr):
        if attr != self.name:
            raise ValueError(f"Param attribute {attr!r} != name {self.name!r}")

    def __get__(self, obj, objtype=None):
        return self  # params are accessed as descriptors, values via get()

    def encode(self, value) -> Any:
        """To a JSON-encodable representation."""
        return _to_jsonable(value)

    def decode(self, payload) -> Any:
        return payload

    def __repr__(self):
        return f"Param({self.name!r})"


def _to_jsonable(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _to_jsonable(x) for k, x in v.items()}
    return v


class ComplexParam(Param):
    """A param whose value isn't JSON-encodable; persisted to its own subdir.

    Equivalent in role to the reference's ``ComplexParam`` hierarchy
    (``core/serialize/ComplexParam.scala``, ``EstimatorParam``, ``UDFParam``,
    ``DataFrameParam``, ``ByteArrayParam``, ...).
    """

    complex = True

    def save_value(self, value, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "value.pkl"), "wb") as f:
            pickle.dump(value, f)

    def load_value(self, path: str):
        with open(os.path.join(path, "value.pkl"), "rb") as f:
            return pickle.load(f)


class StageParam(ComplexParam):
    """Holds a pipeline stage (Estimator/Transformer/Model) as a value.

    Reference: ``EstimatorParam`` / ``TransformerParam`` / ``ModelParam``.
    """

    def save_value(self, value, path: str) -> None:
        value.save(path)

    def load_value(self, path: str):
        from .serialize import load_stage
        return load_stage(path)


class StageListParam(ComplexParam):
    """A list of pipeline stages (used by Pipeline itself)."""

    def save_value(self, value, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        manifest = []
        for i, stage in enumerate(value):
            sub = os.path.join(path, f"{i}")
            stage.save(sub)
            manifest.append(f"{i}")
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f)

    def load_value(self, path: str):
        from .serialize import load_stage
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        return [load_stage(os.path.join(path, name)) for name in manifest]


class DataFrameParam(ComplexParam):
    def save_value(self, value, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        arrays, meta = {}, {}
        for i, c in enumerate(value.columns):
            arrays[f"c{i}"] = value[c]
            meta[f"c{i}"] = c
        np.savez(os.path.join(path, "data.npz"),
                 **{k: v for k, v in arrays.items()})
        with open(os.path.join(path, "columns.json"), "w") as f:
            json.dump({"names": meta,
                       "num_partitions": value.num_partitions}, f)

    def load_value(self, path: str):
        from .dataframe import DataFrame
        with open(os.path.join(path, "columns.json")) as f:
            meta = json.load(f)
        npz = np.load(os.path.join(path, "data.npz"), allow_pickle=True)
        data = {meta["names"][k]: npz[k] for k in npz.files}
        return DataFrame(data, num_partitions=meta["num_partitions"])


class ArrayParam(ComplexParam):
    """Raw ndarray or pytree-of-ndarrays param (model weights etc.)."""

    def save_value(self, value, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        import jax
        leaves, treedef = jax.tree.flatten(value)
        np.savez(os.path.join(path, "leaves.npz"),
                 **{f"l{i}": np.asarray(x) for i, x in enumerate(leaves)})
        with open(os.path.join(path, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)

    def load_value(self, path: str):
        import jax
        npz = np.load(os.path.join(path, "leaves.npz"), allow_pickle=True)
        leaves = [npz[f"l{i}"] for i in range(len(npz.files))]
        with open(os.path.join(path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        return jax.tree.unflatten(treedef, leaves)


class UDFParam(ComplexParam):
    """User function param (reference ``UDFParam``); pickled."""


class ServiceParam(Param):
    """Scalar-or-column param for HTTP/cognitive stages.

    Reference ``cognitive/CognitiveServiceBase.scala:28-101``: every service
    argument can be set as a constant (``setX``) or per-row from a column
    (``setXCol``). Encoded as {"value": v} or {"col": name}; the converter
    wraps every entry path (constructor kwargs, set, setParams, copy) so
    the stored representation is always the tagged dict.
    """

    def __init__(self, name: str, doc: str = "", default: Any = None,
                 has_default: bool | None = None):
        super().__init__(name, doc, converter=ServiceParam._wrap,
                         default=default, has_default=has_default)

    @staticmethod
    def _wrap(v: Any) -> dict:
        if isinstance(v, dict) and v and set(v) <= {"value", "col"}:
            return dict(v)
        return {"value": v}


class Params:
    """Base for anything with params. Synthesizes set/get accessors."""

    _uid_counters: dict[str, int] = {}

    def __init__(self, **kwargs):
        cls = type(self)
        n = Params._uid_counters.get(cls.__name__, 0)
        Params._uid_counters[cls.__name__] = n + 1
        self.uid = f"{cls.__name__}_{n:04x}"
        self._paramMap: dict[str, Any] = {}
        self._defaultOverrides: dict[str, Any] = {}
        if kwargs:
            self.setParams(**kwargs)

    # ------------------------------------------------------------- reflection
    @classmethod
    def params(cls) -> list[Param]:
        # cached per class (stored in cls.__dict__, so subclasses build
        # their own): the MRO walk dominated hot paths like per-request
        # model scoring (~30 params() calls per transform). Params are
        # class attributes fixed at class-creation time — the framework
        # never attaches one at runtime.
        cached = cls.__dict__.get("_params_cache")
        if cached is not None:
            return cached
        out, seen = [], set()
        for klass in cls.__mro__:
            for k, v in vars(klass).items():
                if isinstance(v, Param) and k not in seen:
                    seen.add(k)
                    out.append(v)
        cls._params_cache = out
        return out

    @classmethod
    def get_param(cls, name: str) -> Param:
        cached = cls.__dict__.get("_param_by_name")
        if cached is None:
            cached = {p.name: p for p in cls.params()}
            cls._param_by_name = cached
        p = cached.get(name)
        if p is None:
            raise AttributeError(f"{cls.__name__} has no param {name!r}")
        return p

    @classmethod
    def has_param(cls, name: str) -> bool:
        return any(p.name == name for p in cls.params())

    hasParam = has_param

    # -------------------------------------------------------------- accessors
    def set(self, param: Param | str, value: Any) -> "Params":
        p = self.get_param(param) if isinstance(param, str) else param
        self._paramMap[p.name] = p.converter(value)
        return self

    def setParams(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    @staticmethod
    def _default_value(p: Param) -> Any:
        # Copy mutable defaults so callers can't corrupt the shared Param.
        if isinstance(p.default, list):
            return list(p.default)
        if isinstance(p.default, dict):
            return dict(p.default)
        return p.default

    def _setDefault(self, **kwargs) -> "Params":
        """Instance-level default overrides (SparkML ``setDefault``): used by
        stages whose natural defaults differ from the shared contract mixins
        (e.g. image stages default inputCol to "image")."""
        for k, v in kwargs.items():
            p = self.get_param(k)
            self._defaultOverrides[p.name] = \
                v if v is None else p.converter(v)
        return self

    def get(self, param: Param | str, default: Any = None) -> Any:
        p = self.get_param(param) if isinstance(param, str) else param
        if p.name in self._paramMap:
            return self._paramMap[p.name]
        if p.name in self._defaultOverrides:
            return self._defaultOverrides[p.name]
        if p.has_default:
            return self._default_value(p)
        return default

    def getOrDefault(self, param: Param | str) -> Any:
        p = self.get_param(param) if isinstance(param, str) else param
        if p.name in self._paramMap:
            return self._paramMap[p.name]
        if p.name in self._defaultOverrides:
            return self._defaultOverrides[p.name]
        if p.has_default:
            return self._default_value(p)
        raise KeyError(f"param {p.name!r} is not set and has no default")

    def isSet(self, param: Param | str) -> bool:
        p = self.get_param(param) if isinstance(param, str) else param
        return p.name in self._paramMap

    def isDefined(self, param: Param | str) -> bool:
        p = self.get_param(param) if isinstance(param, str) else param
        return (p.name in self._paramMap
                or p.name in self._defaultOverrides or p.has_default)

    def explainParams(self) -> str:
        lines = []
        for p in sorted(self.params(), key=lambda p: p.name):
            cur = self._paramMap.get(p.name, "undefined")
            dflt = p.default if p.has_default else "undefined"
            lines.append(f"{p.name}: {p.doc} (default: {dflt}, current: {cur})")
        return "\n".join(lines)

    def copy(self, extra: dict | None = None) -> "Params":
        out = type(self).__new__(type(self))
        out.__dict__.update(
            {k: v for k, v in self.__dict__.items()
             if k not in ("_paramMap", "_defaultOverrides")})
        out._paramMap = dict(self._paramMap)
        out._defaultOverrides = dict(self._defaultOverrides)
        if extra:
            out.setParams(**extra)
        return out

    def _copy_params_to(self, other: "Params") -> None:
        for name, value in self._paramMap.items():
            if other.has_param(name):
                other._paramMap[name] = value
        for name, value in self._defaultOverrides.items():
            if other.has_param(name) and name not in other._defaultOverrides:
                other._defaultOverrides[name] = value

    # -------------------------------------------------- synthesized accessors
    def __getattr__(self, item: str):
        # Only called when normal lookup fails: synthesize setX/getX, plus
        # setXCol for ServiceParams (scalar-or-column, reference
        # ``CognitiveServiceBase.scala:28-101``).
        if item.startswith("set") and len(item) > 3:
            if item.endswith("Col") and len(item) > 6:
                name = item[3].lower() + item[4:-3]
                if (type(self).has_param(name) and isinstance(
                        type(self).get_param(name), ServiceParam)):
                    def col_setter(col, _name=name):
                        return self.set(_name, {"col": col})
                    return col_setter
            name = item[3].lower() + item[4:]
            if type(self).has_param(name):
                def setter(value, _name=name):
                    return self.set(_name, value)
                return setter
        if item.startswith("get") and len(item) > 3:
            if item.endswith("Col") and len(item) > 6:
                name = item[3].lower() + item[4:-3]
                if (type(self).has_param(name) and isinstance(
                        type(self).get_param(name), ServiceParam)):
                    def col_getter(_name=name):
                        spec = self.getOrDefault(_name)
                        return spec.get("col") if isinstance(spec, dict) \
                            else None
                    return col_getter
            name = item[3].lower() + item[4:]
            if type(self).has_param(name):
                p = type(self).get_param(name)

                def getter(_name=name, _p=p):
                    v = self.getOrDefault(_name)
                    # ServiceParam getX returns the scalar (reference
                    # getter symmetry); column bindings read via getXCol
                    if isinstance(_p, ServiceParam) and isinstance(v, dict):
                        return v.get("value")
                    return v
                return getter
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {item!r}")

    def __repr__(self):
        shown = ", ".join(f"{k}={v!r}" for k, v in sorted(self._paramMap.items())
                          if not isinstance(v, (np.ndarray,)))
        return f"{type(self).__name__}({shown})"
