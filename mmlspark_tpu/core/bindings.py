"""Dataclass ↔ DataFrame row codecs.

Reference ``core/schema/SparkBindings.scala:13-39``: a case-class ↔ Row
codec derived once per type via ``ExpressionEncoder`` and reused by the
HTTP/serving/cognitive layers to get typed views over rows. Here the
typed carrier is a ``@dataclass``; the codec walks its (possibly nested)
field structure.

Also carries the categorical-metadata companion
(``core/schema/Categoricals.scala``): level lists attached to a column
travel with the DataFrame through select/filter-style operations via
:class:`ColumnMetadata`.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, get_args, get_origin

import numpy as np

from .dataframe import DataFrame


class DataclassBindings:
    """Codec for one dataclass type (reference ``SparkBindings[T]``)."""

    def __init__(self, cls: type):
        if not dataclasses.is_dataclass(cls):
            raise TypeError(f"{cls!r} is not a dataclass")
        self.cls = cls
        self.fields = dataclasses.fields(cls)
        self.hints = typing.get_type_hints(cls)

    # ------------------------------------------------------------ encoding
    def to_df(self, items: list) -> DataFrame:
        """list[T] → DataFrame with one column per field (nested
        dataclasses stay nested as object cells)."""
        cols: dict[str, np.ndarray] = {}
        for f in self.fields:
            vals = [self._encode(getattr(it, f.name)) for it in items]
            arr = np.empty(len(items), object)
            arr[:] = vals
            cols[f.name] = arr
        return DataFrame(cols)

    def _encode(self, v: Any) -> Any:
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            return {f.name: self._encode(getattr(v, f.name))
                    for f in dataclasses.fields(v)}
        if isinstance(v, (list, tuple)):
            return [self._encode(x) for x in v]
        if isinstance(v, np.generic):
            return v.item()
        return v

    # ------------------------------------------------------------ decoding
    def from_df(self, df: DataFrame) -> list:
        """DataFrame → list[T]; missing columns use field defaults."""
        out = []
        for i in range(len(df)):
            kwargs = {}
            for f in self.fields:
                if f.name in df.columns:
                    kwargs[f.name] = self._decode(
                        df[f.name][i], self.hints.get(f.name))
                elif f.default is not dataclasses.MISSING:
                    kwargs[f.name] = f.default
                elif f.default_factory is not dataclasses.MISSING:
                    kwargs[f.name] = f.default_factory()
                else:
                    raise KeyError(
                        f"column {f.name!r} absent and field has no "
                        f"default (decoding {self.cls.__name__})")
            out.append(self.cls(**kwargs))
        return out

    def _decode(self, v: Any, hint) -> Any:
        if hint is None:
            return v
        import types
        origin = get_origin(hint)
        if origin in (typing.Union, types.UnionType):  # Optional[T], X | None
            args = [a for a in get_args(hint) if a is not type(None)]
            if v is None:
                return None
            return self._decode(v, args[0]) if len(args) == 1 else v
        if dataclasses.is_dataclass(hint) and isinstance(v, dict):
            sub = DataclassBindings(hint)
            kwargs = {f.name: sub._decode(v.get(f.name),
                                          sub.hints.get(f.name))
                      for f in sub.fields if f.name in v}
            return hint(**kwargs)
        if origin in (list, tuple) and isinstance(v, (list, tuple,
                                                      np.ndarray)):
            args = get_args(hint)
            elem = args[0] if args else None
            seq = [self._decode(x, elem) for x in v]
            return tuple(seq) if origin is tuple else seq
        if isinstance(v, np.generic):
            v = v.item()
        if hint in (int, float, str, bool) and v is not None:
            return hint(v)
        return v


def bindings(cls: type) -> DataclassBindings:
    """Sugar mirroring the reference's companion-object pattern."""
    return DataclassBindings(cls)


# ---------------------------------------------------------------- metadata
class ColumnMetadata:
    """Per-column metadata side-channel (reference ``Categoricals.scala``
    attaches category levels to ML attributes; DataFrame columns here are
    bare arrays, so metadata rides in this registry keyed by the column's
    identity array)."""

    _KEY = "__column_metadata__"

    @classmethod
    def attach(cls, df: DataFrame, col: str, meta: dict) -> DataFrame:
        """Return a df whose ``col`` carries ``meta``; stored on the
        DataFrame instance and copied by value to derived frames that
        keep the column (via ``carry``)."""
        store = dict(getattr(df, cls._KEY, {}))
        store[col] = dict(meta)
        setattr(df, cls._KEY, store)
        return df

    @classmethod
    def get(cls, df: DataFrame, col: str) -> dict | None:
        return getattr(df, cls._KEY, {}).get(col)

    @classmethod
    def carry(cls, src: DataFrame, dst: DataFrame) -> DataFrame:
        """Propagate metadata for every column dst kept from src.

        Row-subset derivations (filter/take/sample/split) keep per-column
        schema metadata valid, so propagation is by NAME; the one
        invalidating operation — replacing a column's values under the
        same name — is handled where it happens
        (``DataFrame.with_column`` calls :meth:`invalidate`). Stale
        slot_names silently resolving against a rebuilt column would be
        worse than none."""
        store = {c: dict(m) for c, m in getattr(src, cls._KEY, {}).items()
                 if c in dst.columns}
        if store:
            setattr(dst, cls._KEY, {**getattr(dst, cls._KEY, {}), **store})
        return dst

    @classmethod
    def invalidate(cls, df: DataFrame, col: str) -> DataFrame:
        """Drop ``col``'s metadata (its values were replaced)."""
        store = getattr(df, cls._KEY, None)
        if store and col in store:
            store = dict(store)
            del store[col]
            setattr(df, cls._KEY, store)
        return df

    # categorical sugar (the reference's dominant metadata use)
    @classmethod
    def set_categorical(cls, df: DataFrame, col: str,
                        levels: list) -> DataFrame:
        return cls.attach(df, col, {"categorical": True,
                                    "levels": list(levels)})

    @classmethod
    def categorical_levels(cls, df: DataFrame, col: str) -> list | None:
        meta = cls.get(df, col) or {}
        return meta.get("levels") if meta.get("categorical") else None
