"""Columnar DataFrame abstraction — the TPU-native stand-in for Spark DataFrames.

The reference framework operates on Spark DataFrames flowing through
Estimator/Transformer pipeline stages (see reference
``core/schema/SparkBindings.scala:13-39`` for its typed row views). A
row-oriented JVM DataFrame is the wrong shape for a TPU: the accelerator wants
large, fixed-shape, contiguous arrays it can tile onto the MXU. So this
DataFrame is columnar from the start:

- every column is a NumPy array (1-D for scalars, 2-D for fixed-width vector
  columns, object dtype for strings/bytes/ragged values);
- numeric columns convert to ``jax.numpy`` arrays zero-copy via
  ``DataFrame.jnp(col)``;
- "partitions" — Spark's unit of data parallelism — are a lightweight metadata
  concept here (``num_partitions``) used by stages that mirror the reference's
  partition semantics (Repartition, PartitionConsolidator, distributed
  training); the actual device layout is decided by ``jax.sharding`` at
  compute time.
"""

from __future__ import annotations

import numpy as np
from typing import Any, Callable, Iterable, Mapping, Sequence


def _normalize_column(values: Any, n_rows: int | None = None) -> np.ndarray:
    """Normalize arbitrary user input into a canonical column array."""
    if isinstance(values, np.ndarray):
        arr = values
    elif hasattr(values, "__array__") and getattr(values, "shape", None):
        # device-backed arrays (jax.numpy) land here: stages compute in
        # jnp and hand results straight to with_column — materializing
        # at the DataFrame boundary is THE host sync point (the fused
        # pipeline path skips this entirely between stages). 0-d
        # scalars (shape == (), falsy) fall through to the scalar
        # broadcast below
        arr = np.asarray(values)
    elif isinstance(values, (list, tuple)):
        has_seq = any(isinstance(v, (list, tuple, np.ndarray)) for v in values)
        if has_seq:
            # Potential vector column: only keep 2-D if rectangular & numeric.
            try:
                arr = np.asarray(values)
                if arr.dtype == object or arr.ndim == 1:
                    raise ValueError("ragged")
            except ValueError:
                arr = np.empty(len(values), dtype=object)
                arr[:] = [np.asarray(v) if isinstance(v, (list, tuple)) else v
                          for v in values]
        else:
            try:
                arr = np.asarray(values)
            except ValueError:
                arr = np.empty(len(values), dtype=object)
                arr[:] = list(values)
            if arr.dtype.kind == "U":
                arr = arr.astype(object)
    else:
        # scalar broadcast
        if n_rows is None:
            raise ValueError("cannot broadcast scalar column without row count")
        if isinstance(values, str) or values is None:
            arr = np.full(n_rows, values, dtype=object)
        else:
            arr = np.full(n_rows, values)
    if arr.dtype.kind == "U":
        arr = arr.astype(object)
    if n_rows is not None and arr.ndim == 0:
        arr = np.full(n_rows, arr[()])
    if n_rows is not None and arr.shape[0] != n_rows:
        raise ValueError(
            f"column length {arr.shape[0]} != DataFrame length {n_rows}")
    return arr


# ---------------------------------------------------------- host boundary
# The ONE place stage code materializes device values / builds object
# (string, ragged) columns. Stages and featurizers route their host
# plumbing through these helpers so their own transform/fit bodies stay
# free of host ops — that is what graftcheck's traceability report
# measures, and what lets the pipeline compiler (core/compile.py) fuse
# them. Genuinely host-bound work (tokenizer string loops, HTTP) stays
# in the stages and keeps them HOST-BOUND, by design.

def jittable_dtype(dtype) -> bool:
    """Can a column of this dtype enter a traced (jit) segment? Numeric
    and bool only — object (string/ragged) and datetime columns stay on
    host (``core/compile.py`` carries them around fused segments)."""
    return getattr(dtype, "kind", "") in "biuf"


def to_host(values: Any) -> np.ndarray:
    """Materialize a (possibly device-backed) array on host as numpy.
    For a jax array this is the device→host sync; for numpy it is
    free."""
    return np.asarray(values)


def to_host_list(values: Any) -> list:
    """Materialize as a plain Python list (param storage, level lists)."""
    return np.asarray(values).tolist()


def object_column(cells: Iterable) -> np.ndarray:
    """Build a 1-D object column from arbitrary per-row cells without
    numpy guessing at a rectangular layout (lists of arrays must stay
    one-cell-per-row)."""
    cells = list(cells)
    arr = np.empty(len(cells), dtype=object)
    arr[:] = cells
    return arr


def repeat_rows(values: np.ndarray, lengths: Iterable[int]) -> np.ndarray:
    """Repeat each row of ``values`` by the matching length (the
    FlattenBatch/Explode scalar-broadcast path)."""
    return np.repeat(values, np.asarray(list(lengths)), axis=0)


def unique_host(values, return_counts: bool = False,
                drop_nan: bool = False):
    """EXACT distinct values of a host column — the fit-time helper.
    Fitted params (category levels, class-weight keys) must hold the
    exact values ``transform`` will later look up; routing uniqueness
    through the device would round them through jax's 32-bit lattice
    (float64 0.1 → 0.10000000149…, int64 ≥ 2**31 truncated) and the
    fitted model would miss the very values it was fit on."""
    arr = np.asarray(values)
    if return_counts:
        vals, cnts = np.unique(arr, return_counts=True)
        if drop_nan and vals.dtype.kind == "f":
            keep = ~np.isnan(vals)
            vals, cnts = vals[keep], cnts[keep]
        return vals, cnts
    vals = np.unique(arr)
    if drop_nan and vals.dtype.kind == "f":
        vals = vals[~np.isnan(vals)]
    return vals


def argsort_host(values) -> np.ndarray:
    """EXACT stable argsort on host. Epoch-millisecond timestamps are
    int64 ~1.7e12 — a device argsort truncates them to int32 and
    inverts the order across every 2**31 wrap."""
    return np.argsort(np.asarray(values), kind="stable")


def concat_host(parts) -> np.ndarray:
    """EXACT concatenation of host arrays along axis 0. Routing the
    eager un-batch path through the device would demote int64 columns
    (epoch millis wrap at 2**31) and float64 to float32; host columns
    flatten on host in their own dtype."""
    return np.concatenate([np.asarray(p) for p in parts], axis=0)


def f32_exact(value) -> bool:
    """True if ``value`` survives a float32 round-trip exactly — the
    gate for traced lookup tables. Fitted keys compare in the device's
    float32 lattice; a key that doesn't round-trip (ints ≥ 2**24,
    float64 dust) would silently collide with a neighbor or miss."""
    v = float(value)
    return float(np.float32(v)) == v


def quantile_host(values, q) -> float:
    """EXACT quantile of a host column in its own dtype — the profiling
    helper. Summary statistics are reporting output, not device math:
    a float64 column's quantiles must not round through float32."""
    return float(np.quantile(np.asarray(values), q))


class Row(dict):
    """A materialized row: dict with attribute access (Spark Row analogue)."""

    def __getattr__(self, item):
        try:
            return self[item]
        except KeyError as e:  # pragma: no cover
            raise AttributeError(item) from e


class DataFrame:
    """Immutable columnar table. All mutating verbs return a new DataFrame."""

    def __init__(self, data: Mapping[str, Any] | None = None,
                 num_partitions: int = 1):
        data = dict(data or {})
        n: int | None = None
        for v in data.values():
            if isinstance(v, (np.ndarray, list, tuple)):
                n = len(v)
                break
        self._data: dict[str, np.ndarray] = {
            k: _normalize_column(v, n) for k, v in data.items()
        }
        if self._data:
            lengths = {k: v.shape[0] for k, v in self._data.items()}
            if len(set(lengths.values())) > 1:
                raise ValueError(f"ragged column lengths: {lengths}")
        self.num_partitions = max(1, int(num_partitions))

    # ------------------------------------------------------------------ basics
    @property
    def columns(self) -> list[str]:
        return list(self._data.keys())

    @property
    def num_rows(self) -> int:
        if not self._data:
            return 0
        return next(iter(self._data.values())).shape[0]

    def count(self) -> int:
        return self.num_rows

    def __len__(self) -> int:
        return self.num_rows

    def __contains__(self, col: str) -> bool:
        return col in self._data

    def __getitem__(self, col: str) -> np.ndarray:
        if col not in self._data:
            raise KeyError(f"column {col!r} not in {self.columns}")
        return self._data[col]

    def column(self, col: str) -> np.ndarray:
        return self[col]

    def jnp(self, col: str, dtype=None):
        """Column as a jax.numpy array (device transfer happens lazily)."""
        import jax.numpy as jnp
        arr = self[col]
        if arr.dtype == object:
            arr = np.stack([np.asarray(v) for v in arr])
        return jnp.asarray(arr, dtype=dtype)

    @property
    def schema(self) -> dict[str, tuple]:
        """{name: (dtype, trailing_shape)} — trailing shape () for scalars."""
        return {k: (v.dtype, v.shape[1:]) for k, v in self._data.items()}

    def dtypes(self) -> dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._data.items()}

    # ------------------------------------------------------------- projection
    def select(self, *cols: str) -> "DataFrame":
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        missing = [c for c in cols if c not in self._data]
        if missing:
            raise KeyError(f"columns {missing} not in {self.columns}")
        return self._with_data({c: self._data[c] for c in cols})

    def drop(self, *cols: str) -> "DataFrame":
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        return self._with_data(
            {k: v for k, v in self._data.items() if k not in set(cols)})

    def with_column(self, name: str, values: Any) -> "DataFrame":
        if callable(values) and not isinstance(values, np.ndarray):
            values = values(self)
        replacing = name in self._data
        data = dict(self._data)
        data[name] = _normalize_column(
            values, self.num_rows if self._data else None)
        out = self._with_data(data)
        if replacing:
            # replaced values invalidate the column's metadata (e.g.
            # slot_names describing a rebuilt features matrix)
            from .bindings import ColumnMetadata
            ColumnMetadata.invalidate(out, name)
        return out

    withColumn = with_column

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        data = {}
        for k, v in self._data.items():
            data[new if k == old else k] = v
        return self._with_data(data)

    withColumnRenamed = with_column_renamed

    # -------------------------------------------------------------- selection
    def filter(self, cond: Any) -> "DataFrame":
        if callable(cond):
            cond = cond(self)
        mask = np.asarray(cond, dtype=bool)
        return self._with_data({k: v[mask] for k, v in self._data.items()})

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return self._with_data({k: v[:n] for k, v in self._data.items()})

    def head(self, n: int = 5) -> list[Row]:
        return self.limit(n).collect()

    def take(self, indices) -> "DataFrame":
        idx = np.asarray(indices)
        if idx.dtype.kind not in "iub":
            # an empty Python list arrives float64; row indices are
            # integral by contract either way
            idx = idx.astype(np.int64)
        return self._with_data({k: v[idx] for k, v in self._data.items()})

    def sample(self, fraction: float, seed: int = 0,
               with_replacement: bool = False) -> "DataFrame":
        rng = np.random.default_rng(seed)
        n = self.num_rows
        if with_replacement:
            idx = rng.integers(0, n, size=int(round(n * fraction)))
        else:
            idx = np.flatnonzero(rng.random(n) < fraction)
        return self.take(idx)

    def distinct(self) -> "DataFrame":
        import pandas as pd
        keys = {}
        for k, v in self._data.items():
            if v.ndim > 1:
                keys[k] = [v[i].tobytes() for i in range(v.shape[0])]
            elif v.dtype == object:
                keys[k] = [x.tobytes() if isinstance(x, np.ndarray) else x
                           for x in v]
            else:
                keys[k] = v
        idx = pd.DataFrame(keys).drop_duplicates().index.to_numpy()
        return self.take(idx)

    def sort(self, *cols: str, ascending: bool = True) -> "DataFrame":
        if not cols:
            return self
        keys = [self._sort_key(self._data[c]) for c in reversed(cols)]
        order = np.lexsort(keys)
        if not ascending:
            order = order[::-1]
        return self.take(order)

    orderBy = sort

    @staticmethod
    def _sort_key(arr: np.ndarray) -> np.ndarray:
        if arr.dtype == object:
            try:
                # Numeric-valued object column (e.g. None-padded from_rows):
                # sort numerically, Nones last.
                return np.asarray(
                    [np.inf if x is None else float(x) for x in arr])
            except (TypeError, ValueError):
                return np.asarray([str(x) for x in arr])
        return arr

    def random_split(self, weights: Sequence[float],
                     seed: int = 0) -> list["DataFrame"]:
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        n = self.num_rows
        assignment = rng.choice(len(w), size=n, p=w)
        return [self.take(np.flatnonzero(assignment == i))
                for i in range(len(w))]

    randomSplit = random_split

    # ------------------------------------------------------------ combination
    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError(
                f"union schema mismatch: {self.columns} vs {other.columns}")
        data = {}
        for k in self.columns:
            a, b = self._data[k], other._data[k]
            if a.dtype == object or b.dtype == object:
                out = np.empty(len(a) + len(b), dtype=object)
                # Per-row assignment so a 2-D numeric side becomes row cells.
                out[:len(a)] = [a[i] for i in range(len(a))] \
                    if a.ndim > 1 else a
                out[len(a):] = [b[i] for i in range(len(b))] \
                    if b.ndim > 1 else b
                data[k] = out
            else:
                data[k] = np.concatenate([a, b])
        return self._with_data(data)

    @staticmethod
    def concat(dfs: Iterable["DataFrame"]) -> "DataFrame":
        dfs = list(dfs)
        if not dfs:
            return DataFrame()
        out = dfs[0]
        for d in dfs[1:]:
            out = out.union(d)
        return out

    def join(self, other: "DataFrame", on: str | Sequence[str],
             how: str = "inner") -> "DataFrame":
        left = self.to_pandas()
        right = other.to_pandas()
        merged = left.merge(right, on=on, how=how)
        return DataFrame.from_pandas(merged, num_partitions=self.num_partitions)

    def group_by(self, *cols: str):
        return GroupedData(self, list(cols))

    groupBy = group_by

    # ----------------------------------------------------------- partitioning
    def repartition(self, n: int) -> "DataFrame":
        out = self._with_data(dict(self._data))
        out.num_partitions = max(1, int(n))
        return out

    def coalesce(self, n: int) -> "DataFrame":
        return self.repartition(min(self.num_partitions, n))

    def partition_bounds(self) -> list[tuple[int, int]]:
        """Row ranges of each logical partition (contiguous block layout)."""
        n, p = self.num_rows, self.num_partitions
        sizes = [n // p + (1 if i < n % p else 0) for i in range(p)]
        bounds, start = [], 0
        for s in sizes:
            bounds.append((start, start + s))
            start += s
        return bounds

    def partitions(self) -> list["DataFrame"]:
        return [self.take(np.arange(a, b)) for a, b in self.partition_bounds()]

    def map_partitions(self, fn: Callable[["DataFrame"], "DataFrame"]) -> "DataFrame":
        parts = [fn(p) for p in self.partitions()]
        out = DataFrame.concat(
            [p for p in parts if p is not None and p.columns])
        out.num_partitions = self.num_partitions
        return out

    def cache(self) -> "DataFrame":
        return self  # data is already materialized host-side

    # ------------------------------------------------------------------- I/O
    def collect(self) -> list[Row]:
        cols = self.columns
        out = []
        for i in range(self.num_rows):
            out.append(Row({c: self._item(self._data[c], i) for c in cols}))
        return out

    @staticmethod
    def _item(arr: np.ndarray, i: int):
        v = arr[i]
        if isinstance(v, np.generic):
            return v.item()
        return v

    def to_pandas(self):
        import pandas as pd
        data = {}
        for k, v in self._data.items():
            if v.ndim > 1:
                col = np.empty(v.shape[0], dtype=object)
                col[:] = [v[i] for i in range(v.shape[0])]
                data[k] = col
            else:
                data[k] = v
        return pd.DataFrame(data)

    toPandas = to_pandas

    @staticmethod
    def from_pandas(pdf, num_partitions: int = 1) -> "DataFrame":
        data = {}
        for c in pdf.columns:
            col = pdf[c].to_numpy()
            if col.dtype == object and len(col) and isinstance(col[0], np.ndarray):
                try:
                    col = np.stack(col)
                except ValueError:
                    pass
            data[str(c)] = col
        return DataFrame(data, num_partitions=num_partitions)

    def to_arrow(self):
        """DataFrame → Arrow Table (zero-copy numeric columns, vector
        columns as FixedSizeList, categorical metadata in field
        metadata). See :mod:`mmlspark_tpu.core.arrow`."""
        from .arrow import columns_to_table
        return columns_to_table(self)

    toArrow = to_arrow

    @staticmethod
    def from_arrow(table, num_partitions: int = 1) -> "DataFrame":
        """Arrow Table / RecordBatch → DataFrame (zero-copy numeric
        columns, dictionary arrays → categorical metadata)."""
        from .arrow import from_arrow
        return from_arrow(table, num_partitions=num_partitions)

    @staticmethod
    def from_arrow_batches(batches, num_partitions: int = 1) -> "DataFrame":
        """Streaming columnar ingestion from an iterable of Arrow
        RecordBatches (or a RecordBatchReader) — numeric data never
        passes through Python objects."""
        from .arrow import from_arrow_batches
        return from_arrow_batches(batches, num_partitions=num_partitions)

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]],
                  num_partitions: int = 1) -> "DataFrame":
        if not rows:
            return DataFrame()
        cols: list[str] = []
        for r in rows:
            for k in r.keys():
                if k not in cols:
                    cols.append(k)
        return DataFrame({c: [r.get(c) for r in rows] for c in cols},
                         num_partitions=num_partitions)

    def _with_data(self, data: dict[str, np.ndarray]) -> "DataFrame":
        out = DataFrame.__new__(DataFrame)
        out._data = data
        out.num_partitions = self.num_partitions
        # column metadata rides along for columns that survive the
        # derivation unchanged (ColumnMetadata.carry drops metadata for
        # replaced arrays — stale metadata must not resolve)
        from .bindings import ColumnMetadata
        ColumnMetadata.carry(self, out)
        return out

    # ------------------------------------------------------------------ repr
    def __repr__(self) -> str:
        return (f"DataFrame[{self.num_rows} rows x {len(self.columns)} cols; "
                f"{self.num_partitions} partitions]"
                + "".join(f"\n  {k}: {v.dtype}{list(v.shape[1:]) or ''}"
                          for k, v in self._data.items()))

    def show(self, n: int = 20) -> None:
        print(self.limit(n).to_pandas().to_string())


class GroupedData:
    """Minimal group-by support (host-side, pandas-backed)."""

    def __init__(self, df: DataFrame, cols: list[str]):
        self._df = df
        self._cols = cols

    def agg(self, **aggs: tuple[str, str] | str) -> DataFrame:
        """agg(out_col=("in_col", "sum"), n=("*", "count"))"""
        pdf = self._df.to_pandas()
        g = pdf.groupby(self._cols, sort=False)
        out = {}
        for name, spec in aggs.items():
            col, how = spec if isinstance(spec, tuple) else (spec, "sum")
            if how == "count":
                out[name] = g.size()
            else:
                out[name] = getattr(g[col], how)()
        import pandas as pd
        res = pd.DataFrame(out).reset_index()
        return DataFrame.from_pandas(res, num_partitions=self._df.num_partitions)

    def count(self) -> DataFrame:
        return self.agg(count=("*", "count"))
