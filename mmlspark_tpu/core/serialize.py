"""Stage save/load — complex-params-aware persistence.

Role of the reference's ``ComplexParamsWritable``/``ComplexParamsReadable`` +
``org/apache/spark/ml/Serializer.scala:1-147``: stage metadata (class, uid,
simple params) goes to ``metadata.json``; complex params (models, stage lists,
arrays, functions) each persist to their own subdirectory via the param's own
codec. Classes self-register on definition so ``load_stage`` can resolve them.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any

_STAGE_REGISTRY: dict[str, type] = {}


def register_stage(cls: type) -> None:
    _STAGE_REGISTRY[cls.__name__] = cls
    _STAGE_REGISTRY[f"{cls.__module__}.{cls.__name__}"] = cls


def resolve_stage_class(qualified: str) -> type:
    if qualified in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[qualified]
    module, _, name = qualified.rpartition(".")
    if module:
        importlib.import_module(module)
        if qualified in _STAGE_REGISTRY:
            return _STAGE_REGISTRY[qualified]
    raise KeyError(f"unknown stage class {qualified!r}")


class SaveLoadMixin:
    """save/load for Params subclasses."""

    def save(self, path: str, overwrite: bool = True) -> None:
        if os.path.exists(path) and not overwrite:
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        simple, complex_names = {}, []
        for p in type(self).params():
            if p.name not in self._paramMap:
                continue
            value = self._paramMap[p.name]
            if p.complex:
                p.save_value(value, os.path.join(path, "params", p.name))
                complex_names.append(p.name)
            else:
                simple[p.name] = p.encode(value)
        meta = {
            "class": f"{type(self).__module__}.{type(self).__name__}",
            "uid": self.uid,
            "paramMap": simple,
            "complexParams": complex_names,
            # instance-level default overrides (set by stage __init__ or
            # _setDefault) must survive load, which bypasses __init__
            "defaultOverrides": {
                k: type(self).get_param(k).encode(v)
                for k, v in self._defaultOverrides.items()},
            "library": "mmlspark_tpu",
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=1)
        self._save_extra(path)

    def _save_extra(self, path: str) -> None:
        """Hook for stages with non-param state."""

    def _load_extra(self, path: str) -> None:
        pass

    @classmethod
    def load(cls, path: str):
        stage = load_stage(path)
        if not isinstance(stage, cls):
            raise TypeError(f"loaded {type(stage).__name__}, expected "
                            f"{cls.__name__}")
        return stage

    write = save  # familiar aliases
    read = load


def load_stage(path: str) -> Any:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = resolve_stage_class(meta["class"])
    stage = cls.__new__(cls)
    # Re-run Params init without subclass __init__ side effects.
    from .param import Params
    Params.__init__(stage)
    stage.uid = meta["uid"]
    for name, payload in meta["paramMap"].items():
        if stage.has_param(name):
            p = stage.get_param(name)
            stage._paramMap[name] = p.decode(payload)
    for name, payload in meta.get("defaultOverrides", {}).items():
        if stage.has_param(name):
            stage._defaultOverrides[name] = \
                stage.get_param(name).decode(payload)
    for name in meta["complexParams"]:
        p = stage.get_param(name)
        stage._paramMap[name] = p.load_value(
            os.path.join(path, "params", name))
    stage._load_extra(path)
    return stage
