"""Estimator/Transformer/Pipeline — the SparkML-shaped public API surface.

The reference is an ecosystem of SparkML pipeline stages; every component is an
``Estimator`` (``fit(df) -> Model``) or ``Transformer`` (``transform(df) ->
df``) composed into ``Pipeline``s (see SURVEY §1). We keep that exact surface
— it's the contract ~120 stages and the binding generator rely on — while the
execution underneath is columnar batches → jitted XLA programs.
"""

from __future__ import annotations

from typing import Sequence

from .dataframe import DataFrame
from .param import Params, Param, StageListParam, StageParam
from .logging import BasicLogging
from .serialize import SaveLoadMixin, register_stage
from ..obs.profile import pipeline_profiler as _pipeline_profiler


class PipelineStage(Params, BasicLogging, SaveLoadMixin):
    """Common base of all stages."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        register_stage(cls)

    def __init__(self, **kwargs):
        Params.__init__(self, **kwargs)
        self.log_class()


class Transformer(PipelineStage):
    # ------------------------------------------- traceable-stage protocol
    # A stage that can lower into a fused XLA computation exposes
    # ``_trace(columns) -> columns``: a PURE jax.numpy function over a
    # dict of column arrays (numeric columns only — strings and ragged
    # cells never enter a traced segment). The contract:
    #
    # - no host ops: no numpy calls, no I/O, no clock, no Python-level
    #   data-dependent control flow (graftcheck's trace-safety pass and
    #   the traceability report police this statically);
    # - static shapes: output shapes must be a function of input shapes
    #   and stage params, never of the VALUES flowing through (a stage
    #   whose output length depends on the data — Explode, FlattenBatch
    #   over ragged cells — stays host-bound);
    # - ``_trace_ok(schema, n_rows)`` is the static-shape contract
    #   check: given ``{col: (dtype, trailing_shape)}`` and the row
    #   count, the stage says whether THIS configuration can trace
    #   (e.g. DataConversion to "string" cannot; VectorAssembler with
    #   handleInvalid="skip" cannot — its output length is data-
    #   dependent).
    #
    # Default = ``_trace`` absent → host-bound: the pipeline compiler
    # (core/compile.py) runs the stage eagerly and splits the fused
    # segment around it.
    _trace = None

    #: set True by stages whose _trace changes the row count (mini-
    #: batchers, FlattenBatch): they can only fuse when EVERY column is
    #: in the traced dict — a host-carried column could not be re-
    #: attached to a different-length frame.
    _trace_changes_rows = False

    def supports_trace(self, schema: dict, n_rows: int | None = None
                       ) -> bool:
        """Can this stage instance lower into a fused segment for a
        frame with this ``schema`` (``DataFrame.schema``)?"""
        if getattr(type(self), "_trace", None) is None:
            return False
        try:
            return bool(self._trace_ok(schema, n_rows))
        except Exception:
            return False

    def _trace_ok(self, schema: dict, n_rows: int | None) -> bool:
        """Per-stage static-shape contract; override to veto configs."""
        return True

    def _post_host(self, df: DataFrame) -> DataFrame:
        """Host-side metadata hook applied after a fused segment that
        contained this stage (partition counts, column metadata —
        things that live on the DataFrame, not in the arrays)."""
        return df

    def transform(self, df: DataFrame) -> DataFrame:
        with self.log_call("transform"):
            return self._transform(df)

    def _transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: DataFrame) -> "Model":
        with self.log_call("fit"):
            model = self._fit(df)
        model._resolve_parent(self)
        return model

    def _fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted transformer produced by an Estimator."""

    parent: Estimator | None = None

    def _resolve_parent(self, parent: Estimator) -> None:
        self.parent = parent


class Pipeline(Estimator):
    """Sequential composition of stages (SparkML ``Pipeline`` analogue)."""

    stages = StageListParam("stages", "pipeline stages", default=[],
                            has_default=True)

    def _fit(self, df: DataFrame) -> "PipelineModel":
        fitted = []
        cur = df
        stages = self.getOrDefault("stages")
        last_estimator = max(
            (i for i, s in enumerate(stages) if isinstance(s, Estimator)),
            default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                model = stage
            else:
                raise TypeError(f"stage {stage!r} is not a pipeline stage")
            # Transforms past the last estimator feed nothing during fit.
            if i < last_estimator:
                cur = model.transform(cur)
        return PipelineModel().setStages(fitted)


class PipelineModel(Model):
    """Fitted pipeline: a chain of transformers.

    Constructible directly from transformers — the role of the reference's
    ``NamespaceInjections.pipelineModel`` (which needed private-API access in
    Spark; here it's just a constructor).
    """

    stages = StageListParam("stages", "fitted stages", default=[],
                            has_default=True)

    def __init__(self, stages: Sequence[Transformer] | None = None, **kwargs):
        super().__init__(**kwargs)
        if stages is not None:
            self.setStages(list(stages))

    def _transform(self, df: DataFrame) -> DataFrame:
        cur = df
        prof = _pipeline_profiler()
        if prof is None:
            for stage in self.getOrDefault("stages"):
                cur = stage.transform(cur)
            return cur
        # per-stage host-dispatch vs device-execute attribution (obs
        # StepProfiler, opt-in: enable_pipeline_profiling() or
        # MMLSPARK_TPU_PROFILE_PIPELINE=1). The handle's done() sync is
        # the measurement — it serializes the async dispatch pipeline,
        # which is exactly why the default path stays untouched.
        for stage in self.getOrDefault("stages"):
            with prof.step(type(stage).__name__) as h:
                cur = h.done(stage.transform(cur))
        return cur

    def compile(self, example_df: DataFrame, *, mesh=None, rules=None,
                donate: bool = True, service: str = "pipeline"):
        """Lower this pipeline into a :class:`~.compile.CompiledPipeline`:
        maximal runs of traceable stages fuse into single jitted (or,
        with ``mesh``+``rules``, pjit'd) XLA computations with donated
        inter-stage buffers; host-bound stages run eagerly between
        segments. ``example_df`` drives schema propagation — segment
        grouping needs each stage's OUTPUT schema, so the example is
        transformed eagerly once at compile time."""
        from .compile import compile_pipeline
        return compile_pipeline(self, example_df, mesh=mesh, rules=rules,
                                donate=donate, service=service)


# ---------------------------------------------------------------- fluent API
# Reference core/spark/FluentAPI.scala:12-30 — df.mlTransform(t1, t2),
# df.mlFit(e): chain stages without building a Pipeline.
def ml_transform(df: DataFrame, *stages: Transformer) -> DataFrame:
    # Routed through PipelineModel._transform rather than a bare loop so
    # the fluent entry point shares the pipeline profiler hook (and any
    # future fused execution) with Pipeline.fit().transform() — bench
    # numbers taken on either entry point measure the same path.
    return PipelineModel(list(stages)).transform(df)


def ml_fit(df: DataFrame, estimator: Estimator) -> Model:
    return estimator.fit(df)


DataFrame.mlTransform = lambda self, *stages: ml_transform(self, *stages)
DataFrame.mlFit = lambda self, est: ml_fit(self, est)
