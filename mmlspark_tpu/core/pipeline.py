"""Estimator/Transformer/Pipeline — the SparkML-shaped public API surface.

The reference is an ecosystem of SparkML pipeline stages; every component is an
``Estimator`` (``fit(df) -> Model``) or ``Transformer`` (``transform(df) ->
df``) composed into ``Pipeline``s (see SURVEY §1). We keep that exact surface
— it's the contract ~120 stages and the binding generator rely on — while the
execution underneath is columnar batches → jitted XLA programs.
"""

from __future__ import annotations

from typing import Sequence

from .dataframe import DataFrame
from .param import Params, Param, StageListParam, StageParam
from .logging import BasicLogging
from .serialize import SaveLoadMixin, register_stage
from ..obs.profile import pipeline_profiler as _pipeline_profiler


class PipelineStage(Params, BasicLogging, SaveLoadMixin):
    """Common base of all stages."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        register_stage(cls)

    def __init__(self, **kwargs):
        Params.__init__(self, **kwargs)
        self.log_class()


class Transformer(PipelineStage):
    def transform(self, df: DataFrame) -> DataFrame:
        with self.log_call("transform"):
            return self._transform(df)

    def _transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: DataFrame) -> "Model":
        with self.log_call("fit"):
            model = self._fit(df)
        model._resolve_parent(self)
        return model

    def _fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted transformer produced by an Estimator."""

    parent: Estimator | None = None

    def _resolve_parent(self, parent: Estimator) -> None:
        self.parent = parent


class Pipeline(Estimator):
    """Sequential composition of stages (SparkML ``Pipeline`` analogue)."""

    stages = StageListParam("stages", "pipeline stages", default=[],
                            has_default=True)

    def _fit(self, df: DataFrame) -> "PipelineModel":
        fitted = []
        cur = df
        stages = self.getOrDefault("stages")
        last_estimator = max(
            (i for i, s in enumerate(stages) if isinstance(s, Estimator)),
            default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                model = stage
            else:
                raise TypeError(f"stage {stage!r} is not a pipeline stage")
            # Transforms past the last estimator feed nothing during fit.
            if i < last_estimator:
                cur = model.transform(cur)
        return PipelineModel().setStages(fitted)


class PipelineModel(Model):
    """Fitted pipeline: a chain of transformers.

    Constructible directly from transformers — the role of the reference's
    ``NamespaceInjections.pipelineModel`` (which needed private-API access in
    Spark; here it's just a constructor).
    """

    stages = StageListParam("stages", "fitted stages", default=[],
                            has_default=True)

    def __init__(self, stages: Sequence[Transformer] | None = None, **kwargs):
        super().__init__(**kwargs)
        if stages is not None:
            self.setStages(list(stages))

    def _transform(self, df: DataFrame) -> DataFrame:
        cur = df
        prof = _pipeline_profiler()
        if prof is None:
            for stage in self.getOrDefault("stages"):
                cur = stage.transform(cur)
            return cur
        # per-stage host-dispatch vs device-execute attribution (obs
        # StepProfiler, opt-in: enable_pipeline_profiling() or
        # MMLSPARK_TPU_PROFILE_PIPELINE=1). The handle's done() sync is
        # the measurement — it serializes the async dispatch pipeline,
        # which is exactly why the default path stays untouched.
        for stage in self.getOrDefault("stages"):
            with prof.step(type(stage).__name__) as h:
                cur = h.done(stage.transform(cur))
        return cur


# ---------------------------------------------------------------- fluent API
# Reference core/spark/FluentAPI.scala:12-30 — df.mlTransform(t1, t2),
# df.mlFit(e): chain stages without building a Pipeline.
def ml_transform(df: DataFrame, *stages: Transformer) -> DataFrame:
    cur = df
    for s in stages:
        cur = s.transform(cur)
    return cur


def ml_fit(df: DataFrame, estimator: Estimator) -> Model:
    return estimator.fit(df)


DataFrame.mlTransform = lambda self, *stages: ml_transform(self, *stages)
DataFrame.mlFit = lambda self, est: ml_fit(self, est)
