"""Shared column-name param mixins.

Reference ``core/contracts/Params.scala`` (248 LoC): ``HasInputCol``,
``HasOutputCol``, ``HasLabelCol``, ``HasFeaturesCol``, ``HasWeightCol``,
``HasGroupCol`` — mixed into nearly every stage so column wiring is uniform.
"""

from __future__ import annotations

from .param import Param, TypeConverters as TC


class HasInputCol:
    inputCol = Param("inputCol", "name of the input column", TC.toString)


class HasInputCols:
    inputCols = Param("inputCols", "names of the input columns", TC.toListString)


class HasOutputCol:
    outputCol = Param("outputCol", "name of the output column", TC.toString)


class HasOutputCols:
    outputCols = Param("outputCols", "names of the output columns",
                       TC.toListString)


class HasLabelCol:
    labelCol = Param("labelCol", "name of the label column", TC.toString,
                     default="label")


class HasFeaturesCol:
    featuresCol = Param("featuresCol", "name of the features column",
                        TC.toString, default="features")


class HasWeightCol:
    weightCol = Param("weightCol", "name of the instance-weight column",
                      TC.toString)


class HasInitScoreCol:
    initScoreCol = Param("initScoreCol",
                         "column with initial scores (warm start / boosting "
                         "continuation)", TC.toString)


class HasGroupCol:
    groupCol = Param("groupCol", "name of the query-group column (ranking)",
                     TC.toString)


class HasValidationIndicatorCol:
    validationIndicatorCol = Param(
        "validationIndicatorCol",
        "boolean column marking rows held out for early-stopping validation",
        TC.toString)


class HasPredictionCol:
    predictionCol = Param("predictionCol", "name of the prediction column",
                          TC.toString, default="prediction")


class HasRawPredictionCol:
    rawPredictionCol = Param("rawPredictionCol",
                             "raw (margin) prediction column", TC.toString,
                             default="rawPrediction")


class HasProbabilityCol:
    probabilityCol = Param("probabilityCol",
                           "class-probability prediction column", TC.toString,
                           default="probability")


class HasSeed:
    seed = Param("seed", "random seed", TC.toInt, default=0, has_default=True)
