"""Arrow interchange — the columnar bridge to Spark/pandas/any producer.

Reference role: ``core/schema/SparkBindings.scala:13-39`` is the
reference's typed interchange layer between JVM rows and ML code; the
TPU-native equivalent speaks Apache Arrow, the lingua franca every
columnar producer (Spark, pandas, DuckDB, Parquet readers) already emits.
SURVEY §7.1 row 1: "columnar batches (Arrow) → fixed-shape jnp arrays".

Mapping (both directions):
- numeric/bool scalar columns        ↔ primitive arrays, ZERO-COPY when
  single-chunk and null-free (the hot path for feature matrices);
- fixed-width vector columns [n, w]  ↔ ``FixedSizeList`` arrays
  (zero-copy through the flat values buffer);
- strings/bytes/ragged lists         ↔ ``string``/``binary``/``list``
  (materialized — these are host-side metadata columns, never the MXU
  path);
- categorical columns                ↔ ``dictionary`` arrays: the indices
  become the column, the dictionary becomes
  :class:`~mmlspark_tpu.core.bindings.ColumnMetadata` categorical levels
  (the exact shape ``ValueIndexer`` produces, so GBDT categorical-slot
  threading keeps working across the interchange);
- nulls in numeric columns           → ``NaN`` (the engines' missing
  marker; integer-with-null promotes to float64).
"""

from __future__ import annotations

import json

import numpy as np

_LEVELS_KEY = b"mmlspark_tpu.categorical_levels"


def _require_pyarrow():
    try:
        import pyarrow as pa
        return pa
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "pyarrow is required for Arrow interchange "
            "(DataFrame.from_arrow/to_arrow)") from e


def _array_to_numpy(pa_mod, arr, field):
    """One Arrow array (single chunk) → (numpy column, metadata|None)."""
    pa = pa_mod
    t = arr.type
    if pa.types.is_dictionary(t):
        levels = arr.dictionary.to_pylist()
        idx = arr.indices
        if idx.null_count:
            out = idx.cast(pa.float32()).to_numpy(zero_copy_only=False)
        else:
            out = idx.to_numpy(zero_copy_only=False).astype(np.float32)
        return out, {"categorical": True, "levels": levels}
    if pa.types.is_fixed_size_list(t):
        w = t.list_size
        # .values ignores the slice window (returns the full child
        # array), so apply arr.offset ourselves — record batches from
        # to_batches()/streams are slices of one parent buffer
        values = arr.values
        if values.null_count or arr.null_count:
            raise ValueError(
                f"fixed-size-list column {field.name!r} has nulls; "
                "vector columns must be dense")
        flat = values.to_numpy(
            zero_copy_only=_is_primitive(pa, values.type))
        start = arr.offset * w
        return flat[start:start + len(arr) * w].reshape(len(arr), w), None
    if pa.types.is_boolean(t):
        if arr.null_count:
            # bool-with-null would otherwise land as an object column of
            # True/None/False, breaking the nulls→NaN contract
            return (arr.cast(pa.float64())
                    .to_numpy(zero_copy_only=False)), None
        return arr.to_numpy(zero_copy_only=False), None
    if _is_primitive(pa, t):
        if arr.null_count:
            # NaN is the engines' missing marker. Floats keep their own
            # dtype (no needless float64 promotion on the feature-matrix
            # path); only integers must widen to hold NaN.
            if pa.types.is_floating(t):
                return arr.to_numpy(zero_copy_only=False), None
            return (arr.cast(pa.float64())
                    .to_numpy(zero_copy_only=False)), None
        return arr.to_numpy(zero_copy_only=True), None
    # strings / binary / ragged lists / structs → host-side object column
    out = np.empty(len(arr), object)
    out[:] = [np.asarray(v) if isinstance(v, list) else v
              for v in arr.to_pylist()]
    return out, None


def _is_primitive(pa, t) -> bool:
    return (pa.types.is_integer(t) or pa.types.is_floating(t))


def table_to_columns(table):
    """Arrow Table/RecordBatch → ({name: np column}, {name: metadata})."""
    pa = _require_pyarrow()
    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    cols: dict[str, np.ndarray] = {}
    metas: dict[str, dict] = {}
    for i, field in enumerate(table.schema):
        chunked = table.column(i)
        if chunked.num_chunks == 1:
            arr = chunked.chunk(0)
        elif chunked.num_chunks == 0:
            arr = pa.array([], type=field.type)
        else:
            arr = chunked.combine_chunks()
            if isinstance(arr, pa.ChunkedArray):  # pyarrow version drift
                arr = arr.chunk(0)
        col, meta = _array_to_numpy(pa, arr, field)
        cols[field.name] = col
        if meta is None and field.metadata and \
                _LEVELS_KEY in field.metadata:
            meta = {"categorical": True,
                    "levels": json.loads(field.metadata[_LEVELS_KEY])}
        if meta:
            metas[field.name] = meta
    return cols, metas


def columns_to_table(df):
    """DataFrame → Arrow Table (numeric columns zero-copy; categorical
    metadata encoded in field metadata so it survives a round trip)."""
    pa = _require_pyarrow()
    from .bindings import ColumnMetadata

    arrays, fields = [], []
    for name in df.columns:
        col = df[name]
        meta = ColumnMetadata.get(df, name)
        field_meta = None
        if meta and meta.get("categorical"):
            field_meta = {_LEVELS_KEY:
                          json.dumps(list(meta["levels"])).encode()}
        if col.ndim == 2:
            w = col.shape[1]
            flat = np.ascontiguousarray(col).reshape(-1)
            arr = pa.FixedSizeListArray.from_arrays(pa.array(flat), w)
        elif col.dtype == object:
            vals = list(col)
            if vals and isinstance(vals[0], np.ndarray):
                arr = pa.array([None if v is None else list(np.asarray(v))
                                for v in vals])
            else:
                arr = pa.array(vals)
        else:
            arr = pa.array(col)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type, metadata=field_meta))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def from_arrow(table, num_partitions: int = 1):
    """Arrow Table / RecordBatch → DataFrame (with categorical
    metadata)."""
    from .bindings import ColumnMetadata
    from .dataframe import DataFrame
    cols, metas = table_to_columns(table)
    df = DataFrame(cols, num_partitions=num_partitions)
    for name, meta in metas.items():
        ColumnMetadata.attach(df, name, meta)
    return df


def from_arrow_batches(batches, num_partitions: int = 1):
    """Streaming ingestion: an iterable of RecordBatches (or a
    RecordBatchReader) → one DataFrame via a single unified Arrow table
    — numeric data never materializes as Python objects, and
    dictionary-encoded columns whose dictionaries legally change
    mid-stream are unified (per-batch decoding against the last
    dictionary would silently mislabel categories)."""
    pa = _require_pyarrow()
    from .dataframe import DataFrame
    schema = getattr(batches, "schema", None)  # RecordBatchReader
    batch_list = list(batches)
    if not batch_list and schema is None:
        return DataFrame()
    try:
        # a known schema keeps zero-row streams schema-correct: the
        # columns come through empty but named and typed
        table = pa.Table.from_batches(batch_list, schema=schema)
    except pa.lib.ArrowInvalid as e:
        raise ValueError(f"batch schema drift: {e}") from e
    return from_arrow(table, num_partitions=num_partitions)
