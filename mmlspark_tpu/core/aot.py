"""Ahead-of-time executable store: compilation as a build step.

Why: every (route, padding-bucket, mesh) combination pays its XLA
compile the first time traffic hits it, so an autoscaler scale-up is a
compile storm on the fresh worker — first-request latency is seconds
while steady-state p99 is ~0.8 ms (BENCH_r05). Per the full-program
compilation thesis (arXiv:1810.09868) and fingerprint-keyed caching
(arXiv:2008.01040), the fix is to move compilation to build time:
``core/compile.py``'s :class:`~.compile.FusedSegment` is already the
unit of compilation — this module lowers, compiles, serializes, and
reloads it instead of re-tracing per process.

The store is a content-addressed directory tree::

    <root>/<ff[:2]>/<ff>/        ff = full fingerprint (sha256 hex)
        meta.json                key components, specs, tier, checksum
        exe.bin                  serialized executable (tier "serialized")
        hlo.txt                  StableHLO text (debug + retrace tier)

Two fingerprints per entry:

- **static fingerprint** — stage classes + params (fitted state lives
  in params), donation split, host-column contract, mesh descriptor,
  backend platform, jax/jaxlib versions. Everything that decides WHAT
  program a segment lowers to, minus the input shapes.
- **full fingerprint** — static + the column spec (names, dtypes,
  shapes): one entry per padding bucket.

A param change moves the static fingerprint, so stale entries can never
be served (they simply stop matching); :meth:`AotStore.gc` reclaims
them. A corrupt or undeserializable entry is a LOUD miss
(``aot_store_miss_total{reason=...}`` + warning) followed by
compile-and-backfill — never a wrong answer (mirrors
``resilience_checkpoint_skipped_total`` semantics).

Fingerprint computation and store bookkeeping are JAX-free (the CI
smoke asserts it): versions come from ``importlib.metadata``, hashes
from hashlib. Only executable (de)serialization and the build CLI
touch a backend, through :mod:`mmlspark_tpu.parallel.compat`'s
serialize/deserialize split.

Build CLI (see ``docs/aot.md``)::

    python -m mmlspark_tpu.core.aot build --import myapp.serving \\
        --root /var/mmlspark_tpu/aot
    python -m mmlspark_tpu.core.aot list|gc|selftest|verify ...

Warm loading: ``serving/dsl.ServingStream.start`` and
``serving/distributed.remote_worker_loop`` call :func:`maybe_warm`, so
an autoscaler-added worker boots with every registered segment × bucket
already executable — its first request is as fast as its thousandth.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import tempfile
import threading

import numpy as np

_LOG = logging.getLogger("mmlspark_tpu.core.aot")

#: default on-disk root (override with MMLSPARK_TPU_AOT_STORE).
#: Per-user: executables deserialize through pickle, so a shared /tmp
#: path would let any local user plant code another user's server
#: boot would execute (maybe_warm additionally refuses roots this uid
#: does not own).
DEFAULT_STORE_ROOT = "/tmp/mmlspark_tpu_aot_store-" + str(
    getattr(os, "getuid", lambda: "u")())
_META = "meta.json"
_EXE = "exe.bin"
_HLO = "hlo.txt"
STORE_VERSION = 1


def store_root() -> str:
    """The configured store root: ``MMLSPARK_TPU_AOT_STORE`` or the
    default. Shared config point with ``core.utils.scrubbed_cpu_env``'s
    JAX persistent-cache placement."""
    return os.environ.get("MMLSPARK_TPU_AOT_STORE") or DEFAULT_STORE_ROOT


def jax_cache_dir() -> str:
    """Where the JAX persistent compilation cache should live: an
    explicit ``JAX_COMPILATION_CACHE_DIR`` wins; with a configured AOT
    store root the two caches co-locate under it; else the historical
    default. ``core.utils.scrubbed_cpu_env`` honors this instead of
    clobbering (ISSUE 11 satellite)."""
    explicit = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if explicit:
        return explicit
    if os.environ.get("MMLSPARK_TPU_AOT_STORE"):
        return os.path.join(store_root(), "jax_cache")
    return "/tmp/mmlspark_tpu_jax_cache"


# ---------------------------------------------------------------- metrics
def _reg():
    from ..obs.metrics import registry
    return registry


def _metrics():
    reg = _reg()
    return {
        "hit": reg.counter(
            "aot_store_hit_total",
            "segment executables served from the AOT store, by "
            "segment/tier (serialized | retrace)"),
        "miss": reg.counter(
            "aot_store_miss_total",
            "AOT store lookups that fell through to a runtime compile, "
            "by segment/reason (absent | corrupt | deserialize | "
            "unfingerprintable | error)"),
        "backfill": reg.counter(
            "aot_store_backfill_total",
            "runtime-compiled executables written back into the store"),
        "build": reg.histogram(
            "aot_build_seconds",
            "lower+compile wall seconds per store build, by segment"),
        "entries": reg.gauge(
            "aot_store_entries", "executables resident in the store"),
        "gc_kept": reg.counter(
            "aot_gc_kept_versions",
            "gc-stale entries spared because a deploy-registry "
            "version still needs them (deploy state or keep-last-N)"),
    }


# ------------------------------------------------------- deploy registry
def _registry_versions(root: str) -> list[dict]:
    """Version records from the deploy-plane registry persisted beside
    the store tree (``serving/deploy.py`` writes ``registry.json``
    there). Read as plain JSON — the gc/list paths must not grow a
    serving import."""
    try:
        with open(os.path.join(root, "registry.json"),
                  encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return []
    recs = payload.get("versions", [])
    return [r for r in recs if isinstance(r, dict) and r.get("name")]


#: registry states that pin a version's entries unconditionally — the
#: live deploy set (mirrors serving.deploy.DEPLOY_STATES): collecting
#: the active version or a rollback target mid-deploy would turn the
#: next flip into a compile storm
_DEPLOY_STATES = ("warming", "candidate", "active", "draining")


def _protected_static_fps(root: str,
                          keep_model_versions: int | None) -> set:
    """Static fingerprints gc must spare: every registry version in a
    deploy state, plus — with ``keep_model_versions=N`` — the last N
    versions by registration order (the operator's rollback horizon)."""
    recs = _registry_versions(root)
    keep: set = set()
    for rec in recs:
        if rec.get("state") in _DEPLOY_STATES:
            keep.update(rec.get("static_fps", []))
    if keep_model_versions:
        ordered = sorted(recs, key=lambda r: r.get("seq", 0))
        for rec in ordered[-int(keep_model_versions):]:
            keep.update(rec.get("static_fps", []))
    return keep


# ----------------------------------------------------------- fingerprints
class Unfingerprintable(ValueError):
    """A stage carries state that cannot be canonically serialized
    (e.g. a raw callable param): its segment must NEVER match a store
    entry — two different callables would otherwise share an
    executable. The segment stays on the runtime-compile path."""


def runtime_versions() -> dict:
    """jax/jaxlib versions WITHOUT importing jax (fingerprint
    computation must stay JAX-free). Absent packages fingerprint as
    "absent" — a store built with jax can never match a process without
    it."""
    import importlib.metadata as md
    out = {}
    for pkg in ("jax", "jaxlib"):
        try:
            out[pkg] = md.version(pkg)
        except md.PackageNotFoundError:
            out[pkg] = "absent"
    return out


def _canon(value):
    """Reduce a param value to a deterministic JSON-able form; raise
    :class:`Unfingerprintable` for anything without one."""
    if value is None or isinstance(value, (bool, int, str)):
        return value
    if isinstance(value, float):
        return repr(value)  # repr round-trips; str() loses precision
    if isinstance(value, np.generic):
        return _canon(value.item())
    if isinstance(value, (list, tuple)):
        return [_canon(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _canon(v) for k, v in sorted(value.items(),
                                                    key=lambda kv:
                                                    str(kv[0]))}
    if isinstance(value, np.ndarray) and value.dtype != object:
        return {"__ndarray__": [str(value.dtype), list(value.shape),
                                hashlib.sha256(
                                    np.ascontiguousarray(value)
                                    .tobytes()).hexdigest()]}
    arr = getattr(value, "__array__", None)
    if arr is not None and hasattr(value, "dtype") \
            and getattr(value.dtype, "kind", "O") != "O":
        # device arrays canonicalize through their host bytes
        return _canon(np.asarray(value))
    raise Unfingerprintable(
        f"param value of type {type(value).__name__} has no canonical "
        "form; its stage cannot be keyed into the AOT store")


def stage_fingerprint(stage) -> dict:
    """One stage's identity: class + every param value (fitted state —
    levels, fill values, idf vectors — lives in params, so a refit
    moves the fingerprint)."""
    entry = {"class": type(stage).__name__}
    params = {}
    get = getattr(stage, "get", None)
    if callable(get) and hasattr(type(stage), "params"):
        for p in type(stage).params():
            params[p.name] = _canon(get(p))
    entry["params"] = params
    return entry


def column_spec(cols: dict) -> list:
    """Ordered (name, dtype, shape) triples for a column dict — works
    on numpy and device arrays alike, no JAX import."""
    return [[c, str(np.dtype(v.dtype)), list(v.shape)]
            for c, v in sorted(cols.items())]


def arg_sig(donated: dict, dropped: dict) -> tuple:
    """Hashable in-memory key for one (donated, dropped) argument pair
    — the per-bucket executable-cache key inside a FusedSegment."""
    def one(cols):
        return tuple((c, str(np.dtype(v.dtype)), tuple(v.shape))
                     for c, v in sorted(cols.items()))
    return one(donated), one(dropped)


def sig_from_spec(donated_spec: list, dropped_spec: list) -> tuple:
    """The same key :func:`arg_sig` yields, rebuilt from a stored
    meta.json spec (warm loading has no arrays in hand)."""
    def one(spec):
        return tuple((c, dt, tuple(shape)) for c, dt, shape in spec)
    return one(donated_spec), one(dropped_spec)


def _sha(payload) -> str:
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()


def mesh_descriptor(mesh) -> list | None:
    """A mesh's fingerprint-relevant identity: axis names + shape.

    On a multi-process (pod) mesh the descriptor additionally carries
    ``[process_count, process_index]``: an executable compiled for a
    2-process (2, 4) mesh addresses only this worker's shard of the
    devices, so a pod worker must never warm-load a single-host build
    of the "same" mesh shape (nor another rank's). Single-host meshes
    keep the bare two-element form, so existing store fingerprints
    stay valid.
    """
    if mesh is None:
        return None
    devs = np.asarray(mesh.devices)
    desc = [list(getattr(mesh, "axis_names", ())), list(devs.shape)]
    procs = sorted({getattr(d, "process_index", 0) for d in devs.flat})
    if procs != [0]:
        import jax
        desc.append([len(procs), int(jax.process_index())])
    return desc


def _canon_rules(rules) -> list | None:
    """Partition rules' fingerprint form: (pattern, spec) pairs as
    deterministic strings (PartitionSpec reprs are stable). Rules
    change the compiled program's shardings, so they MUST move the
    key."""
    if not rules:
        return None
    try:
        return [[str(p), repr(s)] for p, s in rules]
    except (TypeError, ValueError) as e:
        raise Unfingerprintable(
            f"partition rules have no canonical form: {e}") from e


def segment_static_key(stages, *, no_donate=(), expected_host=(),
                       mesh=None, donate: bool = True, rules=None,
                       platform: str = "cpu",
                       versions: dict | None = None) -> dict:
    """Everything that decides WHAT program a segment lowers to, minus
    input shapes — incl. the donation flag and partition rules, which
    change buffer aliasing / shardings in the executable. Raises
    :class:`Unfingerprintable` when any stage cannot be
    canonicalized."""
    return {
        "v": STORE_VERSION,
        "stages": [stage_fingerprint(s) for s in stages],
        "no_donate": sorted(no_donate),
        "expected_host": sorted(expected_host),
        "mesh": mesh_descriptor(mesh),
        "donate": bool(donate),
        "rules": _canon_rules(rules),
        "platform": platform,
        "versions": versions if versions is not None
        else runtime_versions(),
    }


def fingerprints(static_key: dict, donated_spec: list,
                 dropped_spec: list) -> tuple[str, str]:
    """→ (static_fp, full_fp). The static fp groups every padding
    bucket of one segment program; the full fp is one executable."""
    static_fp = _sha(static_key)
    full_fp = _sha({"static": static_fp, "donated": donated_spec,
                    "dropped": dropped_spec})
    return static_fp, full_fp


def _backend_platform() -> str:
    import jax
    return jax.default_backend()


def segment_fingerprints(segment, donated: dict,
                         dropped: dict) -> tuple[str, str, dict]:
    """Fingerprints for a live :class:`~.compile.FusedSegment` and one
    argument pair (requires jax for the backend platform only)."""
    key = segment_static_key(
        segment.stages, no_donate=segment.no_donate,
        expected_host=segment.expected_host, mesh=segment.mesh,
        donate=segment.donate, rules=segment.rules,
        platform=_backend_platform())
    dspec, pspec = column_spec(donated), column_spec(dropped)
    static_fp, full_fp = fingerprints(key, dspec, pspec)
    return static_fp, full_fp, {"static_key": key, "donated": dspec,
                                "dropped": pspec}


def _zeros_from_spec(spec: list) -> dict:
    return {c: np.zeros(tuple(shape), np.dtype(dt))
            for c, dt, shape in spec}


# ------------------------------------------------------------- the store
class AotStore:
    """On-disk executable store, content-addressed by full fingerprint.

    Writes are atomic (tmp dir + ``os.replace``, the
    ``dl/checkpoint`` discipline) so a killed build never leaves a
    half-entry a loader could trust; every ``exe.bin`` carries its
    sha256 in ``meta.json`` and a mismatch is a loud ``corrupt`` miss,
    never a deserialization attempt."""

    def __init__(self, root: str | None = None):
        self.root = root or store_root()
        self._lock = threading.Lock()
        # metrics live in the process-wide registry like every other
        # subsystem's: one scrape surface per process
        self._m = _metrics()
        # entry count cache: save/invalidate adjust it incrementally
        # so the request-path backfill never walks the whole store
        # (None = not yet counted)
        self._n_entries: int | None = None

    # -- layout --------------------------------------------------------
    def entry_dir(self, full_fp: str) -> str:
        return os.path.join(self.root, full_fp[:2], full_fp)

    def entries(self) -> list[dict]:
        """Every readable meta.json in the store (unreadable entries
        are skipped — they can only ever be misses anyway)."""
        out = []
        if not os.path.isdir(self.root):
            return out
        for shard in sorted(os.listdir(self.root)):
            sdir = os.path.join(self.root, shard)
            if len(shard) != 2 or not os.path.isdir(sdir):
                continue
            for fp in sorted(os.listdir(sdir)):
                # only finished entries: full fingerprints are 64-hex
                # dir names, so in-flight .tmp-* dirs (a concurrent
                # build mid-os.replace) and any leaked junk never read
                # as corrupt entries or count in stats/gc
                if len(fp) != 64 or fp.startswith("."):
                    continue
                meta = self._read_meta(os.path.join(sdir, fp))
                if meta is not None:
                    out.append(meta)
        return out

    def entries_for(self, static_fp: str) -> list[dict]:
        return [m for m in self.entries()
                if m.get("static_fp") == static_fp]

    def _read_meta(self, edir: str) -> dict | None:
        try:
            with open(os.path.join(edir, _META), encoding="utf-8") as f:
                meta = json.load(f)
            meta["_dir"] = edir
            return meta
        except (OSError, ValueError):
            return None

    def _count_entries(self, delta: int | None = None) -> None:
        """Keep the entry gauge (and its cache) current. ``delta``
        adjusts incrementally (save/invalidate — no store walk on the
        request path); ``None`` forces a recount (gc)."""
        with self._lock:
            if delta is None or self._n_entries is None:
                self._n_entries = len(self.entries())
                if delta is not None:
                    delta = 0  # recount already includes the change
            self._n_entries = max(self._n_entries + (delta or 0), 0)
            self._m["entries"].set(self._n_entries)

    # -- write ---------------------------------------------------------
    def save(self, *, full_fp: str, static_fp: str, segment_name: str,
             meta_extra: dict, blob: bytes | None,
             hlo_text: str | None) -> None:
        """Atomically publish one entry. ``blob=None`` writes a
        retrace-tier entry (meta + HLO text only)."""
        meta = {
            "store_version": STORE_VERSION,
            "full_fp": full_fp,
            "static_fp": static_fp,
            "segment": segment_name,
            "tier": "serialized" if blob is not None else "retrace",
            "exe_sha256": hashlib.sha256(blob).hexdigest()
            if blob is not None else None,
        }
        meta.update(meta_extra)
        final = self.entry_dir(full_fp)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        tmp = tempfile.mkdtemp(dir=os.path.dirname(final),
                               prefix=".tmp-")
        try:
            with open(os.path.join(tmp, _META), "w",
                      encoding="utf-8") as f:
                json.dump(meta, f, indent=1, sort_keys=True)
            if blob is not None:
                with open(os.path.join(tmp, _EXE), "wb") as f:
                    f.write(blob)
            if hlo_text is not None:
                with open(os.path.join(tmp, _HLO), "w",
                          encoding="utf-8") as f:
                    f.write(hlo_text)
            with self._lock:
                existed = os.path.isdir(final)
                if existed:
                    shutil.rmtree(final, ignore_errors=True)
                os.replace(tmp, final)
        except Exception:
            # ANY failure (not just OSError — e.g. a meta value json
            # cannot encode) must reclaim the tmp dir, or it lingers
            # in the shard forever
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._count_entries(0 if existed else 1)

    def invalidate(self, full_fp: str) -> bool:
        final = self.entry_dir(full_fp)
        with self._lock:
            if not os.path.isdir(final):
                return False
            shutil.rmtree(final, ignore_errors=True)
        self._count_entries(-1)
        return True

    def gc(self, keep_static: set[str] | None = None,
           keep_versions: bool = True,
           keep_model_versions: int | None = None) -> list[str]:
        """Remove stale entries: anything whose static fingerprint is
        not in ``keep_static`` (when given), plus — with
        ``keep_versions`` — anything built against a different
        jax/jaxlib than this process would fingerprint (those can never
        match again; they are dead weight).

        Deploy-plane protection (``serving/deploy.py``,
        ``registry.json`` beside the tree): an entry a registry version
        in a deploy state (warming/candidate/active/draining) still
        points at is NEVER removed — whatever keep_static says — and
        ``keep_model_versions=N`` (CLI ``gc --keep-versions N``)
        additionally pins the last N registered versions, so a rollback
        target survives every gc that runs mid-deploy. Spared entries
        count in ``aot_gc_kept_versions``."""
        versions = runtime_versions()
        protected = _protected_static_fps(self.root,
                                          keep_model_versions)
        removed, kept = [], 0
        for meta in self.entries():
            stale = False
            if keep_static is not None \
                    and meta.get("static_fp") not in keep_static:
                stale = True
            if keep_versions and meta.get("versions") not in (
                    None, versions):
                stale = True
            if stale and meta.get("static_fp") in protected:
                kept += 1
                continue
            if stale:
                shutil.rmtree(meta["_dir"], ignore_errors=True)
                removed.append(meta["full_fp"])
        if kept:
            self._m["gc_kept"].inc(kept)
            _LOG.info("aot store gc: kept %d entries pinned by the "
                      "deploy registry", kept)
        if removed:
            _LOG.info("aot store gc: removed %d stale entries",
                      len(removed))
        self._count_entries()
        return removed

    # -- read ----------------------------------------------------------
    def _checked_blob(self, meta: dict) -> bytes | None:
        """exe.bin bytes iff present AND matching the recorded sha256;
        a mismatch deletes nothing (evidence) but reads as corrupt."""
        path = os.path.join(meta["_dir"], _EXE)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if hashlib.sha256(blob).hexdigest() != meta.get("exe_sha256"):
            return None
        return blob

    def load_entry(self, meta: dict, *, segment=None):
        """One stored entry → a callable executable, or None with the
        miss reason counted. ``segment`` enables the retrace tier (the
        traced body is needed to re-lower)."""
        name = meta.get("segment", "?")
        if meta.get("tier") == "serialized":
            blob = self._checked_blob(meta)
            if blob is None:
                self._m["miss"].inc(1, segment=name, reason="corrupt")
                _LOG.warning(
                    "aot store entry %s for segment %s is corrupt "
                    "(checksum mismatch or unreadable exe.bin); "
                    "falling back to runtime compile",
                    meta.get("full_fp", "?")[:12], name)
                return None
            from ..parallel import compat
            try:
                exe = compat.deserialize_compiled(blob)
            except Exception:
                self._m["miss"].inc(1, segment=name,
                                    reason="deserialize")
                _LOG.warning(
                    "aot store entry %s for segment %s failed to "
                    "deserialize (jaxlib/backend drift?); falling back "
                    "to runtime compile", meta.get("full_fp", "?")[:12],
                    name, exc_info=True)
                return None
            self._m["hit"].inc(1, segment=name, tier="serialized")
            return exe
        # retrace tier: the store records the program identity + specs;
        # compiling from the traced body at WARM time still moves the
        # cost out of request latency (the tier exists for jax builds
        # without serialize_executable)
        if segment is None:
            self._m["miss"].inc(1, segment=name, reason="deserialize")
            return None
        try:
            donated = _zeros_from_spec(meta["donated"])
            dropped = _zeros_from_spec(meta["dropped"])
            fn = segment._ensure_fn(donated, dropped)
            exe = fn.lower(donated, dropped).compile()
        except Exception:
            self._m["miss"].inc(1, segment=name, reason="error")
            _LOG.warning("aot retrace-tier load failed for segment %s",
                         name, exc_info=True)
            return None
        self._m["hit"].inc(1, segment=name, tier="retrace")
        return exe

    # -- the segment-facing surface -------------------------------------
    def load_or_compile(self, segment, donated: dict, dropped: dict,
                        *, building: bool = False, _fps=None):
        """The FusedSegment request path: store hit → deserialized
        executable; miss → LOUD counter, then compile-and-backfill so
        the next fresh process hits. Returns None only for segments
        that cannot be fingerprinted (they keep the plain jit path).
        ``building=True`` (the build CLI) treats an absent entry as the
        job, not a miss — no counter, no warning. ``_fps`` reuses a
        caller's already-computed fingerprints (hashing every fitted
        param array is the expensive part — don't pay it twice)."""
        try:
            if _fps is None:
                _fps = segment_fingerprints(segment, donated, dropped)
            static_fp, full_fp, specs = _fps
        except Unfingerprintable as e:
            self._m["miss"].inc(1, segment=segment.name,
                                reason="unfingerprintable")
            _LOG.warning("segment %s is not AOT-eligible: %s",
                         segment.name, e)
            return None
        meta = self._read_meta(self.entry_dir(full_fp))
        if meta is not None:
            exe = self.load_entry(meta, segment=segment)
            if exe is not None:
                return exe
            # corrupt/deserialize miss already counted by load_entry
        elif not building:
            self._m["miss"].inc(1, segment=segment.name,
                                reason="absent")
            _LOG.warning(
                "aot store miss (absent) for segment %s bucket %s — "
                "compiling at runtime and backfilling; run the build "
                "CLI to cover this (route, bucket)", segment.name,
                [list(v.shape) for v in donated.values()] or
                [list(v.shape) for v in dropped.values()])
        return self.build_segment(segment, donated, dropped,
                                  _fps=(static_fp, full_fp, specs),
                                  backfill=not building)

    def build_segment(self, segment, donated: dict, dropped: dict, *,
                      _fps=None, backfill: bool = False):
        """lower+compile one segment × bucket and publish it. The build
        CLI's unit of work; also the miss path's backfill."""
        import time as _time
        if _fps is None:
            static_fp, full_fp, specs = segment_fingerprints(
                segment, donated, dropped)
        else:
            static_fp, full_fp, specs = _fps
        fn = segment._ensure_fn(donated, dropped)
        t0 = _time.perf_counter()
        lowered = fn.lower(donated, dropped)
        compiled = lowered.compile()
        self._m["build"].observe(_time.perf_counter() - t0,
                                 segment=segment.name)
        try:
            hlo = lowered.as_text()
        except Exception:
            hlo = None
        from ..parallel import compat
        # analytic cost attribution (obs.attribution, ISSUE 20): every
        # built program carries its cost_analysis flops/bytes in
        # meta.json, and exports its roofline placement now — warm
        # loads re-export from the persisted pair without re-analyzing
        cost = compat.cost_analysis(compiled)
        if cost is not None:
            from ..obs.attribution import cost_attribution
            cost_attribution.record_program(
                segment.name, cost["flops"], cost["bytes"],
                service=segment.name.split(":", 1)[0])
        blob = None
        if compat.aot_serialization_available():
            try:
                blob = compat.serialize_compiled(compiled)
            except Exception:
                _LOG.warning(
                    "executable serialization failed for segment %s; "
                    "storing a retrace-tier entry (warm loads will "
                    "re-lower at boot, not at request time)",
                    segment.name, exc_info=True)
        else:
            _LOG.warning(
                "this JAX build cannot serialize executables; storing "
                "a retrace-tier entry for segment %s", segment.name)
        try:
            self.save(full_fp=full_fp, static_fp=static_fp,
                      segment_name=segment.name,
                      meta_extra={"donated": specs["donated"],
                                  "dropped": specs["dropped"],
                                  "versions":
                                      specs["static_key"]["versions"],
                                  "platform":
                                      specs["static_key"]["platform"],
                                  **({"cost": cost} if cost is not None
                                     else {})},
                      blob=blob, hlo_text=hlo)
            if backfill:
                self._m["backfill"].inc(1, segment=segment.name)
        except OSError:
            _LOG.warning("aot store write failed for segment %s",
                         segment.name, exc_info=True)
        return compiled

    def warm_segment(self, segment, entries: list | None = None) -> int:
        """Preload every stored bucket of one segment into its
        in-memory executable cache — the scale-up warm boot. Returns
        the number of executables now resident. ``entries`` lets a
        multi-segment warm (maybe_warm) walk the store ONCE and share
        the listing."""
        try:
            key = segment_static_key(
                segment.stages, no_donate=segment.no_donate,
                expected_host=segment.expected_host, mesh=segment.mesh,
                donate=segment.donate, rules=segment.rules,
                platform=_backend_platform())
        except Unfingerprintable:
            return 0
        static_fp = _sha(key)
        if entries is None:
            entries = self.entries()
        n = 0
        for meta in entries:
            if meta.get("static_fp") != static_fp:
                continue
            sig = sig_from_spec(meta.get("donated", []),
                                meta.get("dropped", []))
            if segment._exes.get(sig) is not None:
                continue
            exe = self.load_entry(meta, segment=segment)
            if exe is not None:
                try:
                    # one throwaway dispatch on spec-shaped zeros: a
                    # deserialized Compiled builds its argument-
                    # processing path lazily on first call, and that
                    # setup belongs in the warm boot, not in the first
                    # request's latency (segment bodies are pure by
                    # the traceable-stage contract, so a zeros call
                    # has no side effects)
                    exe(_zeros_from_spec(meta.get("donated", [])),
                        _zeros_from_spec(meta.get("dropped", [])))
                except Exception:
                    _LOG.warning(
                        "aot warm dispatch failed for segment %s; the "
                        "first request will pay the call-path setup",
                        segment.name, exc_info=True)
                segment._exes[sig] = exe
                n += 1
                # re-export the entry's persisted analytic cost (no
                # re-analysis — a deserialized Compiled may not even
                # support cost_analysis): warmed processes report the
                # same roofline gauges the builder did
                cost = meta.get("cost")
                if isinstance(cost, dict):
                    from ..obs.attribution import cost_attribution
                    cost_attribution.record_program(
                        segment.name,
                        cost.get("flops", 0.0), cost.get("bytes", 0.0),
                        service=segment.name.split(":", 1)[0],
                        platform=meta.get("platform") or None)
        return n

    def stats(self) -> dict:
        entries = self.entries()
        return {
            "root": self.root,
            "entries": len(entries),
            "segments": sorted({m.get("segment", "?")
                                for m in entries}),
            "tiers": {t: sum(1 for m in entries
                             if m.get("tier") == t)
                      for t in ("serialized", "retrace")},
        }


# ------------------------------------------------- process-wide activation
_active: AotStore | None = None
_active_lock = threading.Lock()


def install(store: AotStore | str | None = None) -> AotStore:
    """Make a store the process-wide active one: every FusedSegment
    consults it on first execution of a novel bucket."""
    global _active
    with _active_lock:
        if not isinstance(store, AotStore):
            store = AotStore(store)
        _active = store
        return store


def uninstall() -> None:
    global _active
    with _active_lock:
        _active = None


def active_store() -> AotStore | None:
    return _active


# ------------------------------------------------------------ warm loading
def _owned_by_us(path: str) -> bool:
    getuid = getattr(os, "getuid", None)
    if getuid is None:  # platforms without uids: nothing to check
        return True
    try:
        return os.stat(path).st_uid == getuid()
    except OSError:
        return False


def _segments_of(obj):
    """Yield every FusedSegment reachable in a transform object: a
    CompiledPipeline, a stage list, or a DSL ``run`` closure that
    carries its ``stages``."""
    from .compile import CompiledPipeline, FusedSegment
    if obj is None:
        return
    if isinstance(obj, FusedSegment):
        yield obj
        return
    if isinstance(obj, CompiledPipeline):
        for item in obj.plan:
            if isinstance(item, FusedSegment):
                yield item
        return
    if isinstance(obj, (list, tuple)):
        for o in obj:
            yield from _segments_of(o)
        return
    # a DSL ``run`` closure carries its chain as ``run.stages`` (a real
    # list — NOT the Param descriptor a PipelineStage's class attribute
    # resolves to, hence the isinstance gate)
    stages = getattr(obj, "stages", None)
    if isinstance(stages, (list, tuple)):
        yield from _segments_of(list(stages))


def maybe_warm(obj, service: str = "") -> int:
    """Warm-load AOT executables for every fused segment reachable in
    ``obj``. Uses the installed store, or auto-installs one when the
    configured root already exists on disk (so a fresh worker process
    boots hot with zero code changes once the build CLI has run).
    Returns the number of executables loaded; never raises — a warm
    failure must not stop a server from starting cold."""
    try:
        store = active_store()
        if store is None:
            root = store_root()
            if not os.path.isdir(root):
                return 0
            if not _owned_by_us(root):
                # deserialization is pickle: auto-trusting a root some
                # OTHER uid controls would execute their code at boot.
                # An operator who really means it can aot.install() it
                # explicitly.
                _LOG.warning(
                    "aot store root %s is not owned by this user; "
                    "refusing to auto-install it (install() it "
                    "explicitly to override)", root)
                return 0
            store = install(AotStore(root))
        n = 0
        listing = None  # one store walk shared by every segment
        for seg in _segments_of(obj):
            if listing is None:
                listing = store.entries()
            n += store.warm_segment(seg, entries=listing)
        if n:
            _LOG.info("aot warm start%s: %d executable(s) loaded from "
                      "%s", f" [{service}]" if service else "", n,
                      store.root)
        return n
    except Exception:
        _LOG.warning("aot warm start failed; serving will compile at "
                     "runtime", exc_info=True)
        return 0


# ------------------------------------------------------ build registrations
#: service → builder() -> {"stages": [...], "example": DataFrame,
#: "buckets": (int, ...), "mesh": ..., "rules": ...}
_BUILDERS: dict[str, callable] = {}
_builders_lock = threading.Lock()


def register_buildable(service: str, builder) -> None:
    """Register a serving pipeline for the build CLI. ``builder`` is a
    zero-arg callable returning the dict above — called lazily so
    registration at import time stays free (and JAX-free)."""
    with _builders_lock:
        _BUILDERS[service] = builder


def buildable_services() -> list[str]:
    with _builders_lock:
        return sorted(_BUILDERS)


def _resize_example(df, n: int):
    """Tile/truncate an example frame to ``n`` rows — one padding
    bucket's worth of representative columns."""
    from .dataframe import DataFrame
    data = {}
    for c in df.columns:
        col = df[c]
        host = np.asarray(col)
        if host.dtype == object:
            reps = -(-n // max(len(host), 1))
            tiled = np.concatenate([host] * reps)[:n]
            out = np.empty(n, object)
            out[:] = list(tiled)
            data[c] = out
        else:
            reps = -(-n // max(len(host), 1))
            data[c] = np.concatenate([host] * reps, axis=0)[:n]
    return DataFrame(data)


def build_pipeline(cp, example_df, store: AotStore) -> list[dict]:
    """Build every fused segment of one CompiledPipeline for the
    example's bucket, installing the executables in place (the plan is
    executed on the example so downstream segments see the traced
    layout, exactly like compile-time schema propagation)."""
    from .compile import FusedSegment, trace_columns
    records = []
    cur = example_df
    for item in cp.plan:
        if isinstance(item, FusedSegment):
            num = trace_columns(cur)
            donated, dropped = item._split(num)
            try:
                static_fp, full_fp, specs = segment_fingerprints(
                    item, donated, dropped)
                exe = store.load_or_compile(
                    item, donated, dropped, building=True,
                    _fps=(static_fp, full_fp, specs))
                if exe is not None:
                    item._exes[arg_sig(donated, dropped)] = exe
                records.append({
                    "segment": item.name, "static_fp": static_fp,
                    "full_fp": full_fp,
                    "built": exe is not None,
                    "stages": [type(s).__name__ for s in item.stages]})
            except Unfingerprintable as e:
                records.append({"segment": item.name, "built": False,
                                "error": str(e)})
        cur = item.run(cur)
    return records


def _bucket_build_order(service: str, buckets) -> list[int]:
    """Cost-model build planner (ISSUE 12): order a service's padding
    buckets by predicted traffic value — observed FeatureLog request
    share × the learned model's predicted execute cost — so an
    interrupted or time-boxed build compiles the hot path first.
    Deterministic ascending order when nothing has been learned yet
    (a fresh process, or perf unavailable)."""
    try:
        from ..perf.costmodel import bucket_build_priority
        ranked = bucket_build_priority(service, buckets)
    except Exception:
        ranked = []
    if ranked:
        _LOG.info("AOT build order for %r by predicted traffic value: "
                  "%s", service, ranked)
        return ranked
    return sorted({int(x) for x in buckets})


def build_registered(service: str | None = None,
                     store: AotStore | None = None,
                     log=print) -> dict:
    """The build CLI body: for every registered service × padding
    bucket, compile the pipeline's fused segments into the store —
    most-valuable buckets first (:func:`_bucket_build_order`).
    Returns a report incl. the AOT coverage of TRACEABLE stages (from
    ``analysis/traceability.json``)."""
    from .compile import compile_pipeline
    store = store or active_store() or install(AotStore())
    services = [service] if service else buildable_services()
    report = {"root": store.root, "services": {}, "entries": []}
    built_stage_classes: set[str] = set()
    for svc in services:
        with _builders_lock:
            builder = _BUILDERS.get(svc)
        if builder is None:
            raise KeyError(f"no AOT builder registered for {svc!r} "
                           f"(registered: {buildable_services()})")
        spec = builder()
        buckets = tuple(spec.get("buckets") or
                        (len(spec["example"]),))
        svc_records = []
        build_order = _bucket_build_order(svc, buckets)
        for b in build_order:
            example = _resize_example(spec["example"], b)
            cp = compile_pipeline(
                spec["stages"], example, mesh=spec.get("mesh"),
                rules=spec.get("rules"), service=svc)
            recs = build_pipeline(cp, example, store)
            for r in recs:
                r["bucket"] = b
                built_stage_classes.update(r.get("stages", ()))
                log(f"  [{svc}] bucket={b} {r['segment']} "
                    f"{'OK ' + r['full_fp'][:12] if r.get('built') else 'SKIP ' + r.get('error', '')}")
            svc_records.extend(recs)
        report["services"][svc] = {
            "buckets": sorted(set(int(x) for x in buckets)),
            "build_order": build_order,
            "segments": svc_records}
        report["entries"].extend(svc_records)
    report["coverage"] = _traceable_coverage(built_stage_classes)
    return report


def _traceable_coverage(built_classes: set[str]) -> dict:
    """AOT coverage of the TRACEABLE stage population —
    ``analysis/traceability.json`` is the work-list this store
    consumes, so the build report says how much of it is covered."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "analysis", "traceability.json")
    try:
        with open(path, encoding="utf-8") as f:
            tr = json.load(f)
    except (OSError, ValueError):
        return {"traceable": 0, "covered": 0, "missing": []}
    traceable = sorted(s["stage"] for s in tr.get("stages", ())
                       if s.get("classification") == "TRACEABLE")
    covered = sorted(s for s in traceable if s in built_classes)
    return {"traceable": len(traceable), "covered": len(covered),
            "missing": [s for s in traceable if s not in covered]}


# -------------------------------------------------------------- selftest
_SELFTEST_SERVICE = "__selftest__"


def _selftest_builder() -> dict:
    """A deterministic all-param pipeline (no callables → fully
    fingerprintable) used by the CI build-then-load round trip."""
    from .dataframe import DataFrame
    from ..featurize import CleanMissingData, VectorAssembler
    from ..featurize.vector import OneHotEncoderModel

    n, width = 8, 4
    img = (np.arange(n * width, dtype=np.float32)
           .reshape(n, width) / 7.0)
    aux = np.arange(n, dtype=np.float32)
    aux[::3] = np.nan
    cat = (np.arange(n) % 3).astype(np.int32)
    df = DataFrame({"img": img, "aux": aux, "cat": cat})
    clean = CleanMissingData(inputCols=["aux"],
                             cleaningMode="Mean").fit(df)
    stages = [
        clean,
        OneHotEncoderModel(inputCol="cat", outputCol="onehot",
                           categorySize=3, handleInvalid="keep"),
        VectorAssembler(inputCols=["img", "aux", "onehot"],
                        outputCol="features", handleInvalid="keep"),
    ]
    return {"stages": stages, "example": df, "buckets": (4, 8)}


def register_selftest() -> None:
    register_buildable(_SELFTEST_SERVICE, _selftest_builder)


def _verify(root: str, service: str) -> int:
    """The load half of the round trip: fresh plan, warm from the
    store, steady-state declared BEFORE the first request — then the
    run must show zero runtime compiles, ≥1 store hit, and output
    bit-equal to a runtime-compiled plan."""
    from .compile import compile_pipeline
    from ..obs.profile import compile_tracker

    if service == _SELFTEST_SERVICE:
        register_selftest()
    with _builders_lock:
        builder = _BUILDERS.get(service)
    if builder is None:
        print(f"verify: no builder registered for {service!r}")
        return 2
    spec = builder()
    store = install(AotStore(root))
    reg = _reg()

    # reference: runtime-compiled fused output (store NOT consulted)
    uninstall()
    ref_cp = compile_pipeline(spec["stages"], spec["example"],
                              service=service + "-ref")
    ref = ref_cp.transform(spec["example"])

    install(store)
    before = {k: v for k, v in reg.snapshot().items()
              if k.startswith("aot_store_hit_total")}
    cp = compile_pipeline(spec["stages"], spec["example"],
                          service=service)
    warmed = maybe_warm(cp, service=service)
    compile_tracker.mark_steady()
    out = cp.transform(spec["example"])
    runtime = compile_tracker.runtime_compiles()
    compile_tracker.unmark_steady()
    ok = True
    if warmed < 1:
        print(f"verify FAIL: warm start loaded {warmed} executables")
        ok = False
    if runtime:
        print(f"verify FAIL: {runtime} runtime compile(s) after "
              f"steady state: {compile_tracker.runtime_compiled()}")
        ok = False
    for c in ref.columns:
        a, b = np.asarray(ref[c]), np.asarray(out[c])
        if a.shape != b.shape or not np.array_equal(a, b):
            print(f"verify FAIL: column {c!r} differs from the "
                  "runtime-compiled reference")
            ok = False
    hits = sum(v for k, v in reg.snapshot().items()
               if k.startswith("aot_store_hit_total")) - \
        sum(before.values())
    print(f"verify: warmed={warmed} runtime_compiles={runtime} "
          f"hits={hits} columns_equal={ok}")
    return 0 if ok else 1


def _cli(argv=None) -> int:
    import argparse
    import subprocess
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.core.aot",
        description="AOT executable store: build / list / gc / "
                    "selftest / verify")
    sub = ap.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("build", help="compile registered pipelines "
                       "into the store")
    b.add_argument("--import", dest="imports", action="append",
                   default=[], metavar="MODULE",
                   help="module(s) to import first (they call "
                        "aot.register_buildable)")
    b.add_argument("--service", default=None)
    b.add_argument("--root", default=None)
    ls = sub.add_parser("list", help="print store entries (and the "
                        "deploy registry's versions, when present)")
    ls.add_argument("--root", default=None)
    g = sub.add_parser("gc", help="drop version-stale entries (and "
                       "anything not matching --keep-static); "
                       "registry versions in a deploy state are "
                       "always spared")
    g.add_argument("--root", default=None)
    g.add_argument("--keep-static", action="append", default=None,
                   metavar="FP")
    g.add_argument("--keep-versions", type=int, default=None,
                   metavar="N",
                   help="additionally pin the last N deploy-registry "
                        "versions' entries (rollback horizon); spared "
                        "entries count in aot_gc_kept_versions")
    st = sub.add_parser("selftest", help="build-then-load round trip "
                        "in two scrubbed subprocesses (CI job)")
    st.add_argument("--root", default=None)
    v = sub.add_parser("verify", help="warm-load a service from the "
                       "store and assert zero runtime compiles")
    v.add_argument("--root", required=True)
    v.add_argument("--service", required=True)
    v.add_argument("--import", dest="imports", action="append",
                   default=[], metavar="MODULE")
    args = ap.parse_args(argv)

    if args.cmd == "list":
        store = AotStore(args.root)
        entries = store.entries()
        for m in entries:
            print(f"{m['full_fp'][:16]} {m.get('tier', '?'):10s} "
                  f"{m.get('segment', '?')}")
        # deploy registry (serving/deploy.py persists registry.json
        # beside the tree): version names, fingerprints, and per-bucket
        # built/warm state — the operator's pre-flip checklist
        recs = _registry_versions(store.root)
        if recs:
            by_static: dict = {}
            for m in entries:
                by_static.setdefault(m.get("static_fp"), []).append(m)
            print("registry versions:")
            for rec in sorted(recs, key=lambda r: r.get("seq", 0)):
                fps = rec.get("static_fps", [])
                print(f"  {rec['name']:20s} "
                      f"{rec.get('state', '?'):10s} "
                      f"warmed={rec.get('warmed', 0)} "
                      f"fps={','.join(fp[:12] for fp in fps) or '-'}")
                for fp in fps:
                    for m in by_static.get(fp, []):
                        spec = m.get("donated") or []
                        bucket = spec[0][2][0] if spec and \
                            spec[0][2] else "?"
                        print(f"    bucket={bucket:<6} "
                              f"{m.get('tier', '?'):10s} "
                              f"{m['full_fp'][:16]}")
        print(json.dumps(store.stats(), indent=1))
        return 0

    if args.cmd == "gc":
        store = AotStore(args.root)
        keep = set(args.keep_static) if args.keep_static else None
        removed = store.gc(keep_static=keep,
                           keep_model_versions=args.keep_versions)
        print(f"gc: removed {len(removed)} entries; "
              f"{store.stats()['entries']} remain")
        return 0

    if args.cmd == "build":
        import importlib
        for mod in args.imports:
            importlib.import_module(mod)
        if args.service == _SELFTEST_SERVICE or (
                not args.imports and not buildable_services()):
            register_selftest()
        store = AotStore(args.root)
        report = build_registered(args.service, store)
        cov = report["coverage"]
        print(f"build: {len(report['entries'])} entries in "
              f"{store.root}; traceable-stage coverage "
              f"{cov['covered']}/{cov['traceable']}")
        return 0

    if args.cmd == "verify":
        import importlib
        for mod in args.imports:
            importlib.import_module(mod)
        return _verify(args.root, args.service)

    if args.cmd == "selftest":
        from .utils import scrubbed_cpu_env
        root = args.root or tempfile.mkdtemp(
            prefix="mmlspark_tpu_aot_selftest_")
        env = scrubbed_cpu_env()
        rc = subprocess.call(
            [sys.executable, "-m", "mmlspark_tpu.core.aot", "build",
             "--service", _SELFTEST_SERVICE, "--root", root], env=env)
        if rc:
            print("selftest FAILED at build")
            return rc
        rc = subprocess.call(
            [sys.executable, "-m", "mmlspark_tpu.core.aot", "verify",
             "--service", _SELFTEST_SERVICE, "--root", root], env=env)
        print("selftest " + ("OK" if rc == 0 else "FAILED at verify"))
        if args.root is None:
            shutil.rmtree(root, ignore_errors=True)
        return rc
    return 2


if __name__ == "__main__":  # pragma: no cover
    # `python -m mmlspark_tpu.core.aot` executes this file as
    # ``__main__`` — a SECOND module object with its own _BUILDERS.
    # Delegate to the canonical import so `--import`ed app modules
    # (which call mmlspark_tpu.core.aot.register_buildable) and the
    # CLI share one registry.
    from mmlspark_tpu.core.aot import _cli as _canonical_cli
    raise SystemExit(_canonical_cli())
