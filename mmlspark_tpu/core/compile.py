"""Whole-pipeline XLA compilation: fuse traceable stage runs into single
jitted/pjit'd computations.

Why: ``BENCH_TPU_BANKED.json`` shows a served model step at ~1 ms while
the contended device-dispatch RTT is ~64 ms — host↔device round trips
BETWEEN pipeline stages, not compute, dominate end-to-end latency.
Following the Julia-to-TPU full-program compilation approach
(arXiv:1810.09868) and TVM's end-to-end operator fusion
(arXiv:1802.04799), a ``PipelineModel`` of featurize → model → postproc
should lower to ONE XLA computation with donated intermediate buffers,
not one dispatch (plus a host materialization) per stage.

How: :func:`compile_pipeline` walks the stage list with an example
frame, asking each stage :meth:`~.pipeline.Transformer.supports_trace`
for the frame's schema at that point (schema propagation runs the
example eagerly — grouping needs every stage's OUTPUT schema). Maximal
runs of traceable stages become :class:`FusedSegment`\\ s — a single
``parallel.compat.jit`` call (CompileTracker-wired, so retraces land on
the scrape) over a dict of column arrays, with the input dict donated
so XLA reuses inter-stage buffers. Host-bound stages (HTTP, VW,
tokenizer string loops) split the run and execute eagerly, exactly as
today. ``graftcheck``'s ``analysis/traceability.json`` is the work-list
this consumes: every stage it flips TRACEABLE grows the fused spans.

Sharded pipelines fuse too: pass ``mesh`` + partition rules (the
``parallel/partition.py`` rule→``PartitionSpec`` engine, matched over
column names) and segments compile with ``in_shardings`` pinned.

Import is JAX-free and segments build their jitted callable lazily on
first execution. Plan construction over a pipeline with traceable
stages DOES touch the backend — schema propagation runs each stage's
``_trace`` eagerly on the example columns, a handful of tiny eager jnp
ops. Only an all-host plan (the no-JAX CI smoke's case) compiles
without jax in the process.
"""

from __future__ import annotations

import logging

import numpy as np

from .dataframe import DataFrame, jittable_dtype as jittable
from .pipeline import PipelineModel, Transformer

_LOG = logging.getLogger("mmlspark_tpu.core.compile")


def _registry():
    from ..obs.metrics import registry
    return registry


def trace_columns(df: DataFrame) -> dict:
    """The numeric column dict a fused segment operates on."""
    return {c: df[c] for c in df.columns if jittable(df[c].dtype)}


class _EagerStage:
    """Plan item: a host-bound stage (or raw ``df -> df`` callable)
    executed exactly as the un-compiled pipeline would."""

    __slots__ = ("stage", "name")

    def __init__(self, stage):
        self.stage = stage
        self.name = type(stage).__name__

    def run(self, df: DataFrame, profiler=None) -> DataFrame:
        fn = getattr(self.stage, "transform", None) or self.stage
        if profiler is None:
            return fn(df)
        with profiler.step(self.name) as h:
            return h.done(fn(df))


class FusedSegment:
    """Plan item: a maximal run of traceable stages lowered into ONE
    jitted computation over the frame's numeric columns.

    The jitted callable is built lazily on first run (plan construction
    stays JAX-free) through ``parallel.compat.jit`` so every retrace is
    counted by the obs :class:`~..obs.profile.CompileTracker` under
    this segment's name. Input columns that survive to the segment's
    output are donated (a dropped column's buffer cannot alias an
    output, so donating it would only earn jax's unusable-donation
    warning): device-resident survivors are reclaimed for the outputs,
    host numpy columns stream in during jit argument processing.

    A segment that fails at trace or execution time (a shape the static
    contract could not foresee — e.g. a mini-batcher hitting a
    non-divisible row count) falls back to eager per-stage execution
    for that call, counted in ``pipeline_fused_fallback_total``.
    """

    def __init__(self, stages, name: str, donate: bool = True,
                 mesh=None, rules=None, expected_host=frozenset(),
                 no_donate=frozenset()):
        self.stages = list(stages)
        self.name = name
        self.donate = donate
        self.mesh = mesh
        self.rules = rules
        # host (non-jittable) column names the EXAMPLE frame carried at
        # segment entry: the compile-time grouping contracts were
        # checked against exactly this set, so a runtime frame with a
        # different host-column set voids them (run() re-checks)
        self.expected_host = frozenset(expected_host)
        # input columns the segment DROPS (per the example propagation):
        # their buffers cannot alias any output, so donating them only
        # earns jax's unusable-donation warning — they ride the
        # non-donated argument instead
        self.no_donate = frozenset(no_donate)
        self._fn = None
        # per-bucket AOT executables: arg_sig -> jax.stages.Compiled
        # (store-loaded or backfilled); None = bucket known ineligible.
        # Filled by core.aot warm loading and the first-call store
        # lookup; absent sigs fall through to the plain jit path.
        self._exes: dict = {}
        reg = _registry()
        self._c_calls = reg.counter(
            "pipeline_fused_calls_total",
            "fused-segment executions, by segment")
        self._c_fallback = reg.counter(
            "pipeline_fused_fallback_total",
            "fused-segment calls that fell back to eager execution")

    # -- lazy jit ----------------------------------------------------------
    def _body(self, donated: dict, dropped: dict) -> dict:
        cols = dict(donated)
        cols.update(dropped)
        for stage in self.stages:
            cols = stage._trace(cols)
        return cols

    def _split(self, num: dict) -> tuple[dict, dict]:
        """Columns the segment's outputs can alias vs columns it drops
        (only the former are donated — no unusable-donation warnings)."""
        donated = {c: v for c, v in num.items() if c not in self.no_donate}
        dropped = {c: v for c, v in num.items() if c in self.no_donate}
        return donated, dropped

    def _ensure_fn(self, donated: dict, dropped: dict):
        if self._fn is not None:
            return self._fn
        from ..parallel import compat
        kwargs = {}
        if self.donate:
            # surviving columns only (see _split): host numpy inputs
            # donate silently (jax owns the transfer buffer),
            # device-resident inputs are genuinely reclaimed for the
            # segment's outputs
            kwargs["donate_argnums"] = (0,)
        if self.mesh is not None and self.rules is not None:
            from ..parallel.partition import (match_partition_rules,
                                              to_shardings)
            kwargs["in_shardings"] = tuple(
                to_shardings(self.mesh, cols,
                             match_partition_rules(self.rules, cols))
                for cols in (donated, dropped))
        self._fn = compat.jit(self._body, name=self.name, **kwargs)
        return self._fn

    def _aot_executable(self, donated: dict, dropped: dict):
        """The ahead-of-time path: a warm-loaded (or store-resident)
        executable for THIS bucket, or None → plain jit. A store miss
        compiles-and-backfills inside the store (loud counters), so a
        fresh process only ever pays each bucket's compile once across
        the whole fleet's lifetime. Failures degrade to the jit path —
        AOT is an accelerator, never a correctness gate."""
        from . import aot
        store = aot.active_store()
        if store is None and not self._exes:
            return None
        sig = aot.arg_sig(donated, dropped)
        if sig in self._exes:
            return self._exes[sig]
        if store is None:
            return None
        try:
            exe = store.load_or_compile(self, donated, dropped)
        except Exception:
            _LOG.warning("aot lookup failed for segment %s; using the "
                         "runtime jit path", self.name, exc_info=True)
            exe = None
        self._exes[sig] = exe
        return exe

    # -- execution ---------------------------------------------------------
    def _eager(self, df: DataFrame) -> DataFrame:
        self._c_fallback.inc(1, segment=self.name)
        cur = df
        for stage in self.stages:
            cur = stage.transform(cur)
        return cur

    def run(self, df: DataFrame, profiler=None) -> DataFrame:
        import jax
        num = trace_columns(df)
        carry = [(c, df[c]) for c in df.columns if c not in num]
        if {c for c, _ in carry} != self.expected_host:
            # the compile-time grouping contracts (row-change veto,
            # drop/select/rename completeness) were checked against the
            # EXAMPLE's host columns; this frame carries a different
            # host-column set, so the traced forms — which never see
            # host columns — could silently diverge from eager
            # semantics (a reshaped frame mis-aligning a carried
            # column, a SelectColumns leaking one). Eager is the
            # reference behavior; run it.
            _LOG.warning("fused segment %s: host columns %s differ "
                         "from the compile example's %s, running "
                         "eagerly", self.name,
                         sorted(c for c, _ in carry),
                         sorted(self.expected_host))
            return self._eager(df)
        # host columns go into the jitted call as-is: jax transfers them
        # during argument processing, which is measurably cheaper than a
        # Python-level jnp.asarray pass per column first
        donated, dropped = self._split(num)
        fn = self._aot_executable(donated, dropped) \
            or self._ensure_fn(donated, dropped)
        try:
            if profiler is None:
                out = fn(donated, dropped)
            else:
                # the single dispatch this segment replaced N per-stage
                # dispatches with — StepProfiler splits it into host-
                # dispatch vs device-execute via the block_until_ready
                # delta, attributed to THIS segment
                with profiler.step(self.name) as h:
                    out = h.done(fn(donated, dropped))
            # ONE batched device→host transfer for the whole segment
            # output; merging stays inside the fallback boundary so a
            # shape the static contract could not foresee degrades to
            # eager execution instead of a corrupt frame
            merged = _merge_traced(df, jax.device_get(out), carry,
                                   self.stages)
        except Exception:
            _LOG.warning("fused segment %s fell back to eager "
                         "execution", self.name, exc_info=True)
            return self._eager(df)
        self._c_calls.inc(1, segment=self.name)
        return merged

    def run_sharded(self, columns: dict) -> dict:
        """Execute the fused body on already-GLOBAL device arrays and
        return device outputs — the pod serving path.

        ``run()`` is host-mediated: numpy in, ``jax.device_get`` out.
        On a multi-process mesh both ends break — no single process
        holds a full row batch, and ``device_get`` on a non-fully-
        addressable array raises. Here the caller feeds global arrays
        (``parallel.feed_process_local`` / ``compat
        .make_array_from_process_local_data``) whose rows live on
        different hosts, every process executes the same program, and
        outputs stay sharded on device; gather explicitly via
        ``compat.process_allgather(..., tiled=True)`` when a host copy
        is wanted. No eager fallback: eager stage-by-stage transforms
        are host numpy code and cannot run on a sharded batch, so
        errors propagate.
        """
        donated, dropped = self._split(dict(columns))
        fn = self._aot_executable(donated, dropped) \
            or self._ensure_fn(donated, dropped)
        if self.mesh is not None:
            with self.mesh:
                out = fn(donated, dropped)
        else:
            out = fn(donated, dropped)
        self._c_calls.inc(1, segment=self.name)
        return out


def _merge_traced(df: DataFrame, out: dict, carry,
                  stages) -> DataFrame:
    """Traced output columns + host-carried columns → DataFrame. This
    is THE host materialization point of the whole segment (one sync,
    not one per stage — ``FusedSegment.run`` hands ``out`` through a
    single batched ``jax.device_get``, so the np.asarray below is a
    no-op there; the compile-time schema-propagation path still
    materializes here); column order follows the input frame,
    renamed/new columns append in ``_trace`` output order. Host
    metadata hooks (partition counts, column metadata) apply last."""
    host = {c: np.asarray(v) for c, v in out.items()}
    data: dict[str, np.ndarray] = {}
    carried = dict(carry)
    for c in df.columns:
        if c in host:
            data[c] = host.pop(c)
        elif c in carried:
            data[c] = carried[c]
    data.update(host)
    # DataFrame.__new__ below skips __init__'s validation — re-check the
    # one invariant that matters so a row-count mismatch (traced columns
    # reshaped, a carried column not) raises into the eager fallback
    # instead of building a silently mis-aligned frame
    lengths = {len(v) for v in data.values()}
    if len(lengths) > 1:
        raise ValueError(
            f"fused segment produced ragged column lengths {lengths}")
    new = DataFrame.__new__(DataFrame)
    new._data = data
    new.num_partitions = df.num_partitions
    for stage in stages:
        hooked = stage._post_host(new)
        # explicit None check: a 0-row DataFrame is falsy, and the
        # hook's result (metadata attach, repartition) must not be
        # dropped on legitimately empty runtime frames
        if hooked is not None:
            new = hooked
    return new


class CompiledPipeline:
    """A lowered pipeline: an ordered plan of :class:`FusedSegment` and
    :class:`_EagerStage` items. Duck-types a Transformer (``transform``
    / ``__call__``), so it drops into ``ServingQuery``, the serving
    DSL, or anywhere a stage fits."""

    def __init__(self, plan, service: str = "pipeline"):
        self.plan = list(plan)
        self.service = service

    # -- introspection -----------------------------------------------------
    @property
    def compiled_segments(self) -> int:
        """Fused-segment count — the dispatch count per call for the
        traced portion (FeatureLog records this per served request)."""
        return sum(1 for p in self.plan if isinstance(p, FusedSegment))

    @property
    def fused_stages(self) -> int:
        return sum(len(p.stages) for p in self.plan
                   if isinstance(p, FusedSegment))

    @property
    def eager_stages(self) -> int:
        return sum(1 for p in self.plan if isinstance(p, _EagerStage))

    def describe(self) -> list[dict]:
        """Human/bench-readable plan: one dict per item."""
        out = []
        for p in self.plan:
            if isinstance(p, FusedSegment):
                out.append({"kind": "fused", "segment": p.name,
                            "stages": [type(s).__name__
                                       for s in p.stages]})
            else:
                out.append({"kind": "eager", "stage": p.name})
        return out

    def warm_aot(self, store=None) -> int:
        """Preload every store-resident executable for this plan's
        fused segments (the scale-up warm boot — see ``core/aot.py``
        and ``docs/aot.md``). Returns executables loaded; 0 when no
        store is installed/on disk."""
        from . import aot
        if store is not None:
            aot.install(store)
        loaded = aot.maybe_warm(self, service=self.service)
        if loaded:
            # HBM watermark after the warm boot (obs.memory): what
            # preloading the executable store cost in device memory,
            # scrapeable as mem_event_watermark_bytes{event="aot_warm"}
            from ..obs.memory import memory_profiler
            memory_profiler.note_event("aot_warm")
        self.attribute_costs()
        return loaded

    def attribute_costs(self) -> int:
        """Export the roofline placement of every RESIDENT executable
        (obs.attribution): store-warmed entries already re-exported
        their persisted meta.json pair, so this pass covers what they
        cannot — runtime-backfilled buckets and live Compiled objects
        whose analysis never hit disk. Programs a backend refuses to
        analyze are counted (``profile_cost_analysis_missing_total``),
        never raised. Returns programs attributed."""
        from ..obs.attribution import cost_attribution
        n = 0
        for item in self.plan:
            if not isinstance(item, FusedSegment):
                continue
            for exe in item._exes.values():
                if exe is None:
                    continue
                if cost_attribution.record_compiled(
                        item.name, exe,
                        service=item.name.split(":", 1)[0]) is not None:
                    n += 1
                    break  # one bucket prices the segment's program
        return n

    # -- execution ---------------------------------------------------------
    def transform(self, df: DataFrame) -> DataFrame:
        from ..obs.profile import pipeline_profiler
        prof = pipeline_profiler()
        cur = df
        for item in self.plan:
            cur = item.run(cur, profiler=prof)
        return cur

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


def compile_pipeline(model_or_stages, example_df: DataFrame, *,
                     mesh=None, rules=None, donate: bool = True,
                     service: str = "pipeline") -> CompiledPipeline:
    """Lower a ``PipelineModel`` (or stage list) into a
    :class:`CompiledPipeline`.

    Walks the stages with ``example_df``, greedily grouping maximal
    runs of stages whose :meth:`supports_trace` accepts the schema AT
    THAT POINT in the pipeline (the example is transformed eagerly once
    to propagate schemas). Stages whose ``_trace`` changes the row
    count only join a segment when every column is numeric — a
    host-carried string column cannot be re-attached to a reshaped
    frame. An all-host pipeline degrades to today's per-stage behavior
    exactly (plan of eager items, zero segments).
    """
    if isinstance(model_or_stages, PipelineModel):
        stages = list(model_or_stages.getOrDefault("stages"))
    else:
        stages = list(model_or_stages)
    plan: list = []
    run: list = []
    run_host: frozenset = frozenset()
    run_entry_cols: dict = {}
    seg_idx = 0
    cur = example_df

    def flush():
        nonlocal seg_idx, run
        if not run:
            return
        # only an entry column that reaches the segment output with the
        # SAME shape and dtype can alias an output buffer — anything
        # dropped, renamed, or reshaped (mini-batchers) is excluded
        # from donation (donating it would only earn jax's
        # unusable-donation warning)
        exit_cols = {c: (v.shape, v.dtype)
                     for c, v in trace_columns(cur).items()}
        plan.append(FusedSegment(
            run, f"{service}:seg{seg_idx}", donate=donate,
            mesh=mesh, rules=rules, expected_host=run_host,
            no_donate=frozenset(
                c for c, sig in run_entry_cols.items()
                if exit_cols.get(c) != sig)))
        seg_idx += 1
        run = []

    for stage in stages:
        ok = isinstance(stage, Transformer) and \
            stage.supports_trace(cur.schema, cur.num_rows)
        if ok and getattr(stage, "_trace_changes_rows", False):
            # row-count-changing stages need the WHOLE frame in the
            # traced dict; any host-carried column vetoes fusion here
            ok = all(jittable(dt) for dt, _ in cur.schema.values())
        if ok:
            if not run:
                # the host-column set the grouping contracts are being
                # checked against — run() re-validates it per call —
                # and the numeric entry set the donation split needs
                run_host = frozenset(
                    c for c, (dt, _) in cur.schema.items()
                    if not jittable(dt))
                run_entry_cols = {c: (v.shape, v.dtype)
                                  for c, v in trace_columns(cur).items()}
            run.append(stage)
            # propagate the example through the TRACED form (run
            # eagerly on the example columns): the fused layout — e.g.
            # a mini-batcher's [nb, size] numeric output vs its eager
            # object cells — is what the next stage's contract check
            # must see
            num = trace_columns(cur)
            carry = [(c, cur[c]) for c in cur.columns if c not in num]
            cur = _merge_traced(cur, stage._trace(num), carry, [stage])
        else:
            flush()
            plan.append(_EagerStage(stage))
            cur = (stage.transform(cur) if hasattr(stage, "transform")
                   else stage(cur))
    flush()
    return CompiledPipeline(plan, service=service)
