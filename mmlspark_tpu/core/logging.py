"""Telemetry logging wrapped around stage entry points.

Role of reference ``logging/BasicLogging.scala:26-92``: every stage logs a
JSON event ``{uid, className, method, buildVersion}`` on construction and on
each fit/transform/predict, plus error events with the exception. Here it is a
context manager so the wrapped region is timed as well (the reference pairs
this with its ``Timer`` stage; we fold wall time into the event).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time

logger = logging.getLogger("mmlspark_tpu.telemetry")

BUILD_VERSION = "0.1.0"


class BasicLogging:
    def _log_event(self, method: str, **extra) -> None:
        payload = {
            "uid": getattr(self, "uid", None),
            "className": type(self).__name__,
            "method": method,
            "buildVersion": BUILD_VERSION,
            **extra,
        }
        logger.info(json.dumps(payload))

    def log_class(self) -> None:
        self._log_event("constructor")

    @contextlib.contextmanager
    def log_call(self, method: str):
        start = time.perf_counter()
        try:
            yield
        except Exception as e:
            self._log_event(method, error=repr(e),
                            seconds=time.perf_counter() - start)
            raise
        else:
            self._log_event(method, seconds=time.perf_counter() - start)
