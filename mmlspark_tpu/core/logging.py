"""Telemetry logging wrapped around stage entry points.

Role of reference ``logging/BasicLogging.scala:26-92``: every stage logs a
JSON event ``{uid, className, method, buildVersion}`` on construction and on
each fit/transform/predict, plus error events with the exception. Here it is a
context manager so the wrapped region is timed as well (the reference pairs
this with its ``Timer`` stage; we fold wall time into the event).

Each ``log_call`` region is also an obs tracer span (``obs.tracing``):
the event carries ``traceId``/``spanId``/``parentId``, and any spans
opened inside the call — boosting rounds, serving batches — nest under
it in the same JSON sink. The span itself emits no separate line here
(the stage event IS the span record), so existing consumers see one
event per call, now with trace linkage.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time

from ..obs.tracing import tracer as _tracer

logger = logging.getLogger("mmlspark_tpu.telemetry")

BUILD_VERSION = "0.1.0"


class BasicLogging:
    def _log_event(self, method: str, **extra) -> None:
        payload = {
            "uid": getattr(self, "uid", None),
            "className": type(self).__name__,
            "method": method,
            "buildVersion": BUILD_VERSION,
            **extra,
        }
        logger.info(json.dumps(payload))

    def log_class(self) -> None:
        self._log_event("constructor")

    @contextlib.contextmanager
    def log_call(self, method: str):
        start = time.perf_counter()
        # the span carries parentage for anything traced inside the call;
        # emission stays with _log_event below (one line per call)
        span = _tracer.start_span(f"{type(self).__name__}.{method}",
                                  uid=getattr(self, "uid", None))
        link = {"traceId": span.trace_id, "spanId": span.span_id,
                "parentId": span.parent_id}
        try:
            yield
        except BaseException as e:
            # BaseException, not Exception: a KeyboardInterrupt thrown
            # into the region must still end the span, or the ambient
            # contextvar keeps pointing at it and every later span in
            # this thread parents under a dead trace
            _tracer.end_span(span, error=e, emit=False)
            self._log_event(method, error=repr(e),
                            seconds=time.perf_counter() - start, **link)
            raise
        else:
            _tracer.end_span(span, emit=False)
            self._log_event(method, seconds=time.perf_counter() - start,
                            **link)
