"""Core utilities: fault tolerance, timing, device topology, schema helpers.

Covers the reference's ``core/utils`` + ``downloader/ModelDownloader.scala``
fault-tolerance wrapper + ``core/utils/ClusterUtil.scala`` cluster-topology
discovery. On TPU, "cluster topology" = the JAX device/mesh view: number of
local devices, hosts, and a default mesh over which stages shard work.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Callable, Sequence, TypeVar

import numpy as np

T = TypeVar("T")

# Reference downloader/ModelDownloader.scala:37-60 backoff sequence.
DEFAULT_BACKOFFS_MS: tuple[int, ...] = (0, 100, 200, 500)


def retry_with_timeout(fn: Callable[[], T],
                       timeout_s: float | None = None,
                       backoffs_ms: Sequence[int] = DEFAULT_BACKOFFS_MS) -> T:
    """Retry ``fn`` over a backoff schedule; optional per-attempt timeout.

    Caveat (same semantics as the reference's ``Await.result``-based wrapper):
    a timed-out attempt's thread keeps running in the background, so with
    ``timeout_s`` the ``fn`` must tolerate concurrent invocations.
    """
    if not backoffs_ms:
        raise ValueError("backoffs_ms must contain at least one entry")
    last: Exception | None = None
    for i, backoff in enumerate(backoffs_ms):
        if backoff:
            time.sleep(backoff / 1000.0)
        try:
            if timeout_s is None:
                return fn()
            # No `with`: __exit__ would join the worker and defeat the timeout.
            ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
            try:
                return ex.submit(fn).result(timeout=timeout_s)
            finally:
                ex.shutdown(wait=False)
        except Exception as e:  # noqa: BLE001 — retry wrapper by design
            last = e
    assert last is not None  # loop ran ≥ once since backoffs_ms is non-empty
    raise last


class StopWatch:
    """Nanosecond accumulator (reference ``core/utils/StopWatch.scala``)."""

    def __init__(self):
        self.elapsed_ns = 0
        self._start: int | None = None

    def start(self) -> None:
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        if self._start is not None:
            self.elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def measure(self, fn: Callable[[], T]) -> T:
        with self:
            return fn()


class ClusterUtil:
    """Device-topology discovery — the TPU analogue of executor counting.

    Reference ``core/utils/ClusterUtil.scala:13-291`` asks Spark how many
    executors × cores are available to size the LightGBM worker mesh; here we
    ask JAX for devices/hosts and size shard counts the same way.
    """

    @staticmethod
    def get_num_devices() -> int:
        import jax
        return jax.device_count()

    @staticmethod
    def get_num_local_devices() -> int:
        import jax
        return jax.local_device_count()

    @staticmethod
    def get_num_hosts() -> int:
        import jax
        return jax.process_count()

    @staticmethod
    def get_host_index() -> int:
        import jax
        return jax.process_index()

    @staticmethod
    def default_mesh(axis_name: str = "dp"):
        import jax
        from jax.sharding import Mesh
        devices = np.asarray(jax.devices())
        return Mesh(devices, (axis_name,))

    @staticmethod
    def get_jvm_cpus() -> int:
        import os
        return os.cpu_count() or 1


def find_unused_column_name(prefix: str, df) -> str:
    """Reference ``core/schema/DatasetExtensions.findUnusedColumnName``."""
    name = prefix
    i = 0
    while name in df.columns:
        i += 1
        name = f"{prefix}_{i}"
    return name


_AXON_HINTS = ("axon", "pallas_axon")


def scrubbed_cpu_env(n_devices: int | None = None,
                     extra_path: str | None = None) -> dict:
    """Subprocess environment with every accelerator-tunnel hook removed
    and the platform pinned to host CPU (optionally with ``n_devices``
    virtual devices). The ONE copy of the wedge-guard scrub: a wedged
    remote-device tunnel hangs ``jax.devices()`` inside any process whose
    site-hook survives, and JAX_PLATFORMS alone does not override the
    hook."""
    import os
    env = dict(os.environ)
    for key in list(env):
        if any(h in key.lower() for h in _AXON_HINTS):
            del env[key]
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and not any(h in p.lower() for h in _AXON_HINTS)]
    if extra_path:
        parts.insert(0, extra_path)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    # persistent-compile-cache placement: an explicit operator override
    # wins, then the AOT store root (core/aot.py — the two caches
    # co-locate), then the historical default. Never clobber a set
    # value: a child that silently wrote elsewhere would split the
    # cache the parent is warming.
    if not env.get("JAX_COMPILATION_CACHE_DIR"):
        from .aot import jax_cache_dir
        env["JAX_COMPILATION_CACHE_DIR"] = jax_cache_dir()
    return env


def stable_sigmoid(x: np.ndarray) -> np.ndarray:
    """Overflow-free logistic: exp is only ever taken of a non-positive
    argument."""
    x = np.asarray(x)
    e = np.exp(-np.abs(x))
    return np.where(x >= 0, 1.0 / (1.0 + e), e / (1.0 + e))


def as_2d_features(df, features_col: str) -> np.ndarray:
    """Features column → dense float32 [n, d] matrix."""
    arr = df[features_col]
    if arr.dtype == object:
        arr = np.stack([np.asarray(v, dtype=np.float32) for v in arr])
    if arr.ndim == 1:
        arr = arr[:, None]
    return np.ascontiguousarray(arr, dtype=np.float32)


def using(resources: Sequence, fn: Callable):
    """RAII helper (reference ``core/env/StreamUtilities.using``)."""
    try:
        return fn(*resources)
    finally:
        for r in resources:
            close = getattr(r, "close", None)
            if close:
                close()
