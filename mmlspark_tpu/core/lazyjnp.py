"""Lazy ``jax.numpy`` proxy for stage modules.

Stage and featurizer modules compute with ``jax.numpy`` (that is what
makes them TRACEABLE — see ``analysis/traceability.json`` and
``docs/pipeline_compilation.md``), but the package must stay importable
on machines with no JAX at all: graftcheck analyzes it as pure ast, the
codegen walks it, and control-plane processes import it for the stage
registry. This proxy defers the ``import jax.numpy`` to the first
attribute access, so ``from ..core.lazyjnp import jnp`` at module top
costs nothing until a transform actually runs.

Inside a traced ``_trace`` body the proxy adds one dict lookup per op —
negligible against trace time, and zero against the compiled program
(tracing happens once per shape).
"""

from __future__ import annotations


class _LazyModule:
    """Attribute-forwarding proxy that imports its target on first use."""

    __slots__ = ("_name", "_mod")

    def __init__(self, name: str):
        self._name = name
        self._mod = None

    def __getattr__(self, attr: str):
        mod = self._mod
        if mod is None:
            import importlib
            mod = self._mod = importlib.import_module(self._name)
        return getattr(mod, attr)


#: ``jax.numpy``, imported on first attribute access.
jnp = _LazyModule("jax.numpy")

#: ``jax.random``, imported on first attribute access (StratifiedRepartition
#: draws its shuffle from here — device RNG, not host RNG, so the stage's
#: compute stays on the traceable side of the report).
jrandom = _LazyModule("jax.random")
