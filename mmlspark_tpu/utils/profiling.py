"""Profiling & tracing.

The reference has no tracer (SURVEY §5) — only the ``Timer`` transformer
and VW's nanosecond stopwatches. The TPU build upgrades this to
``jax.profiler`` device traces (viewable in XProf/TensorBoard) plus the
same stage-timing surface.
"""

from __future__ import annotations

import contextlib
import functools
import time


@contextlib.contextmanager
def profile_trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a device+host trace for the enclosed region
    (``jax.profiler.trace`` wrapper; open with XProf/TensorBoard)."""
    import jax
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profiled(name: str | None = None):
    """Decorator: annotate a function in device traces
    (``jax.profiler.TraceAnnotation``) and record wall time."""
    def wrap(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            import jax
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)
        return inner
    return wrap


class StageTimer:
    """Accumulate named wall-clock spans (the VW ``TrainingStats``
    nanosecond-timing surface, ``vw/VowpalWabbitBase.scala:27-49``)."""

    def __init__(self):
        self.totals_ns: dict[str, int] = {}

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.totals_ns[name] = self.totals_ns.get(name, 0) + \
                time.perf_counter_ns() - t0

    def as_dict(self) -> dict[str, float]:
        return {k: v / 1e9 for k, v in self.totals_ns.items()}
