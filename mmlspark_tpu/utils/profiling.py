"""Profiling: device traces (XProf) + trace annotations.

The reference has no tracer (SURVEY §5) — only the ``Timer`` transformer
and VW's nanosecond stopwatches. The TPU build upgrades this to
``jax.profiler`` device traces (viewable in XProf/TensorBoard); the
host-side span/timing surface lives in ``mmlspark_tpu.obs`` (one
registry + tracer for every layer — see docs/observability.md).
``StageTimer`` is re-exported from there: same ``span``/``as_dict``
contract, now nesting into the process-wide trace as well.
"""

from __future__ import annotations

import contextlib
import functools

from ..obs.tracing import StageTimer  # noqa: F401  (compat re-export)


@contextlib.contextmanager
def profile_trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a device+host trace for the enclosed region
    (``jax.profiler.trace`` wrapper; open with XProf/TensorBoard)."""
    import jax
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profiled(name: str | None = None):
    """Decorator: annotate a function in device traces
    (``jax.profiler.TraceAnnotation``) and record wall time."""
    def wrap(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            import jax
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)
        return inner
    return wrap
