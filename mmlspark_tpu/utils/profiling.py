"""DEPRECATED shim — the profiling surface moved to ``mmlspark_tpu.obs``.

PR 1 left this module as the XProf half of a split timing story; the
continuous profiler (``obs/profile.py``) subsumed it: ``profile_trace``
and ``profiled`` live there (unchanged contracts), ``StageTimer`` in
``obs.tracing``, and the new always-on surfaces (``CompileTracker``,
``StepProfiler``, the cost-model feature log) have no equivalent here.

Importing from this module keeps working but warns once; update imports
to ``mmlspark_tpu.obs.profile`` / ``mmlspark_tpu.obs``.
"""

from __future__ import annotations

import warnings

from ..obs.profile import profile_trace, profiled  # noqa: F401
from ..obs.tracing import StageTimer  # noqa: F401  (compat re-export)

warnings.warn(
    "mmlspark_tpu.utils.profiling is deprecated: profile_trace/profiled "
    "moved to mmlspark_tpu.obs.profile (StageTimer to mmlspark_tpu.obs); "
    "this shim will be removed once in-repo callers are migrated",
    DeprecationWarning, stacklevel=2)
