"""Cross-cutting utilities: profiling/tracing.

The timing/profiling surface itself lives in ``mmlspark_tpu.obs`` (one
registry + tracer + profiler for every layer); these re-exports keep the
historic ``mmlspark_tpu.utils`` import path working without routing
through the deprecated ``utils.profiling`` shim module.
"""

from ..obs.profile import profile_trace, profiled
from ..obs.tracing import StageTimer

__all__ = ["profile_trace", "profiled", "StageTimer"]
