"""Cross-cutting utilities: profiling/tracing."""

from .profiling import profile_trace, profiled, StageTimer

__all__ = ["profile_trace", "profiled", "StageTimer"]
