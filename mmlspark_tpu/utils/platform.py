"""Dependency-light platform detection (jax-only imports).

Lives outside the flax/optax-coupled ``dl`` package so engine code
(e.g. the LightGBM Pallas histogram gate) can import it without pulling
the whole DL stack — or failing on minimal installs that lack flax.
"""

from __future__ import annotations

import jax


def target_platform() -> str:
    """Platform uncommitted computations will land on: honours an active
    ``jax.default_device(...)`` context (e.g. a host-CPU ``module.init``
    on a TPU-attached process) before falling back to the default
    backend. Compiled Pallas must not lower for a CPU placement."""
    dev = jax.config.jax_default_device
    if isinstance(dev, str):       # jax accepts platform-name strings too
        return dev
    platform = getattr(dev, "platform", None)
    if platform is not None:
        return platform
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover - no backend at all
        return "cpu"
