"""Learned service-cost model trained on the obs FeatureLog (ISSUE 12).

PR 6 built the substrate: ``obs/profile.FeatureLog`` appends one
training row per served request (route, batch, padding bucket, entity
bytes, queue depth, execute ms). This module is the first learned
consumer — a per-(service, route) ridge regression over those rows,
per "A Learned Performance Model for TPUs" (arXiv:2008.01040), scoped
to what a pure-stdlib/numpy control plane can train online:

- **features**: padding bucket (the padded shape the executor actually
  runs), raw batch size, entity kilobytes, queue depth — with per-key
  training means filling features the caller cannot know at estimate
  time (admission prices a request before its batch forms);
- **target**: ``execute_ms`` — the batch transform wall time the
  scheduler's close decision and admission's Little's-law shed both
  price today via a per-bucket EWMA;
- **online refresh**: :meth:`CostModel.maybe_refresh` refits from the
  live FeatureLog every ``refresh_every`` new rows — serving traffic
  trains the model that prices serving traffic;
- **loud fallback gate**: a cold model (too few rows for the service)
  or one whose recent absolute error exceeds ``error_gate`` × the
  recent actual magnitude answers ``None`` — the consumer falls back
  to the EWMA it always had, and the refusal is counted
  (``sched_costmodel_fallback_total{reason=cold|error}``) and logged
  on every gate flip, never silent;
- **persistence**: :meth:`save`/:meth:`load_file` round-trip the
  fitted parameters as JSON under :func:`perf_root` (beside the
  autotune winner registry), so a rebooted server prices with last
  boot's model until fresh traffic retrains it.

Rows are schema-checked: anything whose ``schema_version`` is not in
``ACCEPTED_SCHEMA_VERSIONS`` is SKIPPED loudly (counted + warned),
never misparsed — old logs degrade to the EWMA, not to garbage
predictions. v2 rows stay accepted alongside the current v3: v3 only
added the ``process`` rank stamp (a label, not a feature column), so
a pre-fleet log still fits and prices correctly.

Import is stdlib + numpy + obs/sched only — no JAX, no device (the CI
smoke asserts it). Prediction takes a lock; it runs on scheduler and
handler threads, never inside a traced region.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading

import numpy as np

from ..obs import registry as _default_registry
from ..obs.profile import FEATURE_SCHEMA_VERSION, feature_log as _feature_log
from ..sched.policy import bucket_of

_LOG = logging.getLogger("mmlspark_tpu.perf")

__all__ = ["CostModel", "shared_cost_model", "enabled", "perf_root",
           "model_path", "bucket_build_priority"]

#: default on-disk root for learned-performance artifacts (cost-model
#: params + autotune winner registry). Per-user for the same reason as
#: the AOT store: a shared /tmp path would let any local user plant
#: parameters another user's server boot would trust.
DEFAULT_PERF_ROOT = "/tmp/mmlspark_tpu_perf-" + str(
    getattr(os, "getuid", lambda: "u")())

#: the model's feature vector (after the intercept); per-key training
#: means fill features the caller cannot supply at estimate time. The
#: last three are generation-only (v4/v5 rows from the LLM serving
#: engine) — absent on every other row, where they train as 0 and the
#: fitted weights price exactly the decode-vs-prefill split (and, via
#: ``context_blocks``, decode cost by resident context — the chain
#: length the paged-attention kernel streams per step) for services
#: that record them.
FEATURES = ("bucket", "batch", "entity_kb", "queue_depth",
            "decode_steps", "prefill_tokens", "context_blocks",
            "analytic_tflops", "analytic_gb")

#: Row schemas this model can consume. v3 (the fleet PR) added only the
#: ``process`` rank stamp, v4 only the OPTIONAL generation fields
#: (``decode_steps``/``prefill_tokens`` default to 0 when absent), v5
#: only the OPTIONAL ``context_blocks`` (same default), and v6 only the
#: OPTIONAL analytic-cost pair (``analytic_flops``/``analytic_bytes``
#: from obs.attribution, same default) — no existing feature column
#: changed meaning — so v2–v5 logs remain fully usable; anything else
#: is skipped loudly in :meth:`fit`.
ACCEPTED_SCHEMA_VERSIONS = frozenset({FEATURE_SCHEMA_VERSION, 5, 4, 3,
                                      2})

MODEL_VERSION = 1


def perf_root() -> str:
    """The configured artifact root: ``MMLSPARK_TPU_PERF_STORE`` or the
    per-user default (shared with ``perf.autotune``'s registry)."""
    return os.environ.get("MMLSPARK_TPU_PERF_STORE") or DEFAULT_PERF_ROOT


def model_path() -> str:
    return os.path.join(perf_root(), "costmodel.json")


def enabled() -> bool:
    """Process-wide kill switch: ``MMLSPARK_TPU_COSTMODEL=0`` keeps
    every scheduler on the pure-EWMA path (the pre-ISSUE-12 behavior)."""
    return os.environ.get("MMLSPARK_TPU_COSTMODEL", "1") != "0"


def _row_features(row: dict) -> list[float] | None:
    """FeatureLog row → [1, bucket, batch, entity_kb, queue_depth,
    decode_steps, prefill_tokens, context_blocks, analytic_tflops,
    analytic_gb], or None when the row cannot price a batch (no batch /
    no target). The generation fields are v4+/v5-only and the analytic
    pair v6-only — all OPTIONAL: absent (older rows, services without
    them) they train as 0, so old logs keep fitting unchanged. The
    analytic pair is rescaled to Tflops/GB so its weights live in the
    same numeric range as the other columns (raw flops counts would
    dominate the ridge penalty)."""
    try:
        batch = float(row.get("batch") or 0)
        if batch <= 0:
            return None
        bucket = float(row.get("bucket") or bucket_of(int(batch)))
        ekb = float(row.get("entity_bytes") or 0.0) / 1024.0
        depth = float(row.get("queue_depth") or 0.0)
        decode_steps = float(row.get("decode_steps") or 0.0)
        prefill_tokens = float(row.get("prefill_tokens") or 0.0)
        context_blocks = float(row.get("context_blocks") or 0.0)
        analytic_tflops = float(row.get("analytic_flops") or 0.0) / 1e12
        analytic_gb = float(row.get("analytic_bytes") or 0.0) / 1e9
        return [1.0, bucket, batch, ekb, depth, decode_steps,
                prefill_tokens, context_blocks, analytic_tflops,
                analytic_gb]
    except (TypeError, ValueError):
        return None


class CostModel:
    """Per-(service, route) ridge regression predicting ``execute_ms``.

    Keys are ``(service, route)`` plus a ``(service, "")`` aggregate
    trained on every row of the service — batch-level pricing (the
    scheduler's close decision) uses the aggregate; per-route pricing
    falls back to it when the route is unseen.
    """

    def __init__(self, min_rows: int = 64, ridge: float = 1e-3,
                 error_gate: float = 0.5, error_alpha: float = 0.2,
                 refresh_every: int = 64, registry=None):
        reg = registry if registry is not None else _default_registry
        self.min_rows = int(min_rows)
        self.ridge = float(ridge)
        self.error_gate = float(error_gate)
        self.error_alpha = float(error_alpha)
        self.refresh_every = int(refresh_every)
        self._lock = threading.Lock()
        # (service, route) -> {"theta": ndarray, "mean": ndarray,
        #                      "n": int, "train_mae_ms": float}
        self._models: dict[tuple[str, str], dict] = {}
        self._err: dict[str, float] = {}    # EWMA |pred - actual| ms
        self._act: dict[str, float] = {}    # EWMA actual ms
        self._gated: dict[str, bool] = {}   # last gate state (flip log)
        self._last_fit_total = -1           # feature_log.total_recorded
        self._c_fallback = reg.counter(
            "sched_costmodel_fallback_total",
            "cost-model refusals answered by the EWMA instead, by "
            "service/reason (cold | error)")
        self._c_skipped = reg.counter(
            "sched_costmodel_skipped_rows_total",
            "FeatureLog rows the trainer skipped, by reason "
            "(schema | bad)")
        # the history plane's Recorder ticks every sched_-prefixed
        # sample into the time-series store, so this error gauge (and
        # the scheduler's sched_costmodel_error_ms histogram, which the
        # regression sentinel's cost-model watch CUSUMs) gets a
        # queryable drift trajectory for free — /debug/timeline shows
        # the scheduler being priced progressively wrong
        self._g_mae = reg.gauge(
            "sched_costmodel_mae_ms",
            "EWMA absolute prediction error ms, by service")
        self._g_rows = reg.gauge(
            "sched_costmodel_train_rows",
            "rows behind the fitted model, by service")

    # -- training ----------------------------------------------------------
    def fit(self, rows: list[dict]) -> int:
        """Fit from FeatureLog-shaped rows. Returns the rows used.
        Rows with a missing/mismatched ``schema_version`` are skipped
        LOUDLY (counted ``reason="schema"``, warned once per fit) —
        old logs fall back to the EWMA, they are never misparsed."""
        by_key: dict[tuple[str, str], list[tuple[list, float]]] = {}
        skipped_schema = skipped_bad = 0
        for row in rows:
            if row.get("schema_version") not in ACCEPTED_SCHEMA_VERSIONS:
                skipped_schema += 1
                continue
            try:
                y = float(row.get("execute_ms"))
            except (TypeError, ValueError):
                skipped_bad += 1
                continue
            x = _row_features(row)
            if x is None or not math.isfinite(y) or y < 0:
                skipped_bad += 1
                continue
            svc = str(row.get("service") or "")
            route = str(row.get("route") or "")
            by_key.setdefault((svc, ""), []).append((x, y))
            if route:
                by_key.setdefault((svc, route), []).append((x, y))
        if skipped_schema:
            self._c_skipped.inc(skipped_schema, reason="schema")
            _LOG.warning(
                "cost model skipped %d FeatureLog rows with schema_version"
                " not in %s (old log format — retrain from fresh traffic)",
                skipped_schema, sorted(ACCEPTED_SCHEMA_VERSIONS))
        if skipped_bad:
            self._c_skipped.inc(skipped_bad, reason="bad")
        used = 0
        fitted: dict[tuple[str, str], dict] = {}
        for key, pairs in by_key.items():
            # per-key floor: a route with 3 rows must not pretend to a
            # model; the service aggregate covers it meanwhile
            floor = self.min_rows if key[1] == "" else \
                max(self.min_rows // 2, 8)
            if len(pairs) < floor:
                continue
            X = np.asarray([p[0] for p in pairs], np.float64)
            y = np.asarray([p[1] for p in pairs], np.float64)
            d = X.shape[1]
            try:
                theta = np.linalg.solve(
                    X.T @ X + self.ridge * np.eye(d), X.T @ y)
            except np.linalg.LinAlgError:
                continue
            pred = X @ theta
            fitted[key] = {
                "theta": theta,
                "mean": X.mean(axis=0),
                "n": len(pairs),
                "train_mae_ms": float(np.mean(np.abs(pred - y))),
            }
            if key[1] == "":
                used += len(pairs)
                self._g_rows.set(len(pairs), service=key[0])
        with self._lock:
            self._models.update(fitted)
            # a refit resets the gate's error evidence for the services
            # it re-learned: while gated the model never predicts, so
            # the error EWMA that tripped the gate cannot update — if
            # actuals DROPPED (e.g. a warm path made batches faster)
            # the frozen error would hold the gate shut forever even
            # though every refit is accurate. Fresh fit → fresh trial;
            # a still-bad model rebuilds its error and re-trips (each
            # flip is logged).
            for svc in {k[0] for k in fitted}:
                self._err.pop(svc, None)
        return used

    def maybe_refresh(self, log=None, min_new: int | None = None) -> int:
        """Refit from the live FeatureLog when at least ``min_new``
        rows landed since the last fit (the online-refresh loop —
        ``ServiceTimeEstimator.observe`` calls this periodically).
        Returns rows used (0 = no refit)."""
        log = log if log is not None else _feature_log
        min_new = self.refresh_every if min_new is None else min_new
        total = getattr(log, "total_recorded", None)
        if total is None:
            total = len(log)
        if self._last_fit_total >= 0 and \
                total - self._last_fit_total < min_new:
            return 0
        rows = log.snapshot()
        if not rows:
            return 0
        self._last_fit_total = total
        return self.fit(rows)

    # -- prediction --------------------------------------------------------
    def _usable_model(self, svc: str, route: str,
                      count: bool) -> dict | None:
        """Route-then-aggregate model lookup + the gate check, with the
        loud fallback counting (``cold`` / ``error``) in ONE place —
        batch and per-item pricing must never diverge on gating."""
        with self._lock:
            m = self._models.get((svc, route)) if route else None
            if m is None:
                m = self._models.get((svc, ""))
            if m is None:
                if count:
                    self._c_fallback.inc(1, service=svc, reason="cold")
                return None
            gated = self._gate_locked(svc)
        if gated:
            if count:
                self._c_fallback.inc(1, service=svc, reason="error")
            return None
        return m

    def predict_batch_ms(self, service: str, batch: int,
                         route: str = "", entity_bytes: float | None = None,
                         queue_depth: float | None = None,
                         decode_steps: float | None = None,
                         prefill_tokens: float | None = None,
                         context_blocks: float | None = None,
                         count: bool = True) -> float | None:
        """Predicted ``execute_ms`` for a batch, or ``None`` when the
        model is cold for this service or its recent error exceeds the
        gate — the caller MUST fall back to its EWMA then. ``count=False``
        suppresses the fallback counters (error bookkeeping reads).
        ``decode_steps``/``prefill_tokens`` price a generation request's
        two phases separately and ``context_blocks`` its resident
        KV-chain length (services whose rows record them); omitted, the
        service's training mean fills in."""
        batch = int(batch)
        if batch <= 0:
            return None
        m = self._usable_model(str(service), route, count)
        if m is None:
            return None
        mean = m["mean"]
        feats = [
            1.0,
            float(bucket_of(batch)),
            float(batch),
            mean[3] if entity_bytes is None else
            float(entity_bytes) / 1024.0,
            mean[4] if queue_depth is None else float(queue_depth),
        ]
        # a model persisted before the v4 generation features has a
        # 5-dim theta (pre-v5: 7-dim, pre-v6: 8-dim); only append what
        # it was trained with
        if len(m["theta"]) > 5:
            feats.append(mean[5] if decode_steps is None
                         else float(decode_steps))
            feats.append(mean[6] if prefill_tokens is None
                         else float(prefill_tokens))
        if len(m["theta"]) > 7:
            feats.append(mean[7] if context_blocks is None
                         else float(context_blocks))
        if len(m["theta"]) > 8:
            # the v6 analytic pair has no request-time override — the
            # service's training mean (its compiled programs' cost)
            # always fills in
            feats.append(mean[8])
            feats.append(mean[9])
        x = np.asarray(feats, np.float64)
        ms = float(x @ m["theta"])
        # a linear extrapolation can dip negative off the training
        # range; a non-positive service time is never a usable price
        return max(ms, 1e-3)

    def predict_item_ms(self, service: str, route: str = "",
                        count: bool = False) -> float | None:
        """Average per-item cost at the service's observed operating
        point: the predicted batch cost AT the training-mean batch,
        divided by that batch — the same semantic as the EWMA's
        per-item series (seconds / batch_size averaged over observed
        batches). Deliberately NOT the cost of a batch of one: its
        intercept (fixed dispatch cost the real batches amortize) would
        inflate Little's-law drain estimates by the batching factor and
        shed healthy traffic."""
        m = self._usable_model(str(service), route, count)
        if m is None:
            return None
        ms = float(np.asarray(m["mean"], np.float64) @ m["theta"])
        mean_batch = max(float(m["mean"][2]), 1.0)
        return max(ms, 1e-3) / mean_batch

    def ready(self, service: str, route: str = "") -> bool:
        return self.predict_batch_ms(service, 1, route=route,
                                     count=False) is not None

    # -- the error gate ----------------------------------------------------
    def observe(self, service: str, predicted_ms: float | None,
                actual_ms: float) -> None:
        """Fold one (prediction, observation) pair into the gate's
        error EWMA (``predicted_ms=None`` still trains the actual-
        magnitude EWMA, so recovery is possible while gated)."""
        svc = str(service)
        a = self.error_alpha
        with self._lock:
            cur_a = self._act.get(svc)
            self._act[svc] = actual_ms if cur_a is None else \
                a * actual_ms + (1 - a) * cur_a
            if predicted_ms is not None:
                err = abs(float(predicted_ms) - float(actual_ms))
                cur_e = self._err.get(svc)
                self._err[svc] = err if cur_e is None else \
                    a * err + (1 - a) * cur_e
            mae = self._err.get(svc)
            gated = self._gate_locked(svc)
            flipped = gated != self._gated.get(svc, False)
            self._gated[svc] = gated
        if mae is not None:
            self._g_mae.set(mae, service=svc)
        if flipped:
            # LOUD on every flip: an operator must see the scheduler
            # change pricing brains, in the log and in the counter above
            if gated:
                _LOG.warning(
                    "cost model GATED for service %r (EWMA error %.3f ms"
                    " > %.0f%% of recent actual) — scheduler falls back "
                    "to the per-bucket EWMA until the error recovers",
                    svc, mae or 0.0, self.error_gate * 100)
            else:
                _LOG.warning("cost model UNGATED for service %r — "
                             "predictions price admission again", svc)

    def _gate_locked(self, svc: str) -> bool:
        err, act = self._err.get(svc), self._act.get(svc)
        if err is None or act is None:
            return False  # no evidence against the model yet
        return err > self.error_gate * max(act, 1e-6)

    def mae_ms(self, service: str) -> float | None:
        with self._lock:
            return self._err.get(str(service))

    # -- persistence -------------------------------------------------------
    def save(self, path: str | None = None) -> str:
        """Write the fitted parameters as JSON (atomic tmp+replace)."""
        path = path or model_path()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with self._lock:
            models = [{
                "service": k[0], "route": k[1],
                "theta": [float(v) for v in m["theta"]],
                "mean": [float(v) for v in m["mean"]],
                "n": int(m["n"]),
                "train_mae_ms": float(m["train_mae_ms"]),
            } for k, m in sorted(self._models.items())]
        payload = {"version": MODEL_VERSION,
                   "schema_version": FEATURE_SCHEMA_VERSION,
                   "features": list(FEATURES), "models": models}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def load_file(self, path: str | None = None) -> int:
        """Load previously fitted parameters. A version or feature-
        schema mismatch raises — a persisted model from an older row
        schema must not price traffic silently."""
        path = path or model_path()
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        if payload.get("version") != MODEL_VERSION or \
                payload.get("schema_version") not in \
                ACCEPTED_SCHEMA_VERSIONS:
            raise ValueError(
                f"cost-model file {path!r} has version="
                f"{payload.get('version')} schema_version="
                f"{payload.get('schema_version')}; this build expects "
                f"({MODEL_VERSION}, {sorted(ACCEPTED_SCHEMA_VERSIONS)})"
                " — rebuild it from fresh FeatureLog traffic")
        loaded = {}
        for m in payload.get("models", ()):
            loaded[(str(m["service"]), str(m["route"]))] = {
                "theta": np.asarray(m["theta"], np.float64),
                "mean": np.asarray(m["mean"], np.float64),
                "n": int(m["n"]),
                "train_mae_ms": float(m["train_mae_ms"]),
            }
        with self._lock:
            self._models.update(loaded)
        return len(loaded)


# ------------------------------------------------- process-wide instance
_shared: CostModel | None = None
_shared_lock = threading.Lock()


def shared_cost_model() -> CostModel:
    """THE process-wide cost model (``RequestScheduler`` attaches it to
    its estimator). First call warm-boots from :func:`model_path` when
    a persisted model exists — a rebooted server prices with last
    boot's parameters until live traffic retrains them."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = CostModel()
            path = model_path()
            if os.path.exists(path):
                try:
                    n = _shared.load_file(path)
                    _LOG.info("cost model warm-booted %d fitted keys "
                              "from %s", n, path)
                except Exception:
                    _LOG.warning("persisted cost model at %s unusable — "
                                 "starting cold", path, exc_info=True)
        return _shared


# ------------------------------------------- AOT build-planner priority
def bucket_build_priority(service: str, buckets, log=None,
                          model: CostModel | None = None) -> list[int]:
    """Order padding buckets by predicted traffic value — observed
    request share × predicted execute cost — most valuable first, so an
    interrupted or time-boxed AOT build covers the hot path before the
    long tail (``core.aot.build_registered`` consults this).

    Returns ``[]`` when the FeatureLog holds no rows for the service —
    the caller keeps its deterministic ascending order then."""
    log = log if log is not None else _feature_log
    counts: dict[int, int] = {}
    for row in log.snapshot():
        if str(row.get("service") or "") != service:
            continue
        try:
            b = int(row.get("bucket") or
                    bucket_of(int(row.get("batch") or 0)))
        except (TypeError, ValueError):
            continue
        if b > 0:
            counts[b] = counts.get(b, 0) + 1
    if not counts:
        return []
    total = float(sum(counts.values()))
    model = model or shared_cost_model()

    def value(b: int) -> float:
        share = counts.get(b, 0) / total
        # predicted cost weights the share; a cold model degrades to
        # the padded size itself (bigger buckets cost more to compile
        # AND to serve — still a sane proxy)
        ms = model.predict_batch_ms(service, b, count=False)
        return share * (ms if ms is not None else float(b))

    return sorted({int(b) for b in buckets}, key=lambda b: (-value(b), b))
