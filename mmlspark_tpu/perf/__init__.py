"""Learned-performance subsystem (ISSUE 12): the consumers of the
telemetry PR 6 built.

- :mod:`.costmodel` — a numpy ridge regression over FeatureLog rows
  that replaces the scheduler's per-bucket EWMA (behind a loud
  fallback gate), feeds the autoscaler's capacity prediction, and
  orders the AOT build by predicted traffic value.
- :mod:`.autotune` — the offline TVM-style tile search for the Pallas
  kernels, persisting winners the kernels consult at call time.

Import is stdlib + numpy + obs/sched only — no JAX, no device (the CI
smoke asserts it). See docs/perf.md.
"""

from .costmodel import (CostModel, bucket_build_priority, enabled,
                        model_path, perf_root, shared_cost_model)
from . import autotune

__all__ = ["CostModel", "bucket_build_priority", "enabled",
           "model_path", "perf_root", "shared_cost_model", "autotune"]
