"""Offline autotuner for the Pallas kernels (ISSUE 12).

TVM-style search (arXiv:1802.04799), scoped to the kernels this repo
hand-tuned: the flash-attention forward's ``block_q × block_k`` tiles
(``dl/pallas_attention.py`` ships 256/auto), the GBDT histogram's
``feat_block × block_rows`` tiles (``lightgbm/pallas_hist.py`` ships
8/2048), and the paged decode attention's ``block_kv × slots_tile``
(``dl/pallas_paged_attention.py`` ships block_len/1; ISSUE 18). The
tuner

- enumerates a DETERMINISTIC candidate grid respecting the same VMEM
  budget logic the kernels encode (``_resolve_block_k``'s per-block
  byte budget and hard 2048 cap; the histogram's block byte ceiling),
- measures REAL wall clock per config (best-of-``reps`` after a
  warmup/compile pass; the measure fn is injectable so tests feed
  synthetic timings),
- discards anything that fails to compile or times non-finite — a
  broken config can never become a winner
  (``perf_autotune_discarded_total{reason=error|nonfinite}``),
- persists winners keyed by ``(kernel, shape-bucket, platform)`` to a
  JSON registry under :func:`~.costmodel.perf_root` that the kernels
  consult at call time — serving boots with measured-best tiles,
  never search-at-request-time.

Determinism: same candidate grid + same measured timings → the same
winner file, byte for byte (ties break on candidate order, the file is
written sorted).

The in-process winner table (:func:`kernel_winner`) is a PLAIN dict
read — no lock, no IO, no clock — because the kernels consult it at
jit trace time, where any of those is a trace-safety hazard
(graftcheck gates them). :func:`load` populates it (automatically at
import when a registry file exists) and :func:`_search` updates it.

CLI::

    python -m mmlspark_tpu.perf.autotune attention --t 2048 --d 64
    python -m mmlspark_tpu.perf.autotune hist --rows 65536 \
        --features 32 --bins 64
    python -m mmlspark_tpu.perf.autotune paged --context 4096 \
        --block-len 128 --heads 8 --d 64
    python -m mmlspark_tpu.perf.autotune list

Module import is stdlib + numpy + obs/sched only (no JAX); the measure
functions import JAX lazily.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time

from ..obs import registry as _default_registry
from ..sched.policy import bucket_of
from .costmodel import perf_root

_LOG = logging.getLogger("mmlspark_tpu.perf")

__all__ = ["registry_path", "attn_key", "hist_key", "paged_key",
           "kernel_winner", "lookup_stats", "clear", "load",
           "maybe_load", "save", "attention_candidates",
           "hist_candidates", "paged_candidates", "tune_attention",
           "tune_hist", "tune_paged_attention"]

REGISTRY_VERSION = 1

# candidate grids (deterministic order — ties resolve to the earlier
# entry, so the winner file is a pure function of the timings)
_ATTN_BQ = (128, 256, 512)
_ATTN_BK = (256, 512, 1024, 2048)
_HIST_FB = (8, 16)
_HIST_BR = (512, 1024, 2048, 4096)
_PAGED_BKV = (128, 256, 512, 1024, 2048)
_PAGED_ST = (1, 2, 4, 8)

# histogram per-cell VMEM ceiling for candidate filtering: bins block
# (fb × br i32) + vals block (3 × br f32) + output (fb × 3 × bins f32),
# double-buffered headroom left out of a ~16 MiB VMEM
_HIST_VMEM_BYTES = 6 * 1024 * 1024


def registry_path() -> str:
    return os.environ.get("MMLSPARK_TPU_TUNE_STORE") or \
        os.path.join(perf_root(), "autotune.json")


def attn_key(T: int, D: int, causal: bool = False) -> str:
    """Shape bucket for attention: sequence length rounded to its
    power-of-two bucket (one winner serves the whole padded bucket,
    mirroring serving's padding discipline), head dim exact."""
    return f"T{bucket_of(int(T))}-D{int(D)}-c{int(bool(causal))}"


def hist_key(n: int, F: int, num_bins: int) -> str:
    return f"n{bucket_of(int(n))}-F{int(F)}-B{int(num_bins)}"


def paged_key(context: int, D: int, w: int = 1) -> str:
    """Shape bucket for paged decode attention: resident context
    (``max_blocks × block_len``) rounded to its power-of-two bucket —
    one winner serves every table size padding into it — head dim and
    verify-window width exact (w=1 plain decode, w=k+1 speculative)."""
    return f"L{bucket_of(int(context))}-D{int(D)}-w{int(w)}"


# ------------------------------------------------- in-process winner table
_WINNERS: dict[str, dict] = {}
_lookup_hits: dict[str, int] = {}
_lookup_misses: dict[str, int] = {}


def kernel_winner(kernel: str, shape_key: str,
                  platform: str) -> dict | None:
    """The call-time consult: a plain dict read (trace-safe — kernels
    call this while being traced). ``None`` = untuned shape, the kernel
    keeps its default tiles. Hit/miss tallies are lock-free dict bumps
    (GIL-atomic, same discipline as ``CompileTracker``)."""
    w = _WINNERS.get(f"{kernel}|{shape_key}|{platform}")
    if w is not None:
        _lookup_hits[kernel] = _lookup_hits.get(kernel, 0) + 1
    else:
        _lookup_misses[kernel] = _lookup_misses.get(kernel, 0) + 1
    return w


def lookup_stats() -> dict:
    return {"hits": dict(_lookup_hits), "misses": dict(_lookup_misses)}


def clear() -> None:
    """Drop the in-process table (tests)."""
    _WINNERS.clear()
    _lookup_hits.clear()
    _lookup_misses.clear()


def load(path: str | None = None) -> int:
    """Replace the in-process table from a registry file."""
    path = path or registry_path()
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != REGISTRY_VERSION:
        raise ValueError(
            f"autotune registry {path!r} has version "
            f"{payload.get('version')}; expected {REGISTRY_VERSION}")
    winners = {str(k): dict(v)
               for k, v in payload.get("winners", {}).items()}
    _WINNERS.clear()
    _WINNERS.update(winners)
    return len(winners)


def maybe_load() -> int:
    """Best-effort boot load: absent registry → 0 winners, never an
    error (runs at module import so serving boots tuned)."""
    try:
        path = registry_path()
        if os.path.exists(path):
            n = load(path)
            _LOG.info("autotune registry loaded %d winners from %s",
                      n, path)
            return n
    except Exception:
        _LOG.warning("autotune registry load failed", exc_info=True)
    return 0


def save(path: str | None = None) -> str:
    """Persist the in-process table (atomic tmp+replace, sorted keys —
    identical winners produce an identical file)."""
    path = path or registry_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {"version": REGISTRY_VERSION,
               "winners": {k: _WINNERS[k] for k in sorted(_WINNERS)}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


# ------------------------------------------------------ candidate grids
def _attn_bk_budget(D: int, itemsize: int) -> int:
    """Mirror of ``pallas_attention._resolve_block_k``'s per-block K
    budget (imported from the kernel when JAX is importable, so the two
    can never drift silently; the literal fallback keeps candidate
    enumeration JAX-free)."""
    try:
        from ..dl.pallas_attention import _AUTO_BK_BYTES
        budget = _AUTO_BK_BYTES
    except Exception:
        budget = 512 * 1024
    return budget // max(D * itemsize, 1) // 128 * 128


def attention_candidates(T: int, D: int, *, causal: bool = False,
                         itemsize: int = 4) -> list[dict]:
    """The ``block_q × block_k`` grid for one attention shape,
    respecting the kernel's own VMEM logic: k-blocks are 128-multiples
    within the per-block byte budget and the hard 2048 cap (the fused
    backward's score blocks), and no block exceeds the padded row."""
    tq = max(-(-int(T) // 8) * 8, 8)
    tk = max(-(-int(T) // 128) * 128, 128)
    bk_cap = min(_attn_bk_budget(D, itemsize), 2048)
    seen, out = set(), []
    for bq in _ATTN_BQ:
        bq_eff = min(bq, tq)
        for bk in _ATTN_BK:
            if bk > bk_cap:
                continue
            bk_eff = min(bk, tk)
            cfg = (bq_eff, bk_eff)
            if cfg in seen:
                continue
            seen.add(cfg)
            out.append({"block_q": bq_eff, "block_k": bk_eff})
    return out


def paged_candidates(context: int, block_len: int, heads: int,
                     head_dim: int, *, w: int = 1,
                     itemsize: int = 4) -> list[dict]:
    """The ``block_kv × slots_tile`` grid for one paged-decode shape.
    ``block_kv`` is the score-chunk width inside one pool block — the
    same per-chunk K-byte budget and hard 2048 cap as
    ``_resolve_block_k`` apply, and a chunk never exceeds ``block_len``
    (the kernel streams whole pool blocks; chunking past one is
    meaningless). ``slots_tile`` packs slots per parallel grid row —
    pure launch geometry, results invariant. The kernel's own default
    (whole block, one slot) is always candidate 0, so an untuned-equal
    winner is representable."""
    bl = max(int(block_len), 1)
    bkv_cap = min(_attn_bk_budget(head_dim, itemsize), 2048)
    seen, out = set(), []
    for bkv in (bl,) + _PAGED_BKV:
        if bkv > bkv_cap and bkv != bl:
            continue
        bkv_eff = max(min(bkv, bl), 1)
        for st in _PAGED_ST:
            cfg = (bkv_eff, st)
            if cfg in seen:
                continue
            seen.add(cfg)
            out.append({"block_kv": bkv_eff, "slots_tile": st})
    return out


def hist_candidates(n: int, F: int, num_bins: int) -> list[dict]:
    """The ``feat_block × block_rows`` grid for one histogram shape,
    filtered by the per-cell VMEM ceiling and capped at one row block
    past the data (bigger just pads)."""
    out = []
    for fb in _HIST_FB:
        for br in _HIST_BR:
            if br > 2 * max(int(n), _HIST_BR[0]):
                continue
            cell = (fb * br + 3 * br + fb * 3 * int(num_bins)) * 4
            if cell > _HIST_VMEM_BYTES:
                continue
            out.append({"feat_block": fb, "block_rows": br})
    return out


# ------------------------------------------------------- measurement
def current_platform() -> str:
    try:
        from ..utils.platform import target_platform
        return target_platform()
    except Exception:
        return "cpu"


def _time_best(run, reps: int) -> float:
    """Best-of-``reps`` wall ms after one warmup (compile) pass — the
    same min-of-runs discipline bench.py uses: the minimum is the
    deterministic floor, contention only ever adds."""
    run()  # warmup: compile happens here; a broken config raises here
    best = math.inf
    for _ in range(max(int(reps), 1)):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def measure_attention(config: dict, *, T: int, D: int,
                      causal: bool = False, batch: int = 1,
                      heads: int = 1, reps: int = 3, seed: int = 0,
                      interpret: bool | None = None) -> float:
    """Real wall-clock ms for one (block_q, block_k) config on
    deterministic inputs (seeded). Raises on compile failure — the
    search discards such configs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..dl.pallas_attention import flash_attention

    rng = np.random.default_rng(seed)
    shape = (batch, heads, T, D)
    q = jnp.asarray(rng.normal(size=shape), jnp.float32)
    k = jnp.asarray(rng.normal(size=shape), jnp.float32)
    v = jnp.asarray(rng.normal(size=shape), jnp.float32)
    mask = jnp.ones((batch, T), bool)

    def run():
        out = flash_attention(
            q, k, v, key_mask=mask, block_q=int(config["block_q"]),
            block_k=int(config["block_k"]), causal=causal,
            interpret=interpret, bwd_impl="blockwise")
        jax.block_until_ready(out)

    return _time_best(run, reps)


def measure_paged_attention(config: dict, *, context: int,
                            block_len: int, heads: int, head_dim: int,
                            w: int = 1, slots: int = 4, reps: int = 3,
                            seed: int = 0,
                            interpret: bool | None = None) -> float:
    """Real wall-clock ms for one (block_kv, slots_tile) config:
    ``slots`` full chains of ``context // block_len`` pool blocks,
    deterministic inputs (seeded). Raises on compile failure — the
    search discards such configs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..dl.pallas_paged_attention import paged_window_attention

    mb = max(int(context) // max(int(block_len), 1), 1)
    nb = slots * mb + 1  # + the TRASH_BLOCK scratch row
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(slots, heads, w, head_dim)),
                    jnp.float32)
    kp = jnp.asarray(rng.normal(
        size=(nb, block_len, heads, head_dim)), jnp.float32)
    vp = jnp.asarray(rng.normal(
        size=(nb, block_len, heads, head_dim)), jnp.float32)
    rows = jnp.asarray(
        1 + np.arange(slots * mb).reshape(slots, mb), jnp.int32)
    pos = jnp.full((slots,), mb * int(block_len) - w, jnp.int32)
    impl = "pallas" if interpret else None

    def run():
        out = paged_window_attention(
            q, kp, vp, rows, pos, block_kv=int(config["block_kv"]),
            slots_tile=int(config["slots_tile"]), impl=impl,
            interpret=interpret)
        jax.block_until_ready(out)

    return _time_best(run, reps)


def measure_hist(config: dict, *, n: int, F: int, num_bins: int,
                 reps: int = 3, seed: int = 0,
                 interpret: bool | None = None) -> float:
    import jax
    import numpy as np

    from ..lightgbm.pallas_hist import hist_pallas, use_pallas_hist

    if interpret is None:
        interpret = not use_pallas_hist()
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, num_bins, size=(n, F)).astype(np.int32)
    vals = rng.normal(size=(n, 3)).astype(np.float32)

    def run():
        out = hist_pallas(
            bins, vals, num_bins=int(num_bins),
            block_rows=int(config["block_rows"]),
            feat_block=int(config["feat_block"]), interpret=interpret)
        jax.block_until_ready(out)

    return _time_best(run, reps)


# ----------------------------------------------------------- the search
def _search(kernel: str, shape_key: str, candidates: list[dict],
            measure, *, platform: str, registry=None,
            persist: bool = True, path: str | None = None) -> dict:
    """Measure every candidate, keep the fastest VALID one, persist it.
    A config that raises (compile failure) or times non-finite/zero is
    discarded and can never be persisted as a winner; ties break on
    candidate order so the registry is a pure function of the
    timings."""
    reg = registry if registry is not None else _default_registry
    c_trials = reg.counter(
        "perf_autotune_trials_total",
        "autotuner configs measured, by kernel")
    c_disc = reg.counter(
        "perf_autotune_discarded_total",
        "autotuner configs discarded, by kernel/reason "
        "(error | nonfinite)")
    c_win = reg.counter(
        "perf_autotune_winners_total",
        "winner entries recorded, by kernel")
    valid: list[tuple[float, int, dict]] = []
    trials = []
    for i, cfg in enumerate(candidates):
        c_trials.inc(1, kernel=kernel)
        try:
            ms = float(measure(cfg))
        except Exception as e:
            _LOG.warning("autotune %s %s: config %s DISCARDED "
                         "(failed: %s)", kernel, shape_key, cfg, e)
            c_disc.inc(1, kernel=kernel, reason="error")
            trials.append({**cfg, "ms": None, "discarded": "error"})
            continue
        if not math.isfinite(ms) or ms <= 0:
            _LOG.warning("autotune %s %s: config %s DISCARDED "
                         "(non-finite timing %r)", kernel, shape_key,
                         cfg, ms)
            c_disc.inc(1, kernel=kernel, reason="nonfinite")
            trials.append({**cfg, "ms": None, "discarded": "nonfinite"})
            continue
        trials.append({**cfg, "ms": round(ms, 4)})
        valid.append((ms, i, cfg))
    record = {"kernel": kernel, "key": shape_key, "platform": platform,
              "trials": trials, "candidates": len(candidates),
              "valid": len(valid), "winner": None}
    if not valid:
        _LOG.warning("autotune %s %s: NO valid config — nothing "
                     "persisted, kernel keeps its defaults",
                     kernel, shape_key)
        return record
    ms, _, cfg = min(valid, key=lambda r: (r[0], r[1]))
    entry = dict(cfg)
    entry["ms"] = round(ms, 4)
    _WINNERS[f"{kernel}|{shape_key}|{platform}"] = entry
    c_win.inc(1, kernel=kernel)
    record["winner"] = entry
    if persist:
        record["path"] = save(path)
    return record


def tune_attention(T: int, D: int, *, causal: bool = False,
                   batch: int = 1, heads: int = 1, reps: int = 3,
                   seed: int = 0, platform: str | None = None,
                   measure=None, interpret: bool | None = None,
                   persist: bool = True, path: str | None = None,
                   registry=None) -> dict:
    platform = platform or current_platform()
    cands = attention_candidates(T, D, causal=causal)
    meas = measure or (lambda cfg: measure_attention(
        cfg, T=T, D=D, causal=causal, batch=batch, heads=heads,
        reps=reps, seed=seed, interpret=interpret))
    return _search("flash_attention", attn_key(T, D, causal), cands,
                   meas, platform=platform, registry=registry,
                   persist=persist, path=path)


def tune_paged_attention(context: int, block_len: int, heads: int,
                         head_dim: int, *, w: int = 1, slots: int = 4,
                         reps: int = 3, seed: int = 0,
                         platform: str | None = None, measure=None,
                         interpret: bool | None = None,
                         persist: bool = True, path: str | None = None,
                         registry=None) -> dict:
    platform = platform or current_platform()
    cands = paged_candidates(context, block_len, heads, head_dim, w=w)
    meas = measure or (lambda cfg: measure_paged_attention(
        cfg, context=context, block_len=block_len, heads=heads,
        head_dim=head_dim, w=w, slots=slots, reps=reps, seed=seed,
        interpret=interpret))
    return _search("paged_attn", paged_key(context, head_dim, w),
                   cands, meas, platform=platform, registry=registry,
                   persist=persist, path=path)


def tune_hist(n: int, F: int, num_bins: int, *, reps: int = 3,
              seed: int = 0, platform: str | None = None,
              measure=None, interpret: bool | None = None,
              persist: bool = True, path: str | None = None,
              registry=None) -> dict:
    platform = platform or current_platform()
    cands = hist_candidates(n, F, num_bins)
    meas = measure or (lambda cfg: measure_hist(
        cfg, n=n, F=F, num_bins=num_bins, reps=reps, seed=seed,
        interpret=interpret))
    return _search("hist", hist_key(n, F, num_bins), cands, meas,
                   platform=platform, registry=registry,
                   persist=persist, path=path)


# ------------------------------------------------------------------- CLI
def _cli(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.perf.autotune",
        description="Offline Pallas-kernel autotuner: measure tile "
                    "configs, persist winners the kernels load at "
                    "call time")
    sub = ap.add_subparsers(dest="cmd", required=True)
    at = sub.add_parser("attention", help="tune flash-attention tiles")
    at.add_argument("--t", type=int, required=True)
    at.add_argument("--d", type=int, required=True)
    at.add_argument("--causal", action="store_true")
    at.add_argument("--batch", type=int, default=1)
    at.add_argument("--heads", type=int, default=1)
    hi = sub.add_parser("hist", help="tune GBDT-histogram tiles")
    hi.add_argument("--rows", type=int, required=True)
    hi.add_argument("--features", type=int, required=True)
    hi.add_argument("--bins", type=int, required=True)
    pg = sub.add_parser("paged",
                        help="tune paged-decode-attention tiles")
    pg.add_argument("--context", type=int, required=True)
    pg.add_argument("--block-len", type=int, required=True)
    pg.add_argument("--heads", type=int, required=True)
    pg.add_argument("--d", type=int, required=True)
    pg.add_argument("--w", type=int, default=1,
                    help="query window width (1 = plain decode, "
                         "k+1 = speculative verify)")
    pg.add_argument("--slots", type=int, default=4)
    for p in (at, hi, pg):
        p.add_argument("--reps", type=int, default=3)
        p.add_argument("--seed", type=int, default=0)
        p.add_argument("--path", default=None,
                       help="registry file (default: "
                            "$MMLSPARK_TPU_TUNE_STORE or the per-user "
                            "perf root)")
        p.add_argument("--interpret", action="store_true",
                       help="force the Pallas interpreter (off-TPU "
                            "smoke; timings are NOT device-"
                            "representative)")
    ls = sub.add_parser("list", help="print registry winners")
    ls.add_argument("--path", default=None)
    args = ap.parse_args(argv)

    if args.cmd == "list":
        path = args.path or registry_path()
        if os.path.exists(path):
            load(path)
        for key in sorted(_WINNERS):
            print(f"{key}: {json.dumps(_WINNERS[key], sort_keys=True)}")
        print(f"{len(_WINNERS)} winner(s) in {path}")
        return 0

    path = args.path or registry_path()
    if os.path.exists(path):
        load(path)  # accumulate into the existing registry
    interp = True if args.interpret else None
    if args.cmd == "attention":
        rec = tune_attention(args.t, args.d, causal=args.causal,
                             batch=args.batch, heads=args.heads,
                             reps=args.reps, seed=args.seed,
                             interpret=interp, path=path)
    elif args.cmd == "paged":
        rec = tune_paged_attention(args.context, args.block_len,
                                   args.heads, args.d, w=args.w,
                                   slots=args.slots, reps=args.reps,
                                   seed=args.seed, interpret=interp,
                                   path=path)
    else:
        rec = tune_hist(args.rows, args.features, args.bins,
                        reps=args.reps, seed=args.seed,
                        interpret=interp, path=path)
    print(json.dumps({k: v for k, v in rec.items() if k != "trials"},
                     indent=1, sort_keys=True))
    for t in rec["trials"]:
        print(f"  {t}")
    return 0 if rec["winner"] is not None else 1


# boot-time load: a registry built by the offline CLI is live for every
# kernel call in this process without any wiring (module-level, so the
# IO never runs inside a traced region)
maybe_load()


if __name__ == "__main__":  # pragma: no cover
    import sys as _sys
    # `-m` executes this file as __main__ (a second module object);
    # delegate to the canonical import so the CLI and any library code
    # in-process share one winner table (same trick as core.aot).
    from mmlspark_tpu.perf.autotune import _cli as _canonical_cli
    _sys.exit(_canonical_cli())
