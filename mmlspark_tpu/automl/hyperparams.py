"""Hyperparameter ranges and search spaces.

Reference ``automl/HyperparamBuilder.scala:11-111`` (``IntRangeHyperParam``,
``DoubleRangeHyperParam``, ``DiscreteHyperParam``) and
``automl/ParamSpace.scala:11-40`` (``GridSpace``, ``RandomSpace``).
"""

from __future__ import annotations

import itertools

import numpy as np


class DiscreteHyperParam:
    def __init__(self, values, seed: int = 0):
        self.values = list(values)
        self._rng = np.random.default_rng(seed)

    def grid(self):
        return list(self.values)

    def sample(self):
        return self.values[int(self._rng.integers(len(self.values)))]


class IntRangeHyperParam:
    def __init__(self, lo: int, hi: int, seed: int = 0):
        self.lo, self.hi = int(lo), int(hi)
        self._rng = np.random.default_rng(seed)

    def grid(self, n: int = 5):
        return sorted({int(v) for v in
                       np.linspace(self.lo, self.hi - 1, n)})

    def sample(self):
        return int(self._rng.integers(self.lo, self.hi))


class DoubleRangeHyperParam:
    def __init__(self, lo: float, hi: float, seed: int = 0):
        self.lo, self.hi = float(lo), float(hi)
        self._rng = np.random.default_rng(seed)

    def grid(self, n: int = 5):
        return list(np.linspace(self.lo, self.hi, n))

    def sample(self):
        return float(self._rng.uniform(self.lo, self.hi))


FloatRangeHyperParam = DoubleRangeHyperParam


class HyperparamBuilder:
    """(estimator, param-name) → range registry
    (reference ``HyperparamBuilder.addHyperparam``)."""

    def __init__(self):
        self._entries: list[tuple[object, str, object]] = []

    def addHyperparam(self, stage, param_name: str, dist):
        self._entries.append((stage, param_name, dist))
        return self

    def build(self):
        return list(self._entries)


class GridSpace:
    """Exhaustive cartesian product of grids."""

    def __init__(self, entries):
        self.entries = entries

    def param_maps(self):
        grids = [d.grid() for _, _, d in self.entries]
        for combo in itertools.product(*grids):
            yield [(s, name, v) for (s, name, _), v in
                   zip(self.entries, combo)]


class RandomSpace:
    """Random draws (reference ``RandomSpace.paramMaps`` iterator)."""

    def __init__(self, entries, seed: int = 0):
        import copy
        # every dist gets a COPY with a distinct stream derived from
        # this space's seed: dists default to their own seed=0, so
        # without the reseed, identically-constructed ranges draw in
        # lockstep and random search collapses onto the diagonal of the
        # cube. Copying keeps the caller's dists (and sibling spaces
        # over the same entries) untouched — seeded reproducibility
        # must not depend on construction order.
        reseeded = []
        for i, (stage, name, d) in enumerate(entries):
            if hasattr(d, "_rng"):
                d = copy.copy(d)
                d._rng = np.random.default_rng((seed, i))
            reseeded.append((stage, name, d))
        self.entries = reseeded

    def param_maps(self, n: int):
        for _ in range(n):
            yield [(s, name, d.sample()) for s, name, d in self.entries]
