"""TuneHyperparameters + FindBestModel.

Reference ``automl/TuneHyperparameters.scala:34-170``: random search across
(possibly several) estimators with k-fold cross-validation, evaluated in a
thread pool (:95-125); ``automl/FindBestModel.scala``: pick the best of
already-fitted models on an evaluation DataFrame.

The thread pool survives here (model fits release the GIL while XLA runs),
mirroring the reference's task-parallel sweep.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core import ComplexParam, DataFrame, Estimator, Model, Param, \
    TypeConverters as TC
from ..core.contracts import HasLabelCol
from ..train.statistics import classification_metrics, regression_metrics
from .hyperparams import RandomSpace


def _evaluate(model, df, label_col: str, metric: str) -> float:
    scored = model.transform(df)
    y = np.asarray(scored[label_col], np.float64)
    pred = np.asarray(scored["prediction"], np.float64)
    if metric in ("accuracy", "precision", "recall", "AUC"):
        scores = None
        if "probability" in scored.columns:
            p = np.asarray(scored["probability"])
            scores = p[:, -1] if p.ndim == 2 else p
        return classification_metrics(y, pred, scores)[metric]
    return regression_metrics(y, pred)[metric]


def _higher_better(metric: str) -> bool:
    return metric in ("accuracy", "precision", "recall", "AUC", "r^2")


class TuneHyperparameters(Estimator, HasLabelCol):
    models = ComplexParam("models", "estimators to sweep over")
    paramSpace = ComplexParam("paramSpace",
                              "HyperparamBuilder entries (see hyperparams)")
    evaluationMetric = Param("evaluationMetric", "metric to optimize",
                             TC.toString, default="accuracy")
    numFolds = Param("numFolds", "cross-validation folds", TC.toInt,
                     default=3)
    numRuns = Param("numRuns", "random-search draws", TC.toInt, default=10)
    parallelism = Param("parallelism", "concurrent fits", TC.toInt,
                        default=4)
    seed = Param("seed", "fold shuffling seed", TC.toInt, default=0)

    def _fit(self, df):
        metric = self.get("evaluationMetric")
        folds = self.get("numFolds")
        label = self.getLabelCol()
        n = len(df)
        rng = np.random.default_rng(self.get("seed"))
        perm = rng.permutation(n)
        fold_id = np.arange(n) % folds
        fold_of_row = np.empty(n, np.int64)
        fold_of_row[perm] = fold_id

        estimators = self.get("models")
        if not isinstance(estimators, (list, tuple)):
            estimators = [estimators]
        space = RandomSpace(self.get("paramSpace"), seed=self.get("seed"))
        candidates = []
        for est in estimators:
            for pm in space.param_maps(self.get("numRuns")):
                cand = est.copy()
                for stage, name, value in pm:
                    if type(stage) is type(est) and cand.has_param(name):
                        cand.set(name, value)
                candidates.append(cand)

        def run(cand):
            scores = []
            for f in range(folds):
                tr = df.filter(fold_of_row != f)
                te = df.filter(fold_of_row == f)
                m = cand.fit(tr)
                scores.append(_evaluate(m, te, label, metric))
            return float(np.mean(scores))

        with ThreadPoolExecutor(self.get("parallelism")) as pool:
            results = list(pool.map(run, candidates))

        best_idx = int(np.argmax(results) if _higher_better(metric)
                       else np.argmin(results))
        best = candidates[best_idx].fit(df)
        model = TuneHyperparametersModel(
            bestModel=best, bestMetric=float(results[best_idx]))
        self._copy_params_to(model)
        return model


class TuneHyperparametersModel(Model):
    bestModel = ComplexParam("bestModel", "winning fitted model")
    bestMetric = Param("bestMetric", "winning CV metric", TC.toFloat)

    def _transform(self, df):
        return self.get("bestModel").transform(df)


class FindBestModel(Estimator, HasLabelCol):
    """Reference ``automl/FindBestModel.scala``: evaluate fitted models on
    the given data; keep the best."""

    models = ComplexParam("models", "already-fitted models")
    evaluationMetric = Param("evaluationMetric", "metric", TC.toString,
                             default="accuracy")

    def _fit(self, df):
        metric = self.get("evaluationMetric")
        scores = [_evaluate(m, df, self.getLabelCol(), metric)
                  for m in self.get("models")]
        best_idx = int(np.argmax(scores) if _higher_better(metric)
                       else np.argmin(scores))
        model = TuneHyperparametersModel(
            bestModel=self.get("models")[best_idx],
            bestMetric=float(scores[best_idx]))
        self._copy_params_to(model)
        return model
