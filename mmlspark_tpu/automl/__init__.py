"""AutoML: hyperparameter sweeps + model selection.

Reference ``automl/`` (SURVEY §2.10): ``TuneHyperparameters`` (random
search over estimators with k-fold CV, thread-pool parallel),
``HyperparamBuilder``/``ParamSpace`` (typed ranges), ``FindBestModel``.
"""

from .hyperparams import (DiscreteHyperParam, DoubleRangeHyperParam,
                          FloatRangeHyperParam, HyperparamBuilder,
                          IntRangeHyperParam, GridSpace, RandomSpace)
from .defaults import default_range, defaultRange
from .tune import TuneHyperparameters, TuneHyperparametersModel, FindBestModel

__all__ = ["DiscreteHyperParam", "DoubleRangeHyperParam",
           "FloatRangeHyperParam", "HyperparamBuilder", "IntRangeHyperParam",
           "GridSpace", "RandomSpace", "TuneHyperparameters",
           "TuneHyperparametersModel", "FindBestModel",
           "default_range", "defaultRange"]
