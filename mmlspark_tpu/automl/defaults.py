"""Default hyperparameter search ranges per learner.

Reference ``automl/DefaultHyperparams.scala``: a canned, sensible search
space for each supported learner so ``TuneHyperparameters`` works out of
the box without hand-building ranges.
"""

from __future__ import annotations

from .hyperparams import (DoubleRangeHyperParam, HyperparamBuilder,
                          IntRangeHyperParam)


def default_range(estimator):
    """Built (stage, param, dist) entries for ``estimator``'s type —
    the reference's per-learner ``defaultRange`` overloads collapsed
    into one type dispatch."""
    def _is(cls_name: str) -> bool:
        # isinstance-style dispatch without importing every learner
        # package eagerly: match the class or any base by name, so
        # subclasses keep their parent's default space (the reference's
        # overload resolution is polymorphic too)
        return any(c.__name__ == cls_name
                   for c in type(estimator).__mro__)

    b = HyperparamBuilder()
    if _is("LogisticRegression"):
        return (b.addHyperparam(estimator, "regParam",
                                DoubleRangeHyperParam(0.001, 1.0))
                 .addHyperparam(estimator, "maxIter",
                                IntRangeHyperParam(20, 100))
                 .build())
    if any(_is(c) for c in ("LightGBMClassifier", "LightGBMRegressor",
                            "LightGBMRanker")):
        return (b.addHyperparam(estimator, "numLeaves",
                                IntRangeHyperParam(4, 64))
                 .addHyperparam(estimator, "numIterations",
                                IntRangeHyperParam(20, 100))
                 .addHyperparam(estimator, "learningRate",
                                DoubleRangeHyperParam(0.01, 0.3))
                 .addHyperparam(estimator, "baggingFraction",
                                DoubleRangeHyperParam(0.6, 1.0))
                 .build())
    if any(_is(c) for c in ("VowpalWabbitClassifier",
                            "VowpalWabbitRegressor")):
        return (b.addHyperparam(estimator, "learningRate",
                                DoubleRangeHyperParam(0.05, 1.0))
                 .addHyperparam(estimator, "numPasses",
                                IntRangeHyperParam(1, 10))
                 .addHyperparam(estimator, "l2",
                                DoubleRangeHyperParam(0.0, 1e-4))
                 .build())
    raise ValueError(
        f"no default hyperparameter range for "
        f"{type(estimator).__name__}; build one with "
        "HyperparamBuilder")


defaultRange = default_range
