"""Computer Vision services.

Reference ``cognitive/ComputerVision.scala`` — AnalyzeImage, OCR,
RecognizeText (async operation polling), DescribeImage, TagImage,
GenerateThumbnails, DSIR (celebrity/landmark models).
"""

from __future__ import annotations

import json
import time

from ..core import Param, ServiceParam, TypeConverters as TC
from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData, HTTPResponseData
from .base import _ImageInputService


class _Vision(_ImageInputService):
    _path = ""

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/vision/v2.0/{self._path}")


class AnalyzeImage(_Vision):
    _path = "analyze"
    visualFeatures = ServiceParam("visualFeatures",
                                  "Categories,Tags,Description,Faces,...")
    details = ServiceParam("details", "Celebrities,Landmarks")
    language = ServiceParam("language", "response language")

    def _url_params(self, df, row):
        vf = self._resolve("visualFeatures", df, row)
        det = self._resolve("details", df, row)
        return {"visualFeatures": ",".join(vf) if isinstance(
                    vf, (list, tuple)) else vf,
                "details": ",".join(det) if isinstance(
                    det, (list, tuple)) else det,
                "language": self._resolve("language", df, row)}


class DescribeImage(_Vision):
    _path = "describe"
    maxCandidates = ServiceParam("maxCandidates", "caption candidates")

    def _url_params(self, df, row):
        return {"maxCandidates": self._resolve("maxCandidates", df, row)}


class TagImage(_Vision):
    _path = "tag"


class OCR(_Vision):
    _path = "ocr"
    language = ServiceParam("language", "ocr language")
    detectOrientation = ServiceParam("detectOrientation",
                                     "auto-detect orientation")

    def _url_params(self, df, row):
        return {"language": self._resolve("language", df, row),
                "detectOrientation": self._resolve("detectOrientation",
                                                   df, row)}


class RecognizeDomainSpecificContent(_Vision):
    """DSIR (reference ``RecognizeDomainSpecificContent``): celebrity /
    landmark models."""
    model = ServiceParam("model", "celebrities | landmarks")

    def _build_request(self, df, row):
        model = self._resolve("model", df, row, "celebrities")
        self.set("url", self.get("url").replace("{model}", str(model))) \
            if "{model}" in self.get("url") else None
        return super()._build_request(df, row)

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/vision/v2.0/models/{{model}}/analyze")


class GenerateThumbnails(_Vision):
    _path = "generateThumbnail"
    width = ServiceParam("width", "thumbnail width")
    height = ServiceParam("height", "thumbnail height")
    smartCropping = ServiceParam("smartCropping", "smart crop")

    def _url_params(self, df, row):
        return {"width": self._resolve("width", df, row, 64),
                "height": self._resolve("height", df, row, 64),
                "smartCropping": self._resolve("smartCropping", df, row)}

    def _parse_response(self, resp: HTTPResponseData):
        return resp.entity  # binary thumbnail


class RecognizeText(_Vision):
    """Async text recognition: POST → Operation-Location → poll until
    done (reference ``RecognizeText`` with ``pollingDelay`` basic handler)."""
    _path = "recognizeText"
    mode = ServiceParam("mode", "Printed | Handwritten")
    pollingDelay = Param("pollingDelay", "seconds between polls",
                         TC.toFloat, default=0.3)
    maxPolls = Param("maxPolls", "poll attempts before giving up",
                     TC.toInt, default=20)

    def _url_params(self, df, row):
        return {"mode": self._resolve("mode", df, row, "Printed")}

    def _parse_response(self, resp: HTTPResponseData):
        op_url = resp.headers.get("Operation-Location") or \
            resp.headers.get("operation-location")
        if not op_url:
            return resp.json() if resp.entity else None
        key = None
        for k, v in resp.headers.items():
            if k.lower() == "x-request-key":
                key = v
        headers = {"Ocp-Apim-Subscription-Key": key} if key else {}
        for _ in range(self.get("maxPolls")):
            time.sleep(self.get("pollingDelay"))
            poll = send_request(HTTPRequestData(
                url=op_url, method="GET", headers=headers))
            body = poll.json() if poll.entity else {}
            if body.get("status") in ("Succeeded", "Failed"):
                return body
        return {"status": "TimedOut"}
