"""Computer Vision services.

Reference ``cognitive/ComputerVision.scala`` — AnalyzeImage, OCR,
RecognizeText (async operation polling), DescribeImage, TagImage,
GenerateThumbnails, DSIR (celebrity/landmark models).
"""

from __future__ import annotations

from ..core import ServiceParam
from ..io.http.schema import HTTPResponseData
from .base import _AsyncReplyMixin, _ImageInputService


class _Vision(_ImageInputService):
    _path = ""

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/vision/v2.0/{self._path}")


class AnalyzeImage(_Vision):
    _path = "analyze"
    visualFeatures = ServiceParam("visualFeatures",
                                  "Categories,Tags,Description,Faces,...")
    details = ServiceParam("details", "Celebrities,Landmarks")
    language = ServiceParam("language", "response language")

    def _url_params(self, df, row):
        vf = self._resolve("visualFeatures", df, row)
        det = self._resolve("details", df, row)
        return {"visualFeatures": ",".join(vf) if isinstance(
                    vf, (list, tuple)) else vf,
                "details": ",".join(det) if isinstance(
                    det, (list, tuple)) else det,
                "language": self._resolve("language", df, row)}


class DescribeImage(_Vision):
    _path = "describe"
    maxCandidates = ServiceParam("maxCandidates", "caption candidates")

    def _url_params(self, df, row):
        return {"maxCandidates": self._resolve("maxCandidates", df, row)}


class TagImage(_Vision):
    _path = "tag"


class OCR(_Vision):
    _path = "ocr"
    language = ServiceParam("language", "ocr language")
    detectOrientation = ServiceParam("detectOrientation",
                                     "auto-detect orientation")

    def _url_params(self, df, row):
        return {"language": self._resolve("language", df, row),
                "detectOrientation": self._resolve("detectOrientation",
                                                   df, row)}


class RecognizeDomainSpecificContent(_Vision):
    """DSIR (reference ``RecognizeDomainSpecificContent``): celebrity /
    landmark models."""
    model = ServiceParam("model", "celebrities | landmarks")

    def _build_request(self, df, row):
        model = self._resolve("model", df, row, "celebrities")
        self.set("url", self.get("url").replace("{model}", str(model))) \
            if "{model}" in self.get("url") else None
        return super()._build_request(df, row)

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/vision/v2.0/models/{{model}}/analyze")


class GenerateThumbnails(_Vision):
    _path = "generateThumbnail"
    width = ServiceParam("width", "thumbnail width")
    height = ServiceParam("height", "thumbnail height")
    smartCropping = ServiceParam("smartCropping", "smart crop")

    def _url_params(self, df, row):
        return {"width": self._resolve("width", df, row, 64),
                "height": self._resolve("height", df, row, 64),
                "smartCropping": self._resolve("smartCropping", df, row)}

    def _parse_response(self, resp: HTTPResponseData):
        return resp.entity  # binary thumbnail


class RecognizeText(_AsyncReplyMixin, _Vision):
    """Async text recognition: POST → Operation-Location → poll until
    done (reference ``RecognizeText``); shares the generic async-reply
    machinery with ``Read``."""
    _path = "recognizeText"
    mode = ServiceParam("mode", "Printed | Handwritten")

    def _url_params(self, df, row):
        return {"mode": self._resolve("mode", df, row, "Printed")}


class Read(_AsyncReplyMixin, _Vision):
    """The Read API (async OCR v3): POST → 202 + Operation-Location →
    poll until a terminal status (reference ``ComputerVision.scala:341+``
    — ``CognitiveServicesBaseNoHandler with HasAsyncReply``)."""

    _path = "read/analyze"
    language = ServiceParam(
        "language", "force processing as this BCP-47 language (en, nl, "
        "fr, de, it, pt, es); omit for auto-detection")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com/vision/"
                f"v3.1/read/analyze")

    def _url_params(self, df, row):
        return {"language": self._resolve("language", df, row)}

    @staticmethod
    def flatten(result: dict | None) -> str:
        """Reference ``object Read.flatten``: all recognized text lines
        joined into one string."""
        if not result:
            return ""
        reads = (result.get("analyzeResult") or {}).get("readResults", [])
        return " ".join(line.get("text", "")
                        for page in reads
                        for line in page.get("lines", []))
