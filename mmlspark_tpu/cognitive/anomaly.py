"""Anomaly Detector services.

Reference ``cognitive/AnamolyDetection.scala`` — ``DetectAnomalies``
(entire series) and ``DetectLastAnomaly`` (latest point), posting
{"series": [{timestamp, value}...], "granularity": ...}.
"""

from __future__ import annotations

import json

from ..core import Param, ServiceParam, TypeConverters as TC
from .base import CognitiveServiceBase


class _AnomalyBase(CognitiveServiceBase):
    series = ServiceParam("series", "list of {timestamp, value} points")
    granularity = ServiceParam("granularity",
                               "yearly|monthly|weekly|daily|hourly|"
                               "minutely")
    maxAnomalyRatio = ServiceParam("maxAnomalyRatio", "max anomaly ratio")
    sensitivity = ServiceParam("sensitivity", "detection sensitivity")
    customInterval = ServiceParam("customInterval", "granularity multiple")
    _path = ""

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/anomalydetector/v1.0/timeseries/{self._path}")

    def _body(self, df, row: int) -> bytes:
        payload = {"series": self._jsonable(
            self._resolve("series", df, row)),
            "granularity": self._resolve("granularity", df, row, "daily")}
        for opt in ("maxAnomalyRatio", "sensitivity", "customInterval"):
            v = self._resolve(opt, df, row)
            if v is not None:
                payload[opt] = self._jsonable(v)
        return json.dumps(payload).encode()


class DetectAnomalies(_AnomalyBase):
    _path = "entire/detect"


class DetectLastAnomaly(_AnomalyBase):
    _path = "last/detect"


class SimpleDetectAnomalies(_AnomalyBase):
    """Row-oriented anomaly detection over grouped series (reference
    ``AnamolyDetection.scala:157+``): rows carry (timestamp, value,
    group); each group becomes ONE service call over its time-sorted
    series, and every row gets its own point verdict back."""

    _path = "entire/detect"

    timestampCol = Param("timestampCol", "time of the series point",
                         TC.toString, default="timestamp")
    valueCol = Param("valueCol", "value of the series point", TC.toString,
                     default="value")
    groupbyCol = Param("groupbyCol", "column that groups the series",
                       TC.toString, default="group")

    def _transform(self, df):
        import numpy as np

        from ..io.http.clients import AsyncClient
        from ..io.http.schema import HTTPRequestData

        ts = df[self.get("timestampCol")]
        vals = df[self.get("valueCol")]
        groups = df[self.get("groupbyCol")]
        n = len(df)

        by_group: dict = {}
        for i in range(n):
            by_group.setdefault(groups[i], []).append(i)

        def ts_key(i):
            """Chronological order: numeric timestamps numerically,
            otherwise ISO-8601 strings (which sort lexicographically)."""
            v = ts[i]
            try:
                return (0, float(v), "")
            except (TypeError, ValueError):
                return (1, 0.0, str(v))

        requests = []
        order = []  # per request: row indices in series order
        for g, idxs in by_group.items():
            idxs = sorted(idxs, key=ts_key)
            payload = {
                "series": [{"timestamp": str(ts[i]),
                            "value": float(vals[i])} for i in idxs],
                "granularity": self._resolve("granularity", df, idxs[0],
                                             "daily")}
            for opt in ("maxAnomalyRatio", "sensitivity",
                        "customInterval"):
                v = self._resolve(opt, df, idxs[0])
                if v is not None:
                    payload[opt] = self._jsonable(v)
            requests.append(HTTPRequestData(
                url=self._build_url(df, idxs[0]), method="POST",
                headers=self._headers(df, idxs[0]),
                entity=json.dumps(payload).encode()))
            order.append(idxs)

        client = AsyncClient(concurrency=self.get("concurrency"),
                             timeout=self.get("timeout"))
        responses = client.send(requests)

        out = np.empty(n, object)
        err = np.empty(n, object)
        for idxs, resp in zip(order, responses):
            if 200 <= resp.status_code < 300:
                try:
                    parsed = resp.json()
                except Exception as e:
                    for i in idxs:
                        out[i], err[i] = None, f"parse error: {e}"
                    continue
                # plural response arrays → per-row singular fields, the
                # reference's ADSingleResponse shape
                singular = {"isAnomaly": "isAnomaly",
                            "isPositiveAnomaly": "isPositiveAnomaly",
                            "isNegativeAnomaly": "isNegativeAnomaly",
                            "expectedValues": "expectedValue",
                            "upperMargins": "upperMargin",
                            "lowerMargins": "lowerMargin"}
                for pos, i in enumerate(idxs):
                    point = {}
                    for key, name in singular.items():
                        seq = parsed.get(key)
                        if isinstance(seq, list) and pos < len(seq):
                            point[name] = seq[pos]
                    out[i] = point or parsed
                    err[i] = None
            else:
                for i in idxs:
                    out[i] = None
                    err[i] = {"statusCode": resp.status_code,
                              "reason": resp.reason}
        return (df.with_column(self.getOutputCol(), out)
                  .with_column(self.get("errorCol"), err))
