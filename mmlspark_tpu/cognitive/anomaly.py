"""Anomaly Detector services.

Reference ``cognitive/AnamolyDetection.scala`` — ``DetectAnomalies``
(entire series) and ``DetectLastAnomaly`` (latest point), posting
{"series": [{timestamp, value}...], "granularity": ...}.
"""

from __future__ import annotations

import json

from ..core import ServiceParam
from .base import CognitiveServiceBase


class _AnomalyBase(CognitiveServiceBase):
    series = ServiceParam("series", "list of {timestamp, value} points")
    granularity = ServiceParam("granularity",
                               "yearly|monthly|weekly|daily|hourly|"
                               "minutely")
    maxAnomalyRatio = ServiceParam("maxAnomalyRatio", "max anomaly ratio")
    sensitivity = ServiceParam("sensitivity", "detection sensitivity")
    customInterval = ServiceParam("customInterval", "granularity multiple")
    _path = ""

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/anomalydetector/v1.0/timeseries/{self._path}")

    def _body(self, df, row: int) -> bytes:
        payload = {"series": self._jsonable(
            self._resolve("series", df, row)),
            "granularity": self._resolve("granularity", df, row, "daily")}
        for opt in ("maxAnomalyRatio", "sensitivity", "customInterval"):
            v = self._resolve(opt, df, row)
            if v is not None:
                payload[opt] = self._jsonable(v)
        return json.dumps(payload).encode()


class DetectAnomalies(_AnomalyBase):
    _path = "entire/detect"


class DetectLastAnomaly(_AnomalyBase):
    _path = "last/detect"
