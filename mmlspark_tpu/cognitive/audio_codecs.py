"""Compressed-audio (MP3/OGG) container parsing for the speech path.

Reference ``cognitive/SpeechToTextSDK.scala:341-346`` (``CompressedStream``):
the SDK does NOT decode compressed audio locally — it wraps the stream
with its codec so the recognition service decodes server-side. The
TPU-native equivalent: sniff the container, walk its FRAME/PAGE
structure (an MP3 frame or OGG page must never be split mid-unit — a
receiver cannot resynchronize reliably inside one), chunk on those
boundaries, and let the caller send chunks with the right Content-Type.
Frame headers also carry enough timing to stamp Offset/Duration without
decoding a single sample.

Hand-written parsers over the PUBLISHED container layouts (MPEG audio
frame header fields; the OGG page header of RFC 3533) — no codec
libraries involved, nothing is decompressed.
"""

from __future__ import annotations

from dataclasses import dataclass

# MPEG audio frame header tables (Layer III). Bitrates in kbit/s; index
# 0 is "free format" (unsupported here), 15 is invalid.
_MP3_BITRATES_V1 = (None, 32, 40, 48, 56, 64, 80, 96, 112, 128, 160,
                    192, 224, 256, 320, None)
_MP3_BITRATES_V2 = (None, 8, 16, 24, 32, 40, 48, 56, 64, 80, 96, 112,
                    128, 144, 160, None)
_MP3_RATES = {3: (44100, 48000, 32000),    # MPEG1  (version bits 11)
              2: (22050, 24000, 16000),    # MPEG2  (version bits 10)
              0: (11025, 12000, 8000)}     # MPEG2.5 (version bits 00)


@dataclass(frozen=True)
class AudioUnit:
    """One indivisible container unit (MP3 frame / OGG page)."""
    offset: int          # byte offset in the source buffer
    size: int            # bytes
    duration_s: float    # decoded duration this unit carries


def sniff_audio_format(data: bytes) -> str:
    """``wav`` | ``mp3`` | ``ogg`` | ``raw`` by container magic (the
    reference's ``fileType`` sniffing extended to compressed types)."""
    if data[:4] == b"RIFF":
        return "wav"
    if data[:4] == b"OggS":
        return "ogg"
    if data[:3] == b"ID3":
        return "mp3"
    if len(data) >= 2 and data[0] == 0xFF and (data[1] & 0xE0) == 0xE0:
        return "mp3"
    return "raw"


def _mp3_frame_at(data: bytes, i: int):
    """Parse one MPEG frame header at ``i`` → (size, duration_s) or
    None if the bytes there are not a valid Layer III header."""
    if i + 4 > len(data) or data[i] != 0xFF or (data[i + 1] & 0xE0) != 0xE0:
        return None
    version = (data[i + 1] >> 3) & 0x3          # 3=MPEG1 2=MPEG2 0=2.5
    layer = (data[i + 1] >> 1) & 0x3            # 1 = Layer III
    if version == 1 or layer != 1:
        return None
    bitrate_idx = (data[i + 2] >> 4) & 0xF
    rate_idx = (data[i + 2] >> 2) & 0x3
    padding = (data[i + 2] >> 1) & 0x1
    if rate_idx == 3:
        return None
    bitrates = _MP3_BITRATES_V1 if version == 3 else _MP3_BITRATES_V2
    kbps = bitrates[bitrate_idx]
    if kbps is None:
        return None
    rate = _MP3_RATES[version][rate_idx]
    # Layer III: MPEG1 frames carry 1152 samples (coef 144 = 1152/8),
    # MPEG2/2.5 carry 576 (coef 72)
    coef, samples = (144, 1152) if version == 3 else (72, 576)
    size = coef * kbps * 1000 // rate + padding
    if size < 4:
        return None
    return size, samples / rate


def parse_mp3_units(data: bytes) -> list[AudioUnit]:
    """Walk the MPEG frame chain (skipping a leading ID3v2 tag) →
    frame-boundary units with per-frame durations. Raises on buffers
    with no parseable frame (matching ``parse_wav``'s fail-loud
    stance)."""
    i = 0
    if data[:3] == b"ID3" and len(data) >= 10:
        # ID3v2 size: 4 sync-safe bytes (7 bits each) after the flags
        tag = (data[6] << 21) | (data[7] << 14) | (data[8] << 7) | data[9]
        i = 10 + tag
    units: list[AudioUnit] = []
    while i < len(data) - 4:
        got = _mp3_frame_at(data, i)
        if got is None:
            if units:
                break           # trailing tag/junk after the chain
            i += 1              # scan for the first sync word
            continue
        size, dur = got
        if i + size > len(data):
            break               # truncated final frame: drop it
        units.append(AudioUnit(offset=i, size=size, duration_s=dur))
        i += size
    if not units:
        raise ValueError("no MPEG audio frames found (not an MP3, or "
                         "free-format/Layer I/II, which are unsupported)")
    return units


def parse_ogg_units(data: bytes,
                    granule_rate: int | None = None) -> list[AudioUnit]:
    """Walk OGG pages (RFC 3533 header: capture pattern, granule
    position, segment table) → page-boundary units. Durations derive
    from granule-position deltas; the granule clock is codec-defined —
    48 kHz for Opus (RFC 7845 §4, the default,
    ``OGG_DEFAULT_GRANULE_RATE``), the stream's own sample rate for
    Vorbis — pass ``granule_rate`` for non-Opus streams."""
    if granule_rate is None and data[:4] == b"OggS" and len(data) > 27:
        # the codec id header rides the first page's body IN THE CLEAR:
        # Vorbis ("\x01vorbis": sample rate at bytes 12-16 LE) clocks
        # granules at its own sample rate; Opus ("OpusHead") always at
        # 48 kHz (RFC 7845 §4). Still zero decoding — header fields only.
        ns = data[26]
        body = data[27 + ns:27 + ns + sum(data[27:27 + ns])]
        if body[:7] == b"\x01vorbis" and len(body) >= 16:
            granule_rate = int.from_bytes(body[12:16], "little") or None
    rate = granule_rate or OGG_DEFAULT_GRANULE_RATE
    units: list[AudioUnit] = []
    i = 0
    prev_granule = 0
    while i + 27 <= len(data):
        if data[i:i + 4] != b"OggS":
            if units:
                break
            raise ValueError("not an OGG stream (no OggS capture "
                             "pattern at start)")
        nsegs = data[i + 26]
        header_len = 27 + nsegs
        if i + header_len > len(data):
            break
        body = sum(data[i + 27:i + 27 + nsegs])
        size = header_len + body
        if i + size > len(data):
            break               # truncated final page
        granule = int.from_bytes(data[i + 6:i + 14], "little",
                                 signed=True)
        dur = 0.0
        if granule > prev_granule >= 0:
            dur = (granule - prev_granule) / rate
            prev_granule = granule
        elif granule >= 0:
            prev_granule = granule
        units.append(AudioUnit(offset=i, size=size, duration_s=dur))
        i += size
    if not units:
        raise ValueError("no OGG pages found")
    return units


# Opus always uses a 48 kHz granule clock (RFC 7845 §4); Vorbis uses
# its own sample rate — without decoding the id header we take the
# Opus convention, which is what the speech services stream in practice
OGG_DEFAULT_GRANULE_RATE = 48000

CONTENT_TYPES = {"mp3": "audio/mpeg", "ogg": "audio/ogg",
                 "wav": "audio/wav", "raw": "audio/pcm"}


def chunk_units(units: list[AudioUnit], max_seconds: float,
                data: bytes) -> list[tuple[bytes, float, float,
                                           int, int]]:
    """Group whole units into transmit chunks of at most
    ``max_seconds`` decoded audio → ``[(chunk_bytes, offset_s,
    duration_s, first_unit, end_unit)]``. Boundaries always land
    between units, so every chunk starts on a sync point the service
    can decode from; the unit span lets callers slice GROWING prefixes
    of a chunk (intermediate hypotheses) on those same boundaries."""
    chunks: list = []
    start = 0
    t0 = 0.0
    acc = 0.0
    clock = 0.0
    for k, u in enumerate(units):
        if acc > 0 and acc + u.duration_s > max_seconds:
            end = u.offset
            chunks.append((data[units[start].offset:end], t0, acc,
                           start, k))
            start, t0, acc = k, clock, 0.0
        acc += u.duration_s
        clock += u.duration_s
    last = units[-1]
    chunks.append((data[units[start].offset:last.offset + last.size],
                   t0, acc, start, len(units)))
    return chunks
