"""Text Analytics services.

Reference ``cognitive/TextAnalytics.scala`` — sentiment, key phrases, NER,
entity linking, language detection (V3 endpoints).
"""

from __future__ import annotations

from .base import _DocumentsService


class _TextAnalytics(_DocumentsService):
    _path = ""
    _version = "v3.0"

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/text/analytics/{self._version}/{self._path}")


class TextSentiment(_TextAnalytics):
    """Reference ``TextSentiment`` (V3: sentiment + per-sentence scores)."""
    _path = "sentiment"


class KeyPhraseExtractor(_TextAnalytics):
    _path = "keyPhrases"


class NER(_TextAnalytics):
    _path = "entities/recognition/general"


class EntityDetector(_TextAnalytics):
    """Entity linking (reference ``EntityDetector``)."""
    _path = "entities/linking"


class LanguageDetector(_TextAnalytics):
    _path = "languages"


class _TextAnalyticsV2(_TextAnalytics):
    """V2.0 schema variants (reference ``TextAnalyticsSchemasV2.scala`` —
    kept for pipelines pinned to the older API)."""
    _version = "v2.0"


class TextSentimentV2(_TextAnalyticsV2):
    _path = "sentiment"


class KeyPhraseExtractorV2(_TextAnalyticsV2):
    _path = "keyPhrases"


class NERV2(_TextAnalyticsV2):
    _path = "entities"


class LanguageDetectorV2(_TextAnalyticsV2):
    _path = "languages"
