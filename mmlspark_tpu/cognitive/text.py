"""Text Analytics services.

Reference ``cognitive/TextAnalytics.scala`` — sentiment, key phrases, NER,
entity linking, language detection (V3 endpoints).
"""

from __future__ import annotations

from .base import _DocumentsService


class _TextAnalytics(_DocumentsService):
    _path = ""

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/text/analytics/v3.0/{self._path}")


class TextSentiment(_TextAnalytics):
    """Reference ``TextSentiment`` (V3: sentiment + per-sentence scores)."""
    _path = "sentiment"


class KeyPhraseExtractor(_TextAnalytics):
    _path = "keyPhrases"


class NER(_TextAnalytics):
    _path = "entities/recognition/general"


class EntityDetector(_TextAnalytics):
    """Entity linking (reference ``EntityDetector``)."""
    _path = "entities/linking"


class LanguageDetector(_TextAnalytics):
    _path = "languages"
