"""Cognitive Services on DataFrames.

Reference ``cognitive/`` (23 files, ~4.3k LoC — SURVEY §2.8): one
architecture (``CognitiveServiceBase.scala``) where a transformer assembles
an HTTP request per row from ServiceParams (scalar or column), pipes it
through the L7 HTTP stack with retry, and parses JSON responses. All
engine-free — the TPU build reuses it unchanged over its own HTTP layer.
"""

from .base import CognitiveServiceBase
from .text import (KeyPhraseExtractorV2, LanguageDetectorV2, NERV2,
                   TextSentimentV2,
                   TextSentiment, KeyPhraseExtractor, NER, LanguageDetector,
                   EntityDetector)
from .vision import (AnalyzeImage, DescribeImage, OCR, Read,
                     RecognizeText, RecognizeDomainSpecificContent,
                     GenerateThumbnails, TagImage)
from .face import (DetectFace, FindSimilarFace, GroupFaces, IdentifyFaces,
                   VerifyFaces)
from .anomaly import (DetectAnomalies, DetectLastAnomaly,
                      SimpleDetectAnomalies)
from .bing import BingImageSearch
from .speech import (ConversationTranscription, PullAudioInputStream,
                     SpeechToText, SpeechToTextSDK, segment_pcm16)
from .azure_search import (AddDocuments, AzureSearchWriter,
                           validate_index_fields)

__all__ = [
    "CognitiveServiceBase", "TextSentiment", "KeyPhraseExtractor", "NER",
    "LanguageDetector", "EntityDetector", "AnalyzeImage", "DescribeImage",
    "OCR", "RecognizeText", "RecognizeDomainSpecificContent",
    "GenerateThumbnails", "TagImage", "DetectFace", "FindSimilarFace",
    "GroupFaces", "IdentifyFaces", "VerifyFaces", "DetectAnomalies",
    "DetectLastAnomaly", "SimpleDetectAnomalies", "AddDocuments",
    "TextSentimentV2", "KeyPhraseExtractorV2", "NERV2",
    "LanguageDetectorV2", "Read", "BingImageSearch", "SpeechToText",
    "SpeechToTextSDK", "ConversationTranscription",
    "PullAudioInputStream", "segment_pcm16", "AzureSearchWriter",
    "validate_index_fields",
]
