"""Azure Search sink.

Reference ``cognitive/AzureSearch.scala`` (writer with index creation) and
``AzureSearchAPI.scala``: create the index if missing, then POST row
batches to ``/docs/index`` with ``@search.action`` per document.
"""

from __future__ import annotations

import json

import numpy as np

from ..core import DataFrame
from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData


class AzureSearchWriter:
    def __init__(self, service_name: str, index_name: str, key: str,
                 index_fields: dict | None = None,
                 action: str = "mergeOrUpload", batch_size: int = 100,
                 api_version: str = "2019-05-06"):
        self.base = (f"https://{service_name}.search.windows.net"
                     f"/indexes")
        self.index_name = index_name
        self.key = key
        self.index_fields = index_fields
        self.action = action
        self.batch_size = batch_size
        self.api_version = api_version

    def _headers(self):
        return {"Content-Type": "application/json", "api-key": self.key}

    def ensure_index(self) -> bool:
        """Create the index when a field schema was given (reference
        ``SearchIndex.createIfNoneExists``)."""
        if not self.index_fields:
            return False
        fields = [{"name": name, **spec} if isinstance(spec, dict)
                  else {"name": name, "type": spec}
                  for name, spec in self.index_fields.items()]
        body = json.dumps({"name": self.index_name,
                           "fields": fields}).encode()
        resp = send_request(HTTPRequestData(
            url=f"{self.base}?api-version={self.api_version}",
            method="POST", headers=self._headers(), entity=body))
        return 200 <= resp.status_code < 300

    def write(self, df: DataFrame) -> list[dict]:
        """POST documents in batches; returns per-batch API responses."""
        self.ensure_index()
        url = (f"{self.base}/{self.index_name}/docs/index"
               f"?api-version={self.api_version}")
        rows = [dict(r) for r in df.collect()]
        results = []
        for start in range(0, len(rows), self.batch_size):
            docs = []
            for r in rows[start:start + self.batch_size]:
                doc = {"@search.action": self.action}
                for k, v in r.items():
                    doc[k] = v.item() if isinstance(v, np.generic) else \
                        v.tolist() if isinstance(v, np.ndarray) else v
                docs.append(doc)
            resp = send_request(HTTPRequestData(
                url=url, method="POST", headers=self._headers(),
                entity=json.dumps({"value": docs}).encode()))
            results.append(resp.json() if resp.entity else
                           {"statusCode": resp.status_code})
        return results
