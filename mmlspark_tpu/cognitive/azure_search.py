"""Azure Search sink + index management.

Reference ``cognitive/AzureSearch.scala`` (writer with index creation,
schema/action validation) and ``cognitive/AzureSearchAPI.scala`` (index
exists/list/statistics/delete management calls): create the index if
missing, validate the field schema (exactly one key field, known types,
legal actions), then POST row batches to ``/docs/index`` with
``@search.action`` per document.
"""

from __future__ import annotations

import json

import numpy as np

from ..core import DataFrame
from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData


VALID_ACTIONS = ("upload", "merge", "mergeOrUpload", "delete")
VALID_EDM_TYPES = (
    "Edm.String", "Edm.Boolean", "Edm.Int32", "Edm.Int64", "Edm.Double",
    "Edm.DateTimeOffset", "Edm.GeographyPoint", "Collection(Edm.String)",
    "Collection(Edm.Double)", "Collection(Edm.Single)")


def validate_index_fields(index_fields: dict) -> list[dict]:
    """Reference ``AzureSearch.scala`` ``checkSchemaParity``: exactly one
    key field, every type a known EDM type. Returns normalized specs."""
    fields = [{"name": name, **spec} if isinstance(spec, dict)
              else {"name": name, "type": spec}
              for name, spec in index_fields.items()]
    keys = [f["name"] for f in fields if f.get("key")]
    if len(keys) != 1:
        raise ValueError(
            f"exactly one field must have key=True, got {keys or 'none'}")
    for f in fields:
        if f.get("type") not in VALID_EDM_TYPES:
            raise ValueError(
                f"field {f['name']!r} has invalid EDM type "
                f"{f.get('type')!r}; valid: {VALID_EDM_TYPES}")
    return fields


def _row_to_doc(row: dict, action: str) -> dict:
    """One DataFrame row → one indexing document (shared by write() and
    AddDocuments; numpy scalars/arrays become JSON-native values)."""
    if action not in VALID_ACTIONS:
        raise ValueError(f"@search.action must be one of {VALID_ACTIONS}, "
                         f"got {action!r}")
    doc = {"@search.action": action}
    for k, v in row.items():
        doc[k] = v.item() if isinstance(v, np.generic) else \
            v.tolist() if isinstance(v, np.ndarray) else v
    return doc


class AzureSearchWriter:
    def __init__(self, service_name: str, index_name: str, key: str,
                 index_fields: dict | None = None,
                 action: str = "mergeOrUpload", batch_size: int = 100,
                 api_version: str = "2019-05-06",
                 base_url: str | None = None):
        if action not in VALID_ACTIONS:
            raise ValueError(f"action must be one of {VALID_ACTIONS}, "
                             f"got {action!r}")
        # base_url override keeps tests/self-hosted gateways reachable
        self.base = base_url or (f"https://{service_name}"
                                 f".search.windows.net/indexes")
        self.index_name = index_name
        self.key = key
        self.index_fields = index_fields
        self.action = action
        self.batch_size = batch_size
        self.api_version = api_version

    def _headers(self):
        return {"Content-Type": "application/json", "api-key": self.key}

    def _get(self, path: str):
        return send_request(HTTPRequestData(
            url=f"{self.base}{path}?api-version={self.api_version}",
            method="GET", headers=self._headers()))

    # ---- index management (reference AzureSearchAPI.scala) --------------
    def index_exists(self, name: str | None = None) -> bool:
        """Reference ``SearchIndex.exists``."""
        resp = self._get(f"/{name or self.index_name}")
        return 200 <= resp.status_code < 300

    def list_indexes(self) -> list[str]:
        """Reference ``SearchIndex.getExisting`` — index names."""
        resp = self._get("")
        if not 200 <= resp.status_code < 300:
            raise IOError(f"list indexes failed: {resp.status_code}")
        return [i["name"] for i in resp.json().get("value", [])]

    def get_statistics(self, name: str | None = None) -> dict:
        """Reference ``getStatistics`` — {documentCount, storageSize}."""
        resp = self._get(f"/{name or self.index_name}/stats")
        if not 200 <= resp.status_code < 300:
            raise IOError(f"statistics failed: {resp.status_code}")
        return resp.json()

    def delete_index(self, name: str | None = None) -> bool:
        resp = send_request(HTTPRequestData(
            url=(f"{self.base}/{name or self.index_name}"
                 f"?api-version={self.api_version}"),
            method="DELETE", headers=self._headers()))
        return 200 <= resp.status_code < 300

    def ensure_index(self) -> bool:
        """Create the index when a field schema was given (reference
        ``SearchIndex.createIfNoneExists``); validates the schema first."""
        if not self.index_fields:
            return False
        fields = validate_index_fields(self.index_fields)
        if self.index_exists():
            return False
        body = json.dumps({"name": self.index_name,
                           "fields": fields}).encode()
        resp = send_request(HTTPRequestData(
            url=f"{self.base}?api-version={self.api_version}",
            method="POST", headers=self._headers(), entity=body))
        return 200 <= resp.status_code < 300

    def write(self, df: DataFrame) -> list[dict]:
        """POST documents in batches; returns per-batch API responses."""
        self.ensure_index()
        url = (f"{self.base}/{self.index_name}/docs/index"
               f"?api-version={self.api_version}")
        rows = [dict(r) for r in df.collect()]
        results = []
        for start in range(0, len(rows), self.batch_size):
            docs = [_row_to_doc(r, self.action)
                    for r in rows[start:start + self.batch_size]]
            resp = send_request(HTTPRequestData(
                url=url, method="POST", headers=self._headers(),
                entity=json.dumps({"value": docs}).encode()))
            results.append(resp.json() if resp.entity else
                           {"statusCode": resp.status_code})
        return results


class AddDocuments:
    """Transformer-shaped Azure Search sink (reference ``AddDocuments`` in
    ``AzureSearch.scala``): rows become documents with a per-row
    ``@search.action`` (from ``actionCol`` when set), batched to
    ``/docs/index``; the per-document API status comes back as a column.
    """

    def __init__(self, service_name: str = "", index_name: str = "",
                 key: str = "", action_col: str | None = None,
                 batch_size: int = 100, base_url: str | None = None,
                 output_col: str = "indexResponse",
                 api_version: str = "2019-05-06"):
        self.writer = AzureSearchWriter(
            service_name=service_name or "unused", index_name=index_name,
            key=key, batch_size=batch_size, base_url=base_url,
            api_version=api_version)
        self.action_col = action_col
        self.output_col = output_col

    def transform(self, df: DataFrame) -> DataFrame:
        url = (f"{self.writer.base}/{self.writer.index_name}/docs/index"
               f"?api-version={self.writer.api_version}")
        rows = [dict(r) for r in df.collect()]
        statuses: list = [None] * len(rows)
        bs = self.writer.batch_size
        for start in range(0, len(rows), bs):
            docs = []
            for r in rows[start:start + bs]:
                action = (str(r.pop(self.action_col, self.writer.action))
                          if self.action_col else self.writer.action)
                docs.append(_row_to_doc(r, action))
            resp = send_request(HTTPRequestData(
                url=url, method="POST",
                headers=self.writer._headers(),
                entity=json.dumps({"value": docs}).encode()))
            parsed = resp.json() if resp.entity else {}
            values = parsed.get("value", []) if isinstance(parsed, dict) \
                else []
            for j in range(start, min(start + bs, len(rows))):
                pos = j - start
                statuses[j] = (values[pos] if pos < len(values)
                               else {"statusCode": resp.status_code})
        out = np.empty(len(rows), object)
        out[:] = statuses
        return df.with_column(self.output_col, out)
