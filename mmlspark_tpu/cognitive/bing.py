"""Bing Image Search.

Reference ``cognitive/BingImageSearch.scala`` — GET search transformer plus
the ``downloadFromUrls`` helper that fans result urls out to byte columns.
"""

from __future__ import annotations

import numpy as np

from ..core import DataFrame, ServiceParam
from ..io.http.clients import AsyncClient
from ..io.http.schema import HTTPRequestData
from .base import CognitiveServiceBase


class BingImageSearch(CognitiveServiceBase):
    _method = "GET"
    q = ServiceParam("q", "search query")
    count = ServiceParam("count", "results per page")
    offset = ServiceParam("offset", "result offset")
    imageType = ServiceParam("imageType", "Photo|Clipart|...")

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(
            url="https://api.bing.microsoft.com/v7.0/images/search",
            outputCol="images")

    def _url_params(self, df, row):
        return {"q": self._resolve("q", df, row),
                "count": self._resolve("count", df, row),
                "offset": self._resolve("offset", df, row),
                "imageType": self._resolve("imageType", df, row)}

    def _body(self, df, row):
        return None

    @staticmethod
    def getUrlTransformer(image_col: str, url_col: str):
        """Response → exploded contentUrl rows (reference
        ``BingImageSearch.getUrlTransformer``)."""
        from ..core import Transformer

        class _Urls(Transformer):
            def _transform(self, df):
                urls = []
                for r in df[image_col]:
                    for v in (r or {}).get("value", []):
                        if "contentUrl" in v:
                            urls.append(v["contentUrl"])
                col = np.empty(len(urls), object)
                col[:] = urls
                return DataFrame({url_col: col})
        return _Urls()

    @staticmethod
    def downloadFromUrls(url_col: str, bytes_col: str,
                         concurrency: int = 8, timeout: float = 30.0):
        """URL column → bytes column (reference ``downloadFromUrls``)."""
        from ..core import Transformer

        class _Download(Transformer):
            def _transform(self, df):
                reqs = [HTTPRequestData(url=str(u), method="GET")
                        for u in df[url_col]]
                responses = AsyncClient(concurrency=concurrency,
                                        timeout=timeout).send(reqs)
                out = np.empty(len(responses), object)
                out[:] = [r.entity if 200 <= r.status_code < 300 else None
                          for r in responses]
                return df.with_column(bytes_col, out)
        return _Download()
