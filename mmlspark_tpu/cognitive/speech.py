"""Speech-to-text services.

Reference ``cognitive/SpeechToText.scala`` (REST short-audio API) and
``cognitive/SpeechToTextSDK.scala:79-540`` (native Speech SDK streaming):
the SDK feeds a *pull* audio input stream (:341-346) into continuous
recognition, emits intermediate ("recognizing") hypotheses and final
("recognized") utterances with 100-ns offset/duration ticks, and the
``ConversationTranscription`` variant (:493) adds participant/speaker
attribution.

TPU-native shape: the native SDK has no engine role here, so streaming is
reimplemented on open parts — a :class:`PullAudioInputStream` the
recognizer pulls frames from, energy-based voice-activity segmentation of
PCM16 audio into utterances, per-utterance REST recognition, and
incremental partial-result rows when ``streamIntermediateResults`` is on.
Row shape matches the SDK's ``SpeechResponse``.
"""

from __future__ import annotations

import json
import uuid

import numpy as np

from ..core import Param, ServiceParam, TypeConverters as TC
from .base import CognitiveServiceBase

TICKS_PER_SECOND = 10_000_000  # SDK offsets/durations are 100-ns ticks


class SpeechToText(CognitiveServiceBase):
    _content_type = "audio/wav; codecs=audio/pcm; samplerate=16000"
    audioData = ServiceParam("audioData", "raw audio bytes")
    language = ServiceParam("language", "BCP-47 language tag")
    format = ServiceParam("format", "simple | detailed")
    profanity = ServiceParam("profanity", "masked | removed | raw")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.stt.speech.microsoft.com/speech/"
                f"recognition/conversation/cognitiveservices/v1")

    def _url_params(self, df, row):
        return {"language": self._resolve("language", df, row, "en-US"),
                "format": self._resolve("format", df, row),
                "profanity": self._resolve("profanity", df, row)}

    def _body(self, df, row: int) -> bytes:
        return bytes(self._resolve("audioData", df, row))


class PullAudioInputStream:
    """Pull-audio semantics (reference ``SpeechToTextSDK.scala:341-346``):
    the recognizer calls :meth:`read` for the next frame; the source may
    be bytes, a file path, or any zero-arg chunk producer."""

    def __init__(self, source, frame_bytes: int = 3200):
        self.frame_bytes = frame_bytes
        # immutable buffer + read offset: frame extraction is O(frame)
        # per call, not O(remaining) reslicing
        self._buffer = memoryview(b"")
        self._pos = 0
        self._exhausted = False
        self._file = None
        if isinstance(source, (bytes, bytearray, np.ndarray)):
            data = bytes(source)
            self._next_chunk = iter([data]).__next__
        elif isinstance(source, str):
            self._file = open(source, "rb")
            self._next_chunk = lambda: self._file.read(1 << 16)
        elif callable(source):
            self._next_chunk = source
        else:
            raise TypeError(f"unsupported audio source {type(source)}")

    def read(self) -> bytes:
        """Next frame (<= frame_bytes); b'' = end of stream."""
        while (len(self._buffer) - self._pos < self.frame_bytes
               and not self._exhausted):
            try:
                chunk = self._next_chunk()
            except StopIteration:
                chunk = b""
            if not chunk:
                self._exhausted = True
                if self._file is not None:
                    self._file.close()
                break
            remaining = bytes(self._buffer[self._pos:])
            self._buffer = memoryview(remaining + bytes(chunk))
            self._pos = 0
        out = bytes(self._buffer[self._pos:self._pos + self.frame_bytes])
        self._pos += len(out)
        return out


def segment_pcm16(audio: np.ndarray, sample_rate: int,
                  frame_ms: float = 30.0, silence_rel: float = 0.08,
                  min_silence_s: float = 0.25,
                  max_segment_s: float = 15.0) -> list[tuple[int, int]]:
    """Energy-VAD utterance boundaries over int16 PCM → [(start, end)) in
    samples. Splits at runs of low-energy frames (the SDK's segmentation
    role) with a hard cap at ``max_segment_s``."""
    n = audio.shape[0]
    if n == 0:
        return []
    frame = max(int(sample_rate * frame_ms / 1000.0), 1)
    n_frames = (n + frame - 1) // frame
    padded = np.zeros(n_frames * frame, np.float64)
    padded[:n] = audio.astype(np.float64)
    rms = np.sqrt((padded.reshape(n_frames, frame) ** 2).mean(axis=1))
    thresh = max(rms.max() * silence_rel, 1e-9)
    active = rms > thresh
    min_gap = max(int(min_silence_s * 1000 / frame_ms), 1)
    max_frames = max(int(max_segment_s * 1000 / frame_ms), 1)

    segments: list[tuple[int, int]] = []
    start = None
    gap = 0
    for i, a in enumerate(active):
        if a:
            if start is None:
                start = i
            gap = 0
        elif start is not None:
            gap += 1
            if gap >= min_gap:
                segments.append((start, i - gap + 1))
                start, gap = None, 0
        if start is not None and i - start + 1 >= max_frames:
            segments.append((start, i + 1))
            start, gap = None, 0
    if start is not None:
        segments.append((start, n_frames))
    return [(s * frame, min(e * frame, n)) for s, e in segments]


def parse_wav(data: bytes) -> tuple[np.ndarray, int]:
    """RIFF/WAVE container → (mono int16 samples, sample_rate).

    Reference ``cognitive/AudioStreams.scala`` ``WavStream``: the SDK
    accepts WAV files by parsing the header and feeding raw PCM. Stdlib
    ``wave`` does the container work (PCM-only by design); 16-bit only,
    multi-channel audio is downmixed to mono by averaging.
    """
    import io
    import wave
    try:
        with wave.open(io.BytesIO(data)) as w:
            channels = w.getnchannels()
            rate = w.getframerate()
            width = w.getsampwidth()
            pcm = w.readframes(w.getnframes())
    except (wave.Error, EOFError) as e:
        raise ValueError(f"not a supported WAV ({e}); note: compressed "
                         "audio must be decoded upstream") from e
    if width != 2:
        raise ValueError(
            f"only PCM16 WAV is supported (sample width {width} bytes)")
    samples = np.frombuffer(pcm[:len(pcm) // 2 * 2], dtype="<i2")
    if channels > 1:
        n = samples.shape[0] // channels * channels
        samples = samples[:n].reshape(-1, channels) \
            .mean(axis=1).astype(np.int16)
    return samples, rate


class SpeechToTextSDK(SpeechToText):
    """Continuous streaming recognition over a pull audio stream.

    Output rows mirror the SDK's ``SpeechResponse``: dicts with
    ``ResultId``/``DisplayText``/``Offset``/``Duration`` (ticks) and
    ``RecognitionStatus`` (``Recognizing`` for intermediate hypotheses
    when ``streamIntermediateResults`` is set, ``Success`` for finals),
    plus a ``sourceRow`` column tying results to input rows.
    """

    sampleRate = Param("sampleRate", "PCM sample rate (raw input)",
                       TC.toInt, default=16000)
    fileType = Param("fileType",
                     "auto | wav | raw | mp3 | ogg — auto sniffs the "
                     "container magic (reference fileType/AudioStreams; "
                     "mp3/ogg stream COMPRESSED with codec Content-Type "
                     "like the reference's CompressedStream — chunked "
                     "on frame/page boundaries, never decoded locally)",
                     TC.toString, default="auto")
    maxSegmentSeconds = Param("maxSegmentSeconds",
                              "hard utterance length cap", TC.toFloat,
                              default=15.0)
    streamIntermediateResults = Param(
        "streamIntermediateResults",
        "emit partial (Recognizing) hypotheses while an utterance is open",
        TC.toBoolean, default=False)
    intermediateInterval = Param(
        "intermediateInterval",
        "seconds of new audio between intermediate hypotheses",
        TC.toFloat, default=1.0)

    def _recognition_request(self, seg_bytes: bytes, df, row: int,
                             sample_rate: int,
                             content_type: str | None = None):
        """One REST recognition request (the SDK's per-utterance service
        hop); sent in bulk through the async client. The Content-Type
        advertises the ACTUAL sample rate (a WAV's own rate may differ
        from the sampleRate param — a mismatch would make the service
        decode at the wrong speed). Compressed chunks pass their codec
        ``content_type`` (``audio/mpeg`` / ``audio/ogg``) — the
        reference's ``CompressedStream`` contract: the SERVICE decodes,
        the client only labels."""
        from ..io.http.schema import HTTPRequestData
        headers = self._headers(df, row)
        headers["Content-Type"] = content_type or (
            f"audio/wav; codecs=audio/pcm; samplerate={sample_rate}")
        return HTTPRequestData(url=self._build_url(df, row),
                               method="POST", headers=headers,
                               entity=seg_bytes)

    def _result_row(self, parsed, status: str, offset_samples: int,
                    n_samples: int, rate: int) -> dict:
        text = ""
        extra = {}
        if isinstance(parsed, dict):
            text = parsed.get("DisplayText", parsed.get("displayText", ""))
            for k in ("NBest", "SpeakerId", "Speaker"):
                if k in parsed:
                    extra[k] = parsed[k]
        return {"ResultId": uuid.uuid4().hex,
                "RecognitionStatus": status,
                "DisplayText": text,
                "Offset": int(offset_samples / rate * TICKS_PER_SECOND),
                "Duration": int(n_samples / rate * TICKS_PER_SECOND),
                **extra}

    def _transform(self, df):
        from ..core import DataFrame
        rate = self.get("sampleRate")  # raw-PCM default; WAV overrides
        stream_partials = self.get("streamIntermediateResults")

        # phase 1: pull + segment each row's audio, build every recognition
        # request (partials and finals) with its result metadata
        requests = []
        meta = []  # (src_row, status, offset_samples, n_samples, rate)
        prefailed = []  # (src_row, error) rows that never reach the wire
        ftype = self.get("fileType")
        if ftype not in ("auto", "wav", "raw", "mp3", "ogg"):
            raise ValueError(
                "fileType must be auto | wav | raw | mp3 | ogg, got "
                f"{ftype!r}")
        from .audio_codecs import (CONTENT_TYPES, chunk_units,
                                   parse_mp3_units, parse_ogg_units,
                                   sniff_audio_format)
        for i in range(len(df)):
            # batch rows already hold complete audio; PullAudioInputStream
            # remains the API for genuinely incremental sources
            data = bytes(self._resolve("audioData", df, i))
            row_rate = rate
            sniffed = sniff_audio_format(data) if ftype == "auto" \
                else ftype
            if sniffed in ("mp3", "ogg"):
                # compressed path (reference CompressedStream,
                # SpeechToTextSDK.scala:341-346): never decoded locally
                # — chunk on frame/page boundaries so every request
                # starts at a codec sync point, stamp timing from the
                # container's own frame durations / granule positions,
                # and let the service decode. No local VAD (that would
                # need PCM): chunks are fixed-duration utterances.
                try:
                    units = parse_mp3_units(data) if sniffed == "mp3" \
                        else parse_ogg_units(data)
                    # a bare MP3 sync word is only 11 bits: raw PCM can
                    # collide (an int16 sample of -1 starts FF FF). In
                    # AUTO mode demand a CHAINED frame sequence before
                    # believing it — noise essentially never parses to
                    # two back-to-back valid frames
                    if ftype == "auto" and sniffed == "mp3" \
                            and data[:3] != b"ID3" and len(units) < 2:
                        raise ValueError("single unchained frame")
                except ValueError as e:
                    if ftype != "auto":
                        prefailed.append((i, str(e)))
                        continue
                    # auto-sniff was a coincidence: fall through to the
                    # raw-PCM path below, the pre-compressed behavior
                    sniffed = "raw"
                else:
                    ct = CONTENT_TYPES[sniffed]
                    for chunk, off_s, dur_s, u0, u1 in chunk_units(
                            units, self.get("maxSegmentSeconds"), data):
                        if stream_partials:
                            # growing PREFIXES of the chunk, sliced on
                            # unit boundaries (every prefix starts at a
                            # codec sync point and ends on a frame edge
                            # — still nothing decoded locally)
                            step = max(
                                self.get("intermediateInterval"), 0.03)
                            next_at, run = step, 0.0
                            for j in range(u0, u1 - 1):
                                run += units[j].duration_s
                                if run < next_at:
                                    continue
                                next_at = run + step
                                u = units[j]
                                requests.append(
                                    self._recognition_request(
                                        data[units[u0].offset:
                                             u.offset + u.size],
                                        df, i, row_rate,
                                        content_type=ct))
                                meta.append((i, "Recognizing", off_s,
                                             run, 1))
                        requests.append(self._recognition_request(
                            chunk, df, i, row_rate, content_type=ct))
                        # rate=1 ⇒ the "sample" unit below IS seconds
                        meta.append((i, "Success", off_s, dur_s, 1))
                    continue
            if sniffed == "wav":
                try:
                    audio, row_rate = parse_wav(data)
                except ValueError as e:
                    # one bad container ≠ whole batch lost
                    prefailed.append((i, str(e)))
                    continue
            else:
                audio = np.frombuffer(
                    data[:len(data) // 2 * 2], dtype="<i2")
            segments = segment_pcm16(
                audio, row_rate,
                max_segment_s=self.get("maxSegmentSeconds"))
            for s, e in segments:
                seg = audio[s:e]
                if stream_partials:
                    # incremental hypotheses over the growing utterance,
                    # floored at 30 ms so interval≈0 can't explode into
                    # one request per sample
                    step = max(int(self.get("intermediateInterval")
                                   * row_rate),
                               int(0.03 * row_rate), 1)
                    for cut in range(step, seg.shape[0], step):
                        requests.append(self._recognition_request(
                            seg[:cut].tobytes(), df, i, row_rate))
                        meta.append((i, "Recognizing", s, cut, row_rate))
                requests.append(self._recognition_request(
                    seg.tobytes(), df, i, row_rate))
                meta.append((i, "Success", s, seg.shape[0],
                             row_rate))

        # phase 2: bulk send — the concurrency param applies exactly as in
        # the plain request/response services
        from ..io.http.clients import AsyncClient
        client = AsyncClient(concurrency=self.get("concurrency"),
                             timeout=self.get("timeout"))
        responses = client.send(requests)

        # phase 3: assemble rows in deterministic (audio) order
        results: list[dict] = []
        errors: list = []
        src_rows: list[int] = []
        for (i, status, s, n, row_rate), resp in zip(meta, responses):
            if 200 <= resp.status_code < 300:
                try:
                    parsed, err = resp.json(), None
                except Exception as e:  # one bad body ≠ whole batch lost
                    parsed, err = None, f"parse error: {e}"
                    if status == "Success":
                        status = "Error"
            else:
                parsed = None
                err = {"statusCode": resp.status_code,
                       "reason": resp.reason,
                       "response": resp.entity.decode("utf-8", "replace")
                       if resp.entity else None}
                if status == "Success":
                    status = "Error"
            results.append(self._result_row(parsed, status, s, n,
                                            row_rate))
            errors.append(err)
            src_rows.append(i)
        for i, msg in prefailed:
            # through _result_row so subclasses' schema additions
            # (ConversationTranscription's SpeakerId) stay uniform
            results.append(self._result_row(None, "Error", 0, 0, 1))
            errors.append({"error": msg})
            src_rows.append(i)

        out = np.empty(len(results), object)
        out[:] = results
        err = np.empty(len(errors), object)
        err[:] = errors
        return DataFrame({
            self.getOutputCol(): out,
            self.get("errorCol"): err,
            "sourceRow": np.asarray(src_rows, np.int64)})


class ConversationTranscription(SpeechToTextSDK):
    """Multi-speaker transcription (reference
    ``SpeechToTextSDK.scala:493`` ``ConversationTranscription``): the
    streaming pipeline plus participant registration; rows carry the
    service's speaker attribution under ``SpeakerId``."""

    participantsJson = ServiceParam(
        "participantsJson",
        'participants [{"name", "language", "signature"}] json')

    def _url_for_location(self, location: str) -> str:
        return (f"https://transcribe.{location}.cts.speech.microsoft.com/"
                f"speech/recognition/conversation/cognitiveservices/v1")

    def _url_params(self, df, row):
        params = super()._url_params(df, row)
        participants = self._resolve("participantsJson", df, row)
        if participants:
            names = [p.get("name") for p in json.loads(participants)
                     if isinstance(p, dict)]
            params["participants"] = ",".join(n for n in names if n)
        return params

    def _result_row(self, parsed, status, offset_samples, n_samples, rate):
        row = super()._result_row(parsed, status, offset_samples,
                                  n_samples, rate)
        row.setdefault("SpeakerId", "Unidentified")
        return row
