"""Speech-to-text services.

Reference ``cognitive/SpeechToText.scala`` (REST short-audio API) and
``SpeechToTextSDK.scala:79-540`` (native Speech SDK streaming with pull
audio streams). The SDK's native streaming has no TPU-relevant engine —
here ``SpeechToTextSDK`` approximates continuous recognition by chunking
audio and posting each chunk to the REST endpoint, emitting one result row
per chunk (the reference's per-utterance output shape).
"""

from __future__ import annotations

import json

import numpy as np

from ..core import Param, ServiceParam, TypeConverters as TC
from .base import CognitiveServiceBase


class SpeechToText(CognitiveServiceBase):
    _content_type = "audio/wav; codecs=audio/pcm; samplerate=16000"
    audioData = ServiceParam("audioData", "raw audio bytes")
    language = ServiceParam("language", "BCP-47 language tag")
    format = ServiceParam("format", "simple | detailed")
    profanity = ServiceParam("profanity", "masked | removed | raw")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.stt.speech.microsoft.com/speech/"
                f"recognition/conversation/cognitiveservices/v1")

    def _url_params(self, df, row):
        return {"language": self._resolve("language", df, row, "en-US"),
                "format": self._resolve("format", df, row),
                "profanity": self._resolve("profanity", df, row)}

    def _body(self, df, row: int) -> bytes:
        return bytes(self._resolve("audioData", df, row))


class SpeechToTextSDK(SpeechToText):
    """Streaming approximation: chunk audio, one recognition per chunk."""

    chunkSeconds = Param("chunkSeconds", "seconds of audio per chunk",
                         TC.toFloat, default=15.0)
    sampleRate = Param("sampleRate", "PCM sample rate", TC.toInt,
                       default=16000)

    def _transform(self, df):
        bytes_per_chunk = int(self.get("chunkSeconds")
                              * self.get("sampleRate") * 2)  # 16-bit mono
        rows = []
        audio_col = self.get("audioData")
        col_name = audio_col["col"] if isinstance(audio_col, dict) and \
            "col" in audio_col else None
        for i in range(len(df)):
            data = bytes(self._resolve("audioData", df, i))
            chunks = [data[o:o + bytes_per_chunk]
                      for o in range(0, max(len(data), 1),
                                     bytes_per_chunk)]
            for c in chunks:
                rows.append((i, c))
        from ..core import DataFrame
        src = np.empty(len(rows), object)
        src[:] = [c for _, c in rows]
        chunk_df = DataFrame({"_chunk": src})
        inner = SpeechToText(
            url=self.get("url"), outputCol=self.getOutputCol(),
            errorCol=self.get("errorCol"),
            concurrency=self.get("concurrency"))
        inner.set("subscriptionKey", self.get("subscriptionKey"))
        inner.setAudioDataCol("_chunk")
        for p in ("language", "format", "profanity"):
            if self.isSet(p):
                inner.set(p, self.get(p))
        out = inner.transform(chunk_df).drop("_chunk")
        row_idx = np.asarray([i for i, _ in rows])
        return out.with_column("sourceRow", row_idx)
