"""CognitiveServiceBase — the one architecture all services share.

Reference ``cognitive/CognitiveServiceBase.scala``:
- every service argument is a ``ServiceParam`` settable as a scalar
  (``setX``) or per-row column (``setXCol``) (:28-101);
- ``transform`` assembles one HTTP request per row (subscription key
  header, url params, JSON body), sends through the retrying client stack,
  parses JSON into the output column with an error column for failures.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from ..core import Transformer, Param, ServiceParam, TypeConverters as TC
from ..core.contracts import HasOutputCol
from ..io.http.clients import AsyncClient, send_request
from ..io.http.schema import HTTPRequestData, HTTPResponseData
from ..resilience import breaker_for


class CognitiveServiceBase(Transformer, HasOutputCol):
    subscriptionKey = ServiceParam("subscriptionKey", "API key")
    url = Param("url", "full endpoint url", TC.toString, default="")
    errorCol = Param("errorCol", "error output column", TC.toString,
                     default="error")
    concurrency = Param("concurrency", "concurrent requests", TC.toInt,
                        default=1)
    timeout = Param("timeout", "per-request timeout (s)", TC.toFloat,
                    default=60.0)

    # subclasses override
    _method = "POST"
    _content_type = "application/json"
    # per-endpoint circuit breaker construction knobs (first creation
    # wins — breakers are shared process-wide by endpoint host)
    _breaker_config: dict = {"failure_threshold": 0.5, "min_calls": 4,
                             "window": 20, "reset_timeout": 5.0}

    def setLocation(self, location: str):
        """Region shorthand: fills url from the service's path template."""
        self.set("url", self._url_for_location(location))
        return self

    def _url_for_location(self, location: str) -> str:
        raise NotImplementedError(
            f"{type(self).__name__} has no location template; setUrl "
            "directly")

    # ------------------------------------------------------- value plumbing
    def _resolve(self, param_name: str, df, row: int, default=None):
        """ServiceParam resolution: {"value": v} | {"col": name} → value."""
        spec = self.get(param_name)
        if spec is None:
            return default
        if isinstance(spec, dict) and "col" in spec:
            return df[spec["col"]][row]
        if isinstance(spec, dict) and "value" in spec:
            return spec["value"]
        return spec

    @staticmethod
    def _jsonable(v: Any) -> Any:
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray):
            return v.tolist()
        return v

    # ------------------------------------------------------ request builder
    def _url_params(self, df, row: int) -> dict:
        return {}

    def _body(self, df, row: int) -> bytes | None:
        raise NotImplementedError

    def _headers(self, df, row: int) -> dict:
        h = {"Content-Type": self._content_type}
        key = self._resolve("subscriptionKey", df, row)
        if key:
            h["Ocp-Apim-Subscription-Key"] = str(key)
        return h

    def _build_url(self, df, row: int) -> str:
        url = self.get("url")
        params = {k: v for k, v in self._url_params(df, row).items()
                  if v is not None}
        if params:
            from urllib.parse import urlencode
            url = url + ("&" if "?" in url else "?") + urlencode(params)
        return url

    def _build_request(self, df, row: int) -> HTTPRequestData | None:
        return HTTPRequestData(url=self._build_url(df, row),
                               method=self._method,
                               headers=self._headers(df, row),
                               entity=self._body(df, row))

    def _parse_response(self, resp: HTTPResponseData) -> Any:
        return resp.json()

    # -------------------------------------------------------- client stack
    def _endpoint_key(self) -> str:
        """Breaker key: the endpoint host — one failure view per peer,
        shared by every service object talking to it."""
        from urllib.parse import urlparse
        url = self.get("url") or ""
        return urlparse(url).netloc or url or type(self).__name__

    def _guarded_sender(self):
        """The per-row sender, routed through the endpoint's circuit
        breaker (resilience subsystem): a dead endpoint degrades to
        instant error-column rows (503, ``Retry-After`` = the breaker's
        reset window) instead of burning one serial socket timeout per
        row; transport failures (status 0) and 5xx count against the
        breaker, everything the endpoint actually answered counts
        for it."""
        breaker = breaker_for(self._endpoint_key(), **self._breaker_config)

        def sender(req: HTTPRequestData, timeout: float) \
                -> HTTPResponseData:
            if not breaker.allow():
                return HTTPResponseData(
                    status_code=503,
                    reason=f"circuit open: {breaker.endpoint}",
                    headers={"Retry-After":
                             str(max(int(breaker.reset_timeout), 1))},
                    entity=None)
            resp = send_request(req, timeout)
            breaker.record(resp.status_code != 0
                           and resp.status_code < 500)
            return resp

        return sender

    def _client(self) -> AsyncClient:
        return AsyncClient(concurrency=self.get("concurrency"),
                           timeout=self.get("timeout"),
                           sender=self._guarded_sender())

    # ------------------------------------------------------------ transform
    def _transform(self, df):
        n = len(df)
        requests: list[HTTPRequestData | None] = [
            self._build_request(df, i) for i in range(n)]
        live = [(i, r) for i, r in enumerate(requests) if r is not None]
        client = self._client()
        responses = client.send([r for _, r in live])
        out = np.empty(n, object)
        err = np.empty(n, object)
        for (i, _), resp in zip(live, responses):
            if 200 <= resp.status_code < 300:
                try:
                    out[i] = self._parse_response(resp)
                    err[i] = None
                except Exception as e:
                    out[i] = None
                    err[i] = f"parse error: {e}"
            else:
                out[i] = None
                err[i] = {"statusCode": resp.status_code,
                          "reason": resp.reason,
                          "response": resp.entity.decode("utf-8", "replace")
                          if resp.entity else None}
        return (df.with_column(self.getOutputCol(), out)
                  .with_column(self.get("errorCol"), err))


class _AsyncReplyMixin:
    """Async-reply services (reference ``HasAsyncReply`` +
    ``BasicAsyncReply`` handler): the initial POST returns 202 with an
    ``Operation-Location`` header; the result is polled from that URL
    until status leaves the running states."""

    pollingDelay = Param("pollingDelay", "seconds between result polls",
                         TC.toFloat, default=0.3)
    maxPollingRetries = Param("maxPollingRetries", "max result polls",
                              TC.toInt, default=1000)
    suppressMaxRetriesExceededException = Param(
        "suppressMaxRetriesExceededException",
        "error-column instead of raising when polling exhausts",
        TC.toBoolean, default=False)

    _TERMINAL = ("succeeded", "failed", "partiallycompleted")

    def _poll(self, location: str, key: str | None, sender=None):
        import time
        headers = {}
        if key:
            headers["Ocp-Apim-Subscription-Key"] = str(key)
        # polls share the endpoint breaker with the POST path (sender =
        # _guarded_sender): once the endpoint dies mid-operation the
        # breaker opens and the remaining polls answer 503 locally
        # (terminal below) instead of burning maxPollingRetries socket
        # timeouts against a corpse
        sender = sender or self._guarded_sender()
        delay = self.get("pollingDelay")
        for _ in range(self.get("maxPollingRetries")):
            resp = sender(HTTPRequestData(
                url=location, method="GET", headers=headers),
                self.get("timeout"))
            if 200 <= resp.status_code < 300:
                parsed = resp.json()
                status = str(parsed.get("status", "")).lower()
                if status in self._TERMINAL:
                    return parsed, None
            elif resp.status_code >= 400 and resp.status_code != 429:
                # throttling (429) is transient — keep polling; other
                # 4xx/5xx are terminal for this operation
                return None, {"statusCode": resp.status_code,
                              "reason": resp.reason}
            time.sleep(delay)
        err = {"error": "max polling retries exceeded",
               "location": location}
        if self.get("suppressMaxRetriesExceededException"):
            return None, err
        raise TimeoutError(f"async operation never completed: {location}")

    def _transform(self, df):
        n = len(df)
        requests = [self._build_request(df, i) for i in range(n)]
        live = [(i, r) for i, r in enumerate(requests) if r is not None]
        # async-reply POSTs share the endpoint breaker with the sync path
        client = self._client()
        responses = client.send([r for _, r in live])
        out = np.empty(n, object)
        err = np.empty(n, object)
        pending = []  # (row, location, key) — polled concurrently below
        for (i, _), resp in zip(live, responses):
            if resp.status_code in (200, 201, 202):
                loc = {k.lower(): v for k, v in resp.headers.items()}.get(
                    "operation-location")
                if not loc:
                    out[i] = None
                    err[i] = {"error": "202 without Operation-Location"}
                    continue
                pending.append((i, loc,
                                self._resolve("subscriptionKey", df, i)))
            else:
                out[i] = None
                err[i] = {"statusCode": resp.status_code,
                          "reason": resp.reason,
                          "response": resp.entity.decode("utf-8", "replace")
                          if resp.entity else None}
        if pending:
            # operations run server-side in parallel; polling them
            # one-by-one would serialize the wall clock — reuse the same
            # concurrency the POST fan-out had
            from concurrent.futures import ThreadPoolExecutor
            workers = max(int(self.get("concurrency")), 1)
            sender = self._guarded_sender()
            with ThreadPoolExecutor(max_workers=workers) as pool:
                results = list(pool.map(
                    lambda p: self._poll(p[1], p[2], sender), pending))
            for (i, _, _), (res, e) in zip(pending, results):
                out[i], err[i] = res, e
        return (df.with_column(self.getOutputCol(), out)
                  .with_column(self.get("errorCol"), err))


class _JsonBodyService(CognitiveServiceBase):
    """Services posting a JSON object built from ServiceParams."""

    _body_params: tuple[str, ...] = ()

    def _body(self, df, row: int) -> bytes:
        payload = {}
        for name in self._body_params:
            v = self._resolve(name, df, row)
            if v is not None:
                payload[name] = self._jsonable(v)
        return json.dumps(payload).encode()


class _DocumentsService(CognitiveServiceBase):
    """Text Analytics shape: {"documents": [{id, text, language?}]}
    (reference ``cognitive/TextAnalytics.scala`` V3 schemas)."""

    text = ServiceParam("text", "document text")
    language = ServiceParam("language", "document language")

    def _body(self, df, row: int) -> bytes:
        doc = {"id": "0",
               "text": self._jsonable(self._resolve("text", df, row))}
        lang = self._resolve("language", df, row)
        if lang:
            doc["language"] = self._jsonable(lang)
        return json.dumps({"documents": [doc]}).encode()

    def _parse_response(self, resp: HTTPResponseData):
        parsed = resp.json()
        docs = parsed.get("documents") if isinstance(parsed, dict) else None
        return docs[0] if docs else parsed


class _ImageInputService(CognitiveServiceBase):
    """Vision/Face shape: either {"url": ...} JSON or raw image bytes
    (reference ``cognitive/ComputerVision.scala`` HasImageInput)."""

    imageUrl = ServiceParam("imageUrl", "image url")
    imageBytes = ServiceParam("imageBytes", "raw image bytes")

    def _body(self, df, row: int) -> bytes:
        url = self._resolve("imageUrl", df, row)
        if url is not None:
            return json.dumps({"url": str(url)}).encode()
        data = self._resolve("imageBytes", df, row)
        if data is None:
            raise ValueError("set imageUrl(Col) or imageBytes(Col)")
        return bytes(data)

    def _headers(self, df, row: int) -> dict:
        h = super()._headers(df, row)
        if self._resolve("imageUrl", df, row) is None:
            h["Content-Type"] = "application/octet-stream"
        return h
