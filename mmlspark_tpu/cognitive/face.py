"""Face API services.

Reference ``cognitive/Face.scala`` — detect, find similar, group,
identify, verify.
"""

from __future__ import annotations

import json

from ..core import ServiceParam
from .base import _ImageInputService, _JsonBodyService


class DetectFace(_ImageInputService):
    returnFaceId = ServiceParam("returnFaceId", "include face ids")
    returnFaceLandmarks = ServiceParam("returnFaceLandmarks",
                                       "include landmarks")
    returnFaceAttributes = ServiceParam("returnFaceAttributes",
                                        "age,gender,emotion,...")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/face/v1.0/detect")

    def _url_params(self, df, row):
        attrs = self._resolve("returnFaceAttributes", df, row)
        return {"returnFaceId": self._resolve("returnFaceId", df, row),
                "returnFaceLandmarks": self._resolve("returnFaceLandmarks",
                                                     df, row),
                "returnFaceAttributes": ",".join(attrs) if isinstance(
                    attrs, (list, tuple)) else attrs}


class FindSimilarFace(_JsonBodyService):
    faceId = ServiceParam("faceId", "query face id")
    faceIds = ServiceParam("faceIds", "candidate face ids")
    maxNumOfCandidatesReturned = ServiceParam(
        "maxNumOfCandidatesReturned", "max matches")
    mode = ServiceParam("mode", "matchPerson | matchFace")
    _body_params = ("faceId", "faceIds", "maxNumOfCandidatesReturned",
                    "mode")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/face/v1.0/findsimilars")


class GroupFaces(_JsonBodyService):
    faceIds = ServiceParam("faceIds", "face ids to cluster")
    _body_params = ("faceIds",)

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/face/v1.0/group")


class IdentifyFaces(_JsonBodyService):
    faceIds = ServiceParam("faceIds", "face ids to identify")
    personGroupId = ServiceParam("personGroupId", "person group")
    maxNumOfCandidatesReturned = ServiceParam(
        "maxNumOfCandidatesReturned", "candidates per face")
    confidenceThreshold = ServiceParam("confidenceThreshold",
                                       "min confidence")
    _body_params = ("faceIds", "personGroupId",
                    "maxNumOfCandidatesReturned", "confidenceThreshold")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/face/v1.0/identify")


class VerifyFaces(_JsonBodyService):
    faceId1 = ServiceParam("faceId1", "first face")
    faceId2 = ServiceParam("faceId2", "second face")
    _body_params = ("faceId1", "faceId2")

    def _url_for_location(self, location: str) -> str:
        return (f"https://{location}.api.cognitive.microsoft.com"
                f"/face/v1.0/verify")
