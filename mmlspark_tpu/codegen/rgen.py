"""R binding generation (reticulate-backed).

Reference ``codegen/Wrappable.scala:471-495`` (``RWrappable``): every stage
renders a sparklyr-style R function ``ml_<snake_case_name>(...)`` with the
full param surface. The reference calls into the JVM via sparklyr's
invoke; here the generated functions call the Python package through
``reticulate`` — the R-native path to a Python/JAX runtime.

Output: one ``R/<package>.R`` file per stage package plus a loader, all
plain text (no R toolchain required to generate; an R runtime with
``reticulate`` is required to *use* them).
"""

from __future__ import annotations

import inspect
import os
import re
from collections import defaultdict

from ..core import ServiceParam
from ..testing.fuzzing import iter_stage_classes
from .wrappable import param_type_hint

_R_DEFAULTS = {
    "int": "NULL", "float": "NULL", "bool": "NULL", "str": "NULL",
    "list[str]": "NULL", "list[int]": "NULL", "list[float]": "NULL",
    "dict": "NULL", "Any": "NULL",
}


def snake_case(name: str) -> str:
    s = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name)
    return re.sub(r"([A-Z]+)([A-Z][a-z])", r"\1_\2", s).lower()


def r_function_for(cls) -> str:
    """One R wrapper function (reference RWrappable.rClass)."""
    fn = "ml_" + snake_case(cls.__name__)
    params = sorted(cls.params(), key=lambda p: p.name)
    arg_names = [snake_case(p.name) for p in params]
    args = ", ".join(f"{a} = {_R_DEFAULTS.get(param_type_hint(p), 'NULL')}"
                     for a, p in zip(arg_names, params))
    doc = (inspect.getdoc(cls) or "").splitlines()
    title = doc[0] if doc else cls.__name__
    lines = [
        f"#' {title}",
        "#'",
    ]
    for p, a in zip(params, arg_names):
        lines.append(f"#' @param {a} {p.doc}")
    lines += [
        "#' @export",
        f"{fn} <- function({args}) {{" if args else f"{fn} <- function() {{",
        f"  mod <- reticulate::import(\"{cls.__module__}\")",
        "  kwargs <- list()",
    ]
    for p, a in zip(params, arg_names):
        lines.append(f"  if (!is.null({a})) kwargs[[\"{p.name}\"]] <- {a}")
    lines += [
        f"  do.call(mod${cls.__name__}, kwargs)",
        "}",
    ]
    # ServiceParams additionally get the Col-binding setter the Scala
    # codegen exposes (setXCol)
    for p, a in zip(params, arg_names):
        if isinstance(p, ServiceParam):
            lines += [
                "",
                f"#' Bind the {p.name} argument of a fitted stage to a "
                "column",
                "#' @export",
                f"{fn}_set_{a}_col <- function(stage, col) {{",
                f"  stage$set{p.name[0].upper() + p.name[1:]}Col(col)",
                "}",
            ]
    return "\n".join(lines)


def generate_r(out_dir: str) -> list[str]:
    """Write an INSTALLABLE R package layout (reference
    ``Wrappable.scala:471-495`` emits a full sparklyr package):

        <out_dir>/DESCRIPTION          package metadata + reticulate dep
        <out_dir>/NAMESPACE            export() directive per wrapper
        <out_dir>/R/<package>.R        roxygen-documented wrappers
        <out_dir>/R/zzz.R              .onLoad python-availability check

    ``R CMD INSTALL <out_dir>`` (or ``devtools::load_all``) loads it."""
    by_pkg: dict[str, list] = defaultdict(list)
    for cls in iter_stage_classes():
        by_pkg[cls.__module__.split(".")[1]].append(cls)
    r_dir = os.path.join(out_dir, "R")
    os.makedirs(r_dir, exist_ok=True)
    written = []
    exports: list[str] = []
    for pkg, classes in sorted(by_pkg.items()):
        path = os.path.join(r_dir, f"{pkg}.R")
        body = "\n\n\n".join(
            r_function_for(c)
            for c in sorted(classes, key=lambda c: c.__name__))
        with open(path, "w") as f:
            f.write("# Auto-generated R bindings — regenerate with\n"
                    "#   python -m mmlspark_tpu.codegen\n\n" + body + "\n")
        written.append(path)
        for c in sorted(classes, key=lambda c: c.__name__):
            fn = "ml_" + snake_case(c.__name__)
            exports.append(fn)
            for p in c.params():
                if isinstance(p, ServiceParam):
                    exports.append(f"{fn}_set_{snake_case(p.name)}_col")
    loader = os.path.join(r_dir, "zzz.R")
    with open(loader, "w") as f:
        f.write(
            "# package hooks: verify the Python side is importable\n"
            ".onLoad <- function(libname, pkgname) {\n"
            "  if (!reticulate::py_module_available(\"mmlspark_tpu\"))\n"
            "    warning(\"python package mmlspark_tpu not found; \",\n"
            "            \"install it in the active python env\")\n"
            "}\n")
    written.append(loader)
    desc = os.path.join(out_dir, "DESCRIPTION")
    with open(desc, "w") as f:
        f.write(
            "Package: mmlsparktpu\n"
            "Type: Package\n"
            "Title: R Bindings for the mmlspark_tpu Framework\n"
            "Version: 0.1.0\n"
            "Description: Auto-generated wrappers over the Python\n"
            "    mmlspark_tpu package (pipeline stages, distributed\n"
            "    GBDT, featurizers, serving) via reticulate.\n"
            "License: MIT\n"
            "Encoding: UTF-8\n"
            "Imports: reticulate\n"
            "RoxygenNote: 7.0.0\n")
    written.append(desc)
    ns = os.path.join(out_dir, "NAMESPACE")
    with open(ns, "w") as f:
        f.write("# Auto-generated — regenerate with "
                "python -m mmlspark_tpu.codegen\n"
                + "".join(f"export({e})\n" for e in sorted(exports)))
    written.append(ns)
    return written
