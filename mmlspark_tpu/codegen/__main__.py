"""CLI: ``python -m mmlspark_tpu.codegen [out_dir]`` — the reference's sbt
``codegen`` task (``build.sbt:113-120``)."""

import sys

from . import generate_all

if __name__ == "__main__":
    out = generate_all(sys.argv[1] if len(sys.argv) > 1 else "generated")
    print(f"wrote {len(out['stubs'])} stub files, {len(out['r'])} R "
          f"files, and {out['docs']}")
