"""Vendored R syntax checker for the generated bindings.

No R runtime exists in this build environment (VERDICT r3 Weak #7), so
the generated package cannot be smoke-loaded; this module pins the next
best guarantee: every generated ``.R`` file passes a real lexical parse
— string- and comment-aware delimiter matching, function-definition
argument grammar, and roxygen tag validity — instead of the previous
brace-counting heuristic (which a brace inside a string literal or
comment would both fool).

Scope: the R subset the generator emits (``rgen.py``) — function
definitions, calls, ``list()``, ``if``, ``$`` access, strings,
``NULL`` defaults, roxygen comments. It is a validator for OUR
templates, not a general R parser.
"""

from __future__ import annotations

import re

_OPENERS = {"(": ")", "{": "}", "[": "]"}
_CLOSERS = {v: k for k, v in _OPENERS.items()}
_ROXYGEN_TAGS = {"param", "export", "return", "title", "description"}
_IDENT = re.compile(r"^[a-zA-Z.][a-zA-Z0-9._]*$")


class RSyntaxError(ValueError):
    def __init__(self, path: str, line: int, message: str):
        super().__init__(f"{path}:{line}: {message}")
        self.path, self.line, self.message = path, line, message


def _lex(text: str, path: str) -> list[tuple[str, int]]:
    """Strip comments and collapse string literals (string- and
    escape-aware), returning (delimiter-or-code char, line) events for
    the matcher. Raises on an unterminated string."""
    events: list[tuple[str, int]] = []
    line = 1
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch == "\n":
            line += 1
            i += 1
        elif ch == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif ch in ('"', "'"):
            quote, start = ch, line
            i += 1
            while i < n:
                if text[i] == "\\":
                    i += 2
                    continue
                if text[i] == "\n":
                    line += 1
                if text[i] == quote:
                    break
                i += 1
            else:
                raise RSyntaxError(path, start, "unterminated string")
            i += 1
        else:
            if ch in _OPENERS or ch in _CLOSERS:
                events.append((ch, line))
            i += 1
    return events


def _check_delimiters(text: str, path: str) -> None:
    stack: list[tuple[str, int]] = []
    for ch, line in _lex(text, path):
        if ch in _OPENERS:
            stack.append((ch, line))
        else:
            if not stack:
                raise RSyntaxError(path, line, f"unmatched {ch!r}")
            opener, oline = stack.pop()
            if _OPENERS[opener] != ch:
                raise RSyntaxError(
                    path, line,
                    f"mismatched {ch!r} (opened {opener!r} at line "
                    f"{oline})")
    if stack:
        opener, oline = stack[-1]
        raise RSyntaxError(path, oline, f"unclosed {opener!r}")


def _split_args(argstr: str) -> list[str]:
    """Split a definition arg list on top-level commas (string- and
    paren-aware)."""
    out, depth, cur, in_str = [], 0, [], ""
    i = 0
    while i < len(argstr):
        ch = argstr[i]
        if in_str:
            if ch == "\\":
                cur.append(argstr[i:i + 2])
                i += 2
                continue
            if ch == in_str:
                in_str = ""
        elif ch in ('"', "'"):
            in_str = ch
        elif ch in _OPENERS:
            depth += 1
        elif ch in _CLOSERS:
            depth -= 1
        elif ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(ch)
        i += 1
    if cur:
        out.append("".join(cur))
    return out


_FUNDEF = re.compile(
    r"^([a-zA-Z.][a-zA-Z0-9._]*)\s*<-\s*function\s*\((.*)\)\s*\{\s*$")


def _check_fundefs(text: str, path: str) -> list[str]:
    """Validate every single-line function definition the generator
    emits; returns the defined names."""
    defined = []
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if "<- function" not in stripped or stripped.startswith("#"):
            continue
        m = _FUNDEF.match(stripped)
        if m is None:
            raise RSyntaxError(path, lineno,
                               f"malformed function definition: "
                               f"{stripped[:60]!r}")
        defined.append(m.group(1))
        for arg in _split_args(m.group(2)):
            arg = arg.strip()
            if not arg:
                continue
            name = arg.split("=", 1)[0].strip()
            if not _IDENT.match(name):
                raise RSyntaxError(
                    path, lineno, f"invalid argument name {name!r}")
    return defined


def _check_roxygen(text: str, path: str) -> None:
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped.startswith("#'"):
            continue
        body = stripped[2:].strip()
        if body.startswith("@"):
            tag = body[1:].split(None, 1)[0]
            if tag not in _ROXYGEN_TAGS:
                raise RSyntaxError(path, lineno,
                                   f"unknown roxygen tag @{tag}")
            if tag == "param" and len(body.split(None, 2)) < 2:
                raise RSyntaxError(path, lineno,
                                   "@param without a name")


def check_r_source(text: str, path: str = "<string>") -> list[str]:
    """Full check of one generated R source; returns defined function
    names."""
    _check_delimiters(text, path)
    _check_roxygen(text, path)
    return _check_fundefs(text, path)


def check_package(out_dir: str) -> dict[str, list[str]]:
    """Validate a generated package tree (every R/*.R + NAMESPACE
    export coverage). Returns {file: defined function names}."""
    import os
    r_dir = os.path.join(out_dir, "R")
    result: dict[str, list[str]] = {}
    defined: set[str] = set()
    for name in sorted(os.listdir(r_dir)):
        if not name.endswith(".R"):
            continue
        path = os.path.join(r_dir, name)
        with open(path) as f:
            fns = check_r_source(f.read(), path)
        result[name] = fns
        defined.update(fns)
    ns_path = os.path.join(out_dir, "NAMESPACE")
    with open(ns_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.fullmatch(r"export\(([a-zA-Z.][a-zA-Z0-9._]*)\)", line)
            if m is None:
                raise RSyntaxError(ns_path, lineno,
                                   f"malformed NAMESPACE line {line!r}")
            if m.group(1) not in defined:
                raise RSyntaxError(
                    ns_path, lineno,
                    f"export({m.group(1)}) has no definition")
    return result
