"""API binding generator.

Reference L10 (SURVEY §2.12): ``codegen/Wrappable.scala`` renders PySpark
and sparklyr wrappers for ~120 stages by reflecting over their Params.
This framework's public API is already Python, so the generator's jobs
become:

- typed ``.pyi`` stubs making the synthesized ``setX``/``getX`` accessors
  static (IDE/typing parity with the reference's generated classes);
- a markdown API reference (the reference's generated pydocs);
- an installable R package layout (DESCRIPTION/NAMESPACE + roxygen
  wrappers over reticulate, the sparklyr-equivalent surface);
- a PySpark-facing wrapper package whose fluent ``setX``/``getX``
  classes ingest Spark DataFrames over the Arrow bridge (generation
  needs no pyspark installed; only *using* the Spark ingestion path
  does).
"""

from .pygen import generate_pyspark, pyspark_class_for
from .rcheck import RSyntaxError, check_package, check_r_source
from .rgen import generate_r, r_function_for, snake_case
from .wrappable import (generate_all, generate_docs, generate_stubs,
                        param_type_hint, py_stub_for)

__all__ = ["generate_r", "r_function_for", "snake_case",
           "check_package", "check_r_source", "RSyntaxError",
           "generate_all", "generate_docs", "generate_stubs",
           "generate_pyspark", "pyspark_class_for",
           "param_type_hint", "py_stub_for"]
