"""PySpark-facing wrapper generation.

Reference ``codegen/Wrappable.scala:70-468`` (``PythonWrappable``): every
stage renders a complete PySpark wrapper class with fluent
``setX``/``getX`` accessors, so Spark users drive the framework without
learning a new surface. Here the generated wrappers accept
``pyspark.sql.DataFrame`` inputs and move data over the Arrow bridge
(``core/arrow.py``) into the TPU engine — columns, vectors and
dictionary-encoded categoricals land zero-copy/metadata-correct — then
hand results back as Arrow/pandas for Spark re-ingestion.

Generation is pure reflection over ``Params.params()`` (the same walk as
the stub/R generators); the emitted package imports only
``mmlspark_tpu`` at runtime and degrades gracefully when pyspark is
absent (plain DataFrames pass through untouched), so the wrappers are
testable without a Spark installation.
"""

from __future__ import annotations

import inspect
import os
from collections import defaultdict

from ..testing.fuzzing import iter_stage_classes
from .wrappable import param_type_hint, _accessor

_RUNTIME = '''\
"""Runtime shims for the generated PySpark wrappers (auto-generated)."""

from mmlspark_tpu.core import DataFrame as _TpuDataFrame


def to_tpu(df):
    """pyspark.sql.DataFrame | pandas | Arrow | mmlspark_tpu DataFrame
    → mmlspark_tpu DataFrame, through Arrow wherever possible."""
    if isinstance(df, _TpuDataFrame):
        return df
    mod = type(df).__module__
    if mod.startswith("pyspark"):
        if hasattr(df, "toArrow"):          # Spark >= 4
            return _TpuDataFrame.from_arrow(df.toArrow())
        if hasattr(df, "_collect_as_arrow"):  # Spark 3.x fast path
            return _TpuDataFrame.from_arrow_batches(
                iter(df._collect_as_arrow()))
        return _TpuDataFrame.from_pandas(df.toPandas())
    if mod.startswith("pandas"):
        return _TpuDataFrame.from_pandas(df)
    if mod.startswith("pyarrow"):
        return _TpuDataFrame.from_arrow(df)
    raise TypeError(f"cannot ingest {type(df)!r}")


def from_tpu(df, like=None):
    """mmlspark_tpu DataFrame → the caller's ecosystem: a Spark session
    (when ``like`` is a pyspark DataFrame) re-ingests via Arrow/pandas;
    otherwise the columnar frame passes through."""
    if like is not None and type(like).__module__.startswith("pyspark"):
        spark = like.sparkSession
        try:
            return spark.createDataFrame(df.to_arrow())
        except Exception:
            return spark.createDataFrame(df.to_pandas())
    return df


class WrappedModel:
    """Generic fitted-model wrapper: transform + save + attribute pass-
    through to the underlying mmlspark_tpu model."""

    def __init__(self, inner):
        self._inner = inner

    def transform(self, df):
        return from_tpu(self._inner.transform(to_tpu(df)), like=df)

    def save(self, path):
        self._inner.save(path)
        return self

    def __getattr__(self, name):
        return getattr(self._inner, name)
'''


def pyspark_class_for(cls) -> str:
    """One generated wrapper class (reference
    ``PythonWrappable.pyClass``)."""
    params = sorted(cls.params(), key=lambda p: p.name)
    doc = (inspect.getdoc(cls) or cls.__name__).splitlines()[0]
    lines = [
        f"class {cls.__name__}:",
        f'    """{doc}',
        "",
        "    Generated PySpark-facing wrapper over"
        f" ``{cls.__module__}.{cls.__name__}``.",
        '    """',
        "",
        "    def __init__(self, **kwargs):",
        f"        from {cls.__module__} import {cls.__name__} as _Inner",
        "        self._inner = _Inner(**kwargs)",
        "",
    ]
    for p in params:
        acc = _accessor(p.name)
        hint = param_type_hint(p)
        lines += [
            f"    def set{acc}(self, value: {hint})"
            f" -> \"{cls.__name__}\":",
            f"        self._inner.set({p.name!r}, value)",
            "        return self",
            "",
            f"    def get{acc}(self) -> {hint}:",
            f"        return self._inner.get({p.name!r})",
            "",
        ]
    from ..core import Estimator, Transformer
    if issubclass(cls, Estimator):
        lines += [
            "    def fit(self, df):",
            "        return _rt.WrappedModel(self._inner.fit("
            "_rt.to_tpu(df)))",
            "",
        ]
    if issubclass(cls, Transformer) and not issubclass(cls, Estimator):
        lines += [
            "    def transform(self, df):",
            "        return _rt.from_tpu(self._inner.transform("
            "_rt.to_tpu(df)), like=df)",
            "",
        ]
    lines += [
        "    def save(self, path):",
        "        self._inner.save(path)",
        "        return self",
    ]
    return "\n".join(lines)


def generate_pyspark(out_dir: str) -> list[str]:
    """Write the PySpark wrapper package: one module per stage package
    plus the runtime shim; importable as a plain directory package."""
    by_pkg: dict[str, list] = defaultdict(list)
    for cls in iter_stage_classes():
        by_pkg[cls.__module__.split(".")[1]].append(cls)
    os.makedirs(out_dir, exist_ok=True)
    written = []
    rt_path = os.path.join(out_dir, "_runtime.py")
    with open(rt_path, "w") as f:
        f.write(_RUNTIME)
    written.append(rt_path)
    header = ("# Auto-generated PySpark wrappers — regenerate with\n"
              "#   python -m mmlspark_tpu.codegen\n"
              "from typing import Any\n"
              "from . import _runtime as _rt\n\n\n")
    pkg_names = []
    for pkg, classes in sorted(by_pkg.items()):
        path = os.path.join(out_dir, f"{pkg}.py")
        body = "\n\n\n".join(
            pyspark_class_for(c)
            for c in sorted(classes, key=lambda c: c.__name__))
        with open(path, "w") as f:
            f.write(header + body + "\n")
        written.append(path)
        pkg_names.append(pkg)
    init = os.path.join(out_dir, "__init__.py")
    with open(init, "w") as f:
        f.write("# Auto-generated PySpark wrapper package\n"
                + "".join(f"from . import {p}\n" for p in pkg_names))
    written.append(init)
    return written
