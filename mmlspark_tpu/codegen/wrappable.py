"""Reflection-driven stub + doc generation.

Reference ``codegen/Wrappable.scala:33-67`` maps each Param to a typed
``ParamInfo`` and renders getters/setters; ``:70-...`` renders the wrapper
class. The same reflection here walks ``Params.params()``.
"""

from __future__ import annotations

import inspect
import os
from collections import defaultdict

from ..core import Estimator, Param, ComplexParam, ServiceParam, \
    Transformer
from ..core.param import TypeConverters as TC
from ..testing.fuzzing import iter_stage_classes

_CONVERTER_HINTS = {
    "toInt": "int", "toFloat": "float", "toBoolean": "bool",
    "toString": "str", "toListString": "list[str]",
    "toListInt": "list[int]", "toListFloat": "list[float]",
    "toDict": "dict", "identity": "Any",
}


def param_type_hint(p: Param) -> str:
    """Reference ``ParamInfo`` type mapping (Wrappable.scala:33-67)."""
    if isinstance(p, ServiceParam):
        return "Any"
    if isinstance(p, ComplexParam):
        return "Any"
    for name, hint in _CONVERTER_HINTS.items():
        if p.converter is getattr(TC, name, None):
            return hint
    return "Any"


def _accessor(name: str) -> str:
    return name[0].upper() + name[1:]


def stub_base_imports(classes) -> list[str]:
    """Import lines resolving every base class used by the stubs (pyright
    needs real names, including private bases like _LightGBMBase)."""
    local = {c.__name__ for c in classes}
    imports = set()
    for cls in classes:
        for b in cls.__bases__:
            if b is object or b.__name__ in local:
                continue
            imports.add(f"from {b.__module__} import {b.__name__}")
    return sorted(imports)


def py_stub_for(cls) -> str:
    """One class stub with typed synthesized accessors."""
    bases = [b.__name__ for b in cls.__bases__ if b is not object] or \
        ["object"]
    lines = [f"class {cls.__name__}({', '.join(bases)}):"]
    doc = inspect.getdoc(cls)
    if doc:
        first = doc.splitlines()[0]
        lines.append(f'    """{first}"""')
    params = sorted(cls.params(), key=lambda p: p.name)
    if not params:
        lines.append("    ...")
        return "\n".join(lines)
    init_args = ", ".join(
        f"{p.name}: {param_type_hint(p)} = ..." for p in params)
    lines.append(f"    def __init__(self, *, {init_args}) -> None: ...")
    for p in params:
        hint = param_type_hint(p)
        acc = _accessor(p.name)
        lines.append(
            f"    def set{acc}(self, value: {hint}) ->"
            f" \"{cls.__name__}\": ...")
        lines.append(f"    def get{acc}(self) -> {hint}: ...")
        if isinstance(p, ServiceParam):
            lines.append(
                f"    def set{acc}Col(self, col: str) ->"
                f" \"{cls.__name__}\": ...")
            lines.append(f"    def get{acc}Col(self) -> str | None: ...")
    return "\n".join(lines)


def generate_stubs(out_dir: str) -> list[str]:
    """Write one ``<module>.pyi``-style stub file per stage module."""
    by_module: dict[str, list] = defaultdict(list)
    for cls in iter_stage_classes():
        by_module[cls.__module__].append(cls)
    written = []
    os.makedirs(out_dir, exist_ok=True)
    for module, classes in sorted(by_module.items()):
        path = os.path.join(out_dir, module.replace(".", "_") + ".pyi")
        header = ("# Auto-generated API stubs — regenerate with\n"
                  "#   python -m mmlspark_tpu.codegen\n"
                  "from typing import Any\n"
                  + "\n".join(stub_base_imports(classes)) + "\n\n")
        body = "\n\n\n".join(
            py_stub_for(c) for c in
            sorted(classes, key=lambda c: c.__name__))
        with open(path, "w") as f:
            f.write(header + body + "\n")
        written.append(path)
    return written


def generate_docs(out_path: str) -> str:
    """Markdown API reference (the reference's generated sphinx docs)."""
    sections: dict[str, list[str]] = defaultdict(list)
    for cls in iter_stage_classes():
        pkg = cls.__module__.split(".")[1]
        kind = ("Estimator" if issubclass(cls, Estimator) else
                "Transformer" if issubclass(cls, Transformer) else "Model")
        doc = (inspect.getdoc(cls) or "").splitlines()
        summary = doc[0] if doc else ""
        rows = [f"### `{cls.__name__}` ({kind})", "", summary, "",
                "| param | type | default | doc |",
                "|---|---|---|---|"]
        for p in sorted(cls.params(), key=lambda p: p.name):
            default = p.default if p.has_default else "—"
            rows.append(f"| `{p.name}` | {param_type_hint(p)} | "
                        f"`{default}` | {p.doc} |")
        sections[pkg].append("\n".join(rows))
    out = ["# mmlspark_tpu API reference", ""]
    for pkg in sorted(sections):
        out.append(f"## {pkg}")
        out.append("")
        out.extend(sections[pkg])
        out.append("")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    content = "\n".join(out)
    with open(out_path, "w") as f:
        f.write(content)
    return out_path


def generate_all(base_dir: str = "generated") -> dict:
    from .pygen import generate_pyspark
    from .rgen import generate_r
    stubs = generate_stubs(os.path.join(base_dir, "stubs"))
    docs = generate_docs(os.path.join(base_dir, "docs", "api.md"))
    r = generate_r(os.path.join(base_dir, "r_package"))
    pyspark = generate_pyspark(os.path.join(base_dir, "pyspark",
                                            "mmlspark_tpu_spark"))
    return {"stubs": stubs, "docs": docs, "r": r, "pyspark": pyspark}
