"""LIME — model-agnostic local explanations at scale.

Reference ``lime/`` (SURVEY §2.10): ``TabularLIME`` (:169), ``ImageLIME``
(:262, superpixel masking), ``TextLIME`` (word-level), with local linear
fits via least squares (``lime/BreezeUtils.scala``). TPU framing: mask
sampling is one RNG batch, perturbed predictions one batched transform,
and the per-row weighted least-squares solves are a single vmapped
``jnp.linalg.lstsq``.
"""

from .lime import TabularLIME, TabularLIMEModel, ImageLIME, TextLIME
from .superpixel import Superpixel, SuperpixelTransformer

__all__ = ["TabularLIME", "TabularLIMEModel", "ImageLIME", "TextLIME", "Superpixel",
           "SuperpixelTransformer"]
