"""LIME explainers: tabular, image, text.

Reference ``lime/LIME.scala`` — TabularLIME (:169): perturb each row with
Gaussian noise around feature statistics, score through the model, fit a
weighted linear surrogate; ImageLIME (:262): mask superpixels
(``:33-45`` mask sampling), score, fit; TextLIME: mask words. All local
fits are one vmapped weighted least-squares batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, DataFrame, Transformer, Param, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from ..core.utils import as_2d_features
from .superpixel import Superpixel


@jax.jit
def _weighted_lstsq(X, y, w):
    """One ridge-stabilized weighted least squares: X [S, F+1], y [S],
    w [S] → coef [F+1]."""
    sw = jnp.sqrt(w)[:, None]
    A = X * sw
    b = y * sw[:, 0]
    AtA = A.T @ A + 1e-6 * jnp.eye(X.shape[1])
    return jnp.linalg.solve(AtA, A.T @ b)


_batched_lstsq = jax.jit(jax.vmap(_weighted_lstsq))


def _surrogate_fit(masks: np.ndarray, preds: np.ndarray,
                   kernel_width: float) -> np.ndarray:
    """masks [R, S, F] binary, preds [R, S] → coefs [R, F]."""
    R, S, F = masks.shape
    ones = np.ones((R, S, 1), np.float32)
    X = jnp.asarray(np.concatenate([masks, ones], axis=2))
    y = jnp.asarray(preds)
    # LIME proximity kernel: exp(-d²/width²), d = fraction masked off
    d = 1.0 - masks.mean(axis=2)
    w = jnp.asarray(np.exp(-(d ** 2) / kernel_width ** 2))
    coefs = _batched_lstsq(X, y, w)
    return np.asarray(coefs)[:, :F]


class _LIMEBase(Transformer, HasInputCol, HasOutputCol):
    model = ComplexParam("model", "transformer to explain")
    predictionCol = Param("predictionCol",
                          "column of the model's output to explain",
                          TC.toString, default="prediction")
    nSamples = Param("nSamples", "perturbations per row", TC.toInt,
                     default=100)
    kernelWidth = Param("kernelWidth", "proximity kernel width", TC.toFloat,
                        default=0.75)
    seed = Param("seed", "sampling seed", TC.toInt, default=0)

    def _predict(self, df) -> np.ndarray:
        scored = self.get("model").transform(df)
        p = np.asarray(scored[self.get("predictionCol")], np.float64)
        return p[:, -1] if p.ndim == 2 else p


class TabularLIME(_LIMEBase):
    """Per-feature linear attribution for vector-feature rows."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="features", outputCol="weights")

    def _transform(self, df):
        x = as_2d_features(df, self.getInputCol()).astype(np.float32)
        n, F = x.shape
        S = self.get("nSamples")
        rng = np.random.default_rng(self.get("seed"))
        sigma = x.std(axis=0, keepdims=True) + 1e-9

        # binary on/off masks: off = feature replaced by its mean
        masks = (rng.random((n, S, F)) < 0.5).astype(np.float32)
        mean = x.mean(axis=0, keepdims=True)
        perturbed = masks * x[:, None, :] + (1 - masks) * mean[None]
        del sigma

        flat = perturbed.reshape(n * S, F)
        preds = self._predict(
            DataFrame({self.getInputCol(): flat})).reshape(n, S)
        coefs = _surrogate_fit(masks, preds.astype(np.float32),
                               self.get("kernelWidth"))
        return df.with_column(self.getOutputCol(),
                              coefs.astype(np.float64))


class ImageLIME(_LIMEBase):
    """Superpixel attribution (reference ``ImageLIME``, ``LIME.scala:262``):
    perturbations turn superpixels gray; output = weight per superpixel."""

    superpixelCol = Param("superpixelCol", "precomputed superpixel labels "
                          "('' = compute)", TC.toString, default="")
    cellSize = Param("cellSize", "superpixel size", TC.toFloat,
                     default=16.0)
    modifier = Param("modifier", "SLIC compactness", TC.toFloat,
                     default=130.0)
    samplingFraction = Param("samplingFraction",
                             "P(superpixel stays on)", TC.toFloat,
                             default=0.7)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="weights")

    def _transform(self, df):
        col = df[self.getInputCol()]
        images = list(col) if col.dtype == object else [a for a in col]
        S = self.get("nSamples")
        rng = np.random.default_rng(self.get("seed"))
        spx_col = self.get("superpixelCol")

        weights_out = np.empty(len(images), object)
        spx_out = np.empty(len(images), object)
        for r, img in enumerate(images):
            img = np.asarray(img, np.float32)
            labels = (np.asarray(df[spx_col][r]) if spx_col
                      else Superpixel.cluster(img, self.get("cellSize"),
                                              self.get("modifier")))
            K = int(labels.max()) + 1
            masks = (rng.random((S, K))
                     < self.get("samplingFraction")).astype(np.float32)
            onoff = masks[:, labels]                  # [S, H, W]
            gray = img.mean()
            batch = (onoff[..., None] * img[None]
                     + (1 - onoff[..., None]) * gray)
            preds = self._predict(
                DataFrame({self.getInputCol(): batch.astype(np.float32)}))
            coefs = _surrogate_fit(masks[None], preds[None].astype(
                np.float32), self.get("kernelWidth"))[0]
            weights_out[r] = coefs
            spx_out[r] = labels
        out = df.with_column(self.getOutputCol(), weights_out)
        if not spx_col:
            out = out.with_column("superpixels", spx_out)
        return out


class TextLIME(_LIMEBase):
    """Word-level attribution (reference ``TextLIME.scala``): mask tokens,
    score, fit; output = weight per token."""

    tokensCol = Param("tokensCol", "output column for the tokens",
                      TC.toString, default="tokens")
    samplingFraction = Param("samplingFraction", "P(token stays)",
                             TC.toFloat, default=0.7)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="text", outputCol="weights")

    def _transform(self, df):
        texts = [str(t) for t in df[self.getInputCol()]]
        S = self.get("nSamples")
        rng = np.random.default_rng(self.get("seed"))
        weights_out = np.empty(len(texts), object)
        tokens_out = np.empty(len(texts), object)
        for r, text in enumerate(texts):
            toks = text.split()
            K = max(len(toks), 1)
            masks = (rng.random((S, K))
                     < self.get("samplingFraction")).astype(np.float32)
            variants = [" ".join(t for t, m in zip(toks, row) if m > 0)
                        for row in masks]
            col = np.empty(S, object)
            col[:] = variants
            preds = self._predict(DataFrame({self.getInputCol(): col}))
            coefs = _surrogate_fit(masks[None],
                                   preds[None].astype(np.float32),
                                   self.get("kernelWidth"))[0]
            weights_out[r] = coefs
            tokens_out[r] = toks
        return (df.with_column(self.getOutputCol(), weights_out)
                  .with_column(self.get("tokensCol"), tokens_out))
