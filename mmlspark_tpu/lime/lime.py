"""LIME explainers: tabular, image, text.

Reference ``lime/LIME.scala`` — TabularLIME (:169): perturb each row with
Gaussian noise around feature statistics, score through the model, fit a
weighted linear surrogate; ImageLIME (:262): mask superpixels
(``:33-45`` mask sampling), score, fit; TextLIME: mask words. All local
fits are one vmapped weighted least-squares batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, DataFrame, Estimator, Model, \
    Transformer, Param, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from ..core.utils import as_2d_features
from .superpixel import Superpixel


@jax.jit
def _weighted_lstsq(X, y, w, reg):
    """One ridge-stabilized weighted least squares: X [S, F+1], y [S],
    w [S] → coef [F+1]. ``reg`` is the user regularization (reference
    LIME's ``regularization``, a ridge here) on top of a 1e-6
    stabilizer — the INTERCEPT column only gets the stabilizer (LIME
    never shrinks the baseline; a shrunk intercept leaks the model's
    baseline into every feature weight)."""
    sw = jnp.sqrt(w)[:, None]
    A = X * sw
    b = y * sw[:, 0]
    eye = jnp.eye(X.shape[1])
    penalty = reg * eye.at[-1, -1].set(0.0) + 1e-6 * eye
    AtA = A.T @ A + penalty
    return jnp.linalg.solve(AtA, A.T @ b)


_batched_lstsq = jax.jit(jax.vmap(_weighted_lstsq,
                                  in_axes=(0, 0, 0, None)))


def _fit_surrogates(feats: np.ndarray, preds: np.ndarray,
                    w: np.ndarray, regularization: float) -> np.ndarray:
    """Shared fit core: feats [R, S, F] + intercept column → [R, F]."""
    R, S, F = feats.shape
    ones = np.ones((R, S, 1), np.float32)
    X = jnp.asarray(np.concatenate([feats, ones], axis=2))
    coefs = _batched_lstsq(X, jnp.asarray(preds), jnp.asarray(w),
                           jnp.float32(regularization))
    return np.asarray(coefs)[:, :F]


def _surrogate_fit(masks: np.ndarray, preds: np.ndarray,
                   kernel_width: float,
                   regularization: float = 0.0) -> np.ndarray:
    """masks [R, S, F] binary, preds [R, S] → coefs [R, F]."""
    # LIME proximity kernel: exp(-d²/width²), d = fraction masked off
    d = 1.0 - masks.mean(axis=2)
    w = np.exp(-(d ** 2) / kernel_width ** 2).astype(np.float32)
    return _fit_surrogates(masks, preds, w, regularization)


def _surrogate_fit_linear(Z: np.ndarray, preds: np.ndarray,
                          regularization: float) -> np.ndarray:
    """Unweighted local linear fit for gaussian perturbations:
    Z [R, S, F] standardized offsets, preds [R, S] → coefs [R, F] (in
    standardized units — the reference's lasso without sample weights)."""
    w = np.ones(Z.shape[:2], np.float32)
    return _fit_surrogates(Z, preds, w, regularization)


class _LIMEParams(HasInputCol, HasOutputCol):
    """Params + scoring shared by every LIME stage (estimator, model and
    the mask-based transformers) — ONE declaration each."""

    model = ComplexParam("model", "transformer to explain")
    predictionCol = Param("predictionCol",
                          "column of the model's output to explain",
                          TC.toString, default="prediction")
    nSamples = Param("nSamples", "perturbations per row", TC.toInt,
                     default=100)
    regularization = Param("regularization",
                           "regularization of the local surrogate fit "
                           "(reference LIME's lasso strength; a ridge "
                           "penalty here)", TC.toFloat, default=0.0)
    seed = Param("seed", "sampling seed", TC.toInt, default=0)

    def _predict(self, df) -> np.ndarray:
        scored = self.get("model").transform(df)
        p = np.asarray(scored[self.get("predictionCol")], np.float64)
        return p[:, -1] if p.ndim == 2 else p


class _LIMEBase(Transformer, _LIMEParams):
    kernelWidth = Param("kernelWidth", "proximity kernel width", TC.toFloat,
                        default=0.75)


class TabularLIME(Estimator, _LIMEParams):
    """Estimator half of tabular LIME (reference ``LIME.scala:169-199``):
    fit computes per-column standard deviations (the reference fits a
    StandardScaler) which the model uses to scale its gaussian
    perturbations around each explained instance."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="features", outputCol="weights")

    def _fit(self, df):
        x = as_2d_features(df, self.getInputCol()).astype(np.float64)
        stds = (x.std(axis=0, ddof=1) if x.shape[0] > 1
                else np.ones(x.shape[1]))
        stds = np.where(stds > 0, stds, 1.0)
        model = TabularLIMEModel()
        self._copy_params_to(model)
        model.set("columnSTDs", [float(v) for v in stds])
        return model


class TabularLIMEModel(Model, _LIMEParams):
    """Per-feature linear attribution: perturb each instance with
    gaussian noise scaled by ``columnSTDs`` (reference
    ``perturbedDenseVectors``, ``LIME.scala:216-221``), score through
    the explained model, fit a regularized local linear surrogate."""

    columnSTDs = Param("columnSTDs", "per-column perturbation scales",
                       TC.toListFloat, default=[])

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="features", outputCol="weights")

    def _transform(self, df):
        x = as_2d_features(df, self.getInputCol()).astype(np.float64)
        n, F = x.shape
        stds = np.asarray(self.get("columnSTDs"), np.float64)
        if stds.size == 0:
            raise ValueError(
                "columnSTDs is unset — fit TabularLIME first (or set "
                "per-column perturbation scales explicitly)")
        if stds.shape[0] != F:
            raise ValueError(
                f"columnSTDs has {stds.shape[0]} entries for {F} "
                "features")
        if not np.all(stds > 0):
            raise ValueError(
                "columnSTDs must be strictly positive (zero would make "
                "the standardized surrogate design NaN)")
        S = self.get("nSamples")
        rng = np.random.default_rng(self.get("seed"))
        # the standard-normal draws ARE the standardized design — scale
        # up once for the perturbation instead of dividing back later
        Z = rng.standard_normal((n, S, F)).astype(np.float32)
        perturbed = x[:, None, :] + Z * stds[None, None, :]
        flat = perturbed.reshape(n * S, F).astype(np.float32)
        preds = self._predict(
            DataFrame({self.getInputCol(): flat})).reshape(n, S)
        # local surrogate on standardized offsets (unit-variance design,
        # like the reference's scaler-backed fit); coefficients are
        # rescaled back to raw feature units
        coefs = _surrogate_fit_linear(Z, preds.astype(np.float32),
                                      self.get("regularization"))
        coefs = coefs / stds[None, :]
        return df.with_column(self.getOutputCol(),
                              coefs.astype(np.float64))


class ImageLIME(_LIMEBase):
    """Superpixel attribution (reference ``ImageLIME``, ``LIME.scala:262``):
    perturbations turn superpixels gray; output = weight per superpixel."""

    superpixelCol = Param("superpixelCol", "precomputed superpixel labels "
                          "('' = compute)", TC.toString, default="")
    cellSize = Param("cellSize", "superpixel size", TC.toFloat,
                     default=16.0)
    modifier = Param("modifier", "SLIC compactness", TC.toFloat,
                     default=130.0)
    samplingFraction = Param("samplingFraction",
                             "P(superpixel stays on)", TC.toFloat,
                             default=0.7)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="weights")

    def _transform(self, df):
        col = df[self.getInputCol()]
        images = list(col) if col.dtype == object else [a for a in col]
        S = self.get("nSamples")
        rng = np.random.default_rng(self.get("seed"))
        spx_col = self.get("superpixelCol")

        weights_out = np.empty(len(images), object)
        spx_out = np.empty(len(images), object)
        for r, img in enumerate(images):
            img = np.asarray(img, np.float32)
            labels = (np.asarray(df[spx_col][r]) if spx_col
                      else Superpixel.cluster(img, self.get("cellSize"),
                                              self.get("modifier")))
            K = int(labels.max()) + 1
            masks = (rng.random((S, K))
                     < self.get("samplingFraction")).astype(np.float32)
            onoff = masks[:, labels]                  # [S, H, W]
            gray = img.mean()
            batch = (onoff[..., None] * img[None]
                     + (1 - onoff[..., None]) * gray)
            preds = self._predict(
                DataFrame({self.getInputCol(): batch.astype(np.float32)}))
            coefs = _surrogate_fit(masks[None], preds[None].astype(
                np.float32), self.get("kernelWidth"),
                self.get("regularization"))[0]
            weights_out[r] = coefs
            spx_out[r] = labels
        out = df.with_column(self.getOutputCol(), weights_out)
        if not spx_col:
            out = out.with_column("superpixels", spx_out)
        return out


class TextLIME(_LIMEBase):
    """Word-level attribution (reference ``TextLIME.scala``): mask tokens,
    score, fit; output = weight per token."""

    tokensCol = Param("tokensCol", "output column for the tokens",
                      TC.toString, default="tokens")
    samplingFraction = Param("samplingFraction", "P(token stays)",
                             TC.toFloat, default=0.7)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="text", outputCol="weights")

    def _transform(self, df):
        texts = [str(t) for t in df[self.getInputCol()]]
        S = self.get("nSamples")
        rng = np.random.default_rng(self.get("seed"))
        weights_out = np.empty(len(texts), object)
        tokens_out = np.empty(len(texts), object)
        for r, text in enumerate(texts):
            toks = text.split()
            K = max(len(toks), 1)
            masks = (rng.random((S, K))
                     < self.get("samplingFraction")).astype(np.float32)
            variants = [" ".join(t for t, m in zip(toks, row) if m > 0)
                        for row in masks]
            col = np.empty(S, object)
            col[:] = variants
            preds = self._predict(DataFrame({self.getInputCol(): col}))
            coefs = _surrogate_fit(masks[None],
                                   preds[None].astype(np.float32),
                                   self.get("kernelWidth"),
                                   self.get("regularization"))[0]
            weights_out[r] = coefs
            tokens_out[r] = toks
        return (df.with_column(self.getOutputCol(), weights_out)
                  .with_column(self.get("tokensCol"), tokens_out))
