"""Superpixel clustering (SLIC-style) for image explanations.

Reference ``lime/Superpixel.scala``: cluster pixels into locally-coherent
segments used as the interpretable units of ImageLIME. SLIC iterations are
jitted — distance computation and assignment are whole-image array ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol


@functools.partial(jax.jit, static_argnames=("n_seg_h", "n_seg_w", "iters"))
def _slic(image, *, n_seg_h: int, n_seg_w: int, iters: int = 5,
          compactness: float = 10.0):
    """image [H, W, C] float32 → labels [H, W] int32 in
    [0, n_seg_h*n_seg_w)."""
    H, W, C = image.shape
    K = n_seg_h * n_seg_w
    gy, gx = jnp.meshgrid(jnp.arange(H, dtype=jnp.float32),
                          jnp.arange(W, dtype=jnp.float32), indexing="ij")
    # initial cluster centers on a grid
    cy = (jnp.arange(n_seg_h) + 0.5) * (H / n_seg_h)
    cx = (jnp.arange(n_seg_w) + 0.5) * (W / n_seg_w)
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"),
                    axis=-1).reshape(K, 2)
    s = (H * W / K) ** 0.5
    py = cyx[:, 0].astype(jnp.int32).clip(0, H - 1)
    px = cyx[:, 1].astype(jnp.int32).clip(0, W - 1)
    centers_rgb = image[py, px]                       # [K, C]
    centers = jnp.concatenate([cyx, centers_rgb], axis=1)  # [K, 2+C]

    pix = jnp.concatenate(
        [gy[..., None], gx[..., None], image], axis=-1)    # [H, W, 2+C]
    flat = pix.reshape(-1, 2 + C)

    def step(_, centers):
        d_space = ((flat[:, None, :2] - centers[None, :, :2]) ** 2) \
            .sum(-1)
        d_color = ((flat[:, None, 2:] - centers[None, :, 2:]) ** 2) \
            .sum(-1)
        d = d_color + (compactness ** 2) * d_space / (s * s)
        labels = jnp.argmin(d, axis=1)                # [H*W]
        onehot = jax.nn.one_hot(labels, K, dtype=jnp.float32)
        counts = onehot.sum(axis=0)[:, None]
        new_centers = (onehot.T @ flat) / jnp.maximum(counts, 1.0)
        return jnp.where(counts > 0, new_centers, centers)

    centers = jax.lax.fori_loop(0, iters, step, centers)
    d_space = ((flat[:, None, :2] - centers[None, :, :2]) ** 2).sum(-1)
    d_color = ((flat[:, None, 2:] - centers[None, :, 2:]) ** 2).sum(-1)
    labels = jnp.argmin(d_color + (compactness ** 2) * d_space / (s * s),
                        axis=1)
    return labels.reshape(H, W).astype(jnp.int32)


class Superpixel:
    """Functional superpixel API (reference ``Superpixel.clusterImage``)."""

    @staticmethod
    def cluster(image: np.ndarray, cell_size: float = 16.0,
                modifier: float = 10.0, iters: int = 5) -> np.ndarray:
        img = np.asarray(image, np.float32)
        if img.ndim == 2:
            img = img[..., None]
        H, W = img.shape[:2]
        n_h = max(1, int(round(H / cell_size)))
        n_w = max(1, int(round(W / cell_size)))
        return np.asarray(_slic(jnp.asarray(img), n_seg_h=n_h, n_seg_w=n_w,
                                iters=iters, compactness=modifier))


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Adds a superpixel-label column for each image (reference
    ``lime/SuperpixelTransformer.scala``)."""

    cellSize = Param("cellSize", "target superpixel size (px)", TC.toFloat,
                     default=16.0)
    modifier = Param("modifier", "SLIC compactness", TC.toFloat,
                     default=130.0)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="superpixels")

    def _transform(self, df):
        col = df[self.getInputCol()]
        imgs = col if (isinstance(col, np.ndarray) and col.ndim == 4) \
            else list(col)
        labels = [Superpixel.cluster(img, self.get("cellSize"),
                                     self.get("modifier")) for img in imgs]
        out = np.empty(len(labels), object)
        out[:] = labels
        return df.with_column(self.getOutputCol(), out)
