"""Per-tenant feature plumbing.

Reference ``cyber/feature/indexers.py`` (IdIndexer: per-tenant string→int
with 1-based ids) and ``cyber/feature/scalers.py`` (partitioned
standard/linear scalers).
"""

from __future__ import annotations

import numpy as np

from ..core import ComplexParam, DataFrame, Estimator, Model, Param, \
    Transformer, TypeConverters as TC
from ..core.param import StageListParam


class IdIndexer(Estimator):
    inputCol = Param("inputCol", "raw id column", TC.toString)
    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    outputCol = Param("outputCol", "indexed id column", TC.toString)
    resetPerPartition = Param("resetPerPartition",
                              "ids restart at 1 per tenant", TC.toBoolean,
                              default=True)

    def _fit(self, df):
        vocab: dict = {}
        tenants = df[self.get("partitionKey")]
        values = df[self.get("inputCol")]
        reset = self.get("resetPerPartition")
        for t, v in zip(tenants, values):
            key = (t if reset else None)
            tenant_vocab = vocab.setdefault(key, {})
            if v not in tenant_vocab:
                tenant_vocab[v] = len(tenant_vocab) + 1  # 1-based
        model = IdIndexerModel(vocabulary=vocab)
        self._copy_params_to(model)
        return model


class IdIndexerModel(Model):
    inputCol = Param("inputCol", "raw id column", TC.toString)
    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    outputCol = Param("outputCol", "indexed id column", TC.toString)
    resetPerPartition = Param("resetPerPartition", "per-tenant ids",
                              TC.toBoolean, default=True)
    vocabulary = ComplexParam("vocabulary", "tenant -> value -> id")

    def _transform(self, df):
        vocab = self.get("vocabulary")
        reset = self.get("resetPerPartition")
        tenants = df[self.get("partitionKey")]
        values = df[self.get("inputCol")]
        out = np.asarray([
            vocab.get(t if reset else None, {}).get(v, 0)
            for t, v in zip(tenants, values)], np.int64)
        return df.with_column(self.get("outputCol"), out)


class _PartitionedScaler(Estimator):
    inputCol = Param("inputCol", "value column", TC.toString)
    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    outputCol = Param("outputCol", "scaled column", TC.toString)

    def _stats(self, vals: np.ndarray) -> tuple:
        raise NotImplementedError

    def _fit(self, df):
        stats: dict = {}
        tenants = np.asarray(df[self.get("partitionKey")])
        vals = np.asarray(df[self.get("inputCol")], np.float64)
        for t in set(tenants.tolist()):
            stats[t] = self._stats(vals[tenants == t])
        model = _ScalerModel(stats=stats, kind=type(self).__name__)
        self._copy_params_to(model)
        return model


class _ScalerModel(Model):
    inputCol = Param("inputCol", "value column", TC.toString)
    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    outputCol = Param("outputCol", "scaled column", TC.toString)
    stats = ComplexParam("stats", "tenant -> scaling stats")
    kind = Param("kind", "scaler type", TC.toString)

    def _transform(self, df):
        stats = self.get("stats")
        tenants = np.asarray(df[self.get("partitionKey")])
        vals = np.asarray(df[self.get("inputCol")], np.float64)
        out = np.zeros(len(vals))
        for t, s in stats.items():
            m = tenants == t
            if self.get("kind") == "StandardScalarScaler":
                mean, std = s
                out[m] = (vals[m] - mean) / (std if std > 0 else 1.0)
            else:
                lo, hi, (a, b) = s
                span = hi - lo if hi > lo else 1.0
                out[m] = a + (vals[m] - lo) * (b - a) / span
        return df.with_column(self.get("outputCol"), out)


class StandardScalarScaler(_PartitionedScaler):
    """Per-tenant (x - mean) / std (reference ``scalers.py``)."""

    def _stats(self, vals):
        return float(vals.mean()), float(vals.std())


class LinearScalarScaler(_PartitionedScaler):
    """Per-tenant min/max → [minRequired, maxRequired]."""

    minRequiredValue = Param("minRequiredValue", "output min", TC.toFloat,
                             default=0.0)
    maxRequiredValue = Param("maxRequiredValue", "output max", TC.toFloat,
                             default=1.0)

    def _stats(self, vals):
        return (float(vals.min()), float(vals.max()),
                (self.get("minRequiredValue"),
                 self.get("maxRequiredValue")))


class MultiIndexer(Estimator):
    """Index several (inputCol, outputCol) pairs in one fit (reference
    ``cyber/feature/indexers.py`` ``MultiIndexer``: a convenience over a
    list of IdIndexers sharing the tenant key)."""

    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    inputCols = Param("inputCols", "raw id columns", TC.toListString)
    outputCols = Param("outputCols", "indexed id columns",
                       TC.toListString)
    resetPerPartition = Param("resetPerPartition",
                              "ids restart at 1 per tenant", TC.toBoolean,
                              default=True)

    def _fit(self, df):
        ins = self.get("inputCols")
        outs = self.get("outputCols")
        if len(ins) != len(outs):
            raise ValueError(
                f"inputCols ({len(ins)}) and outputCols ({len(outs)}) "
                "must pair up")
        models = [IdIndexer(inputCol=i, outputCol=o,
                            partitionKey=self.get("partitionKey"),
                            resetPerPartition=self.get(
                                "resetPerPartition")).fit(df)
                  for i, o in zip(ins, outs)]
        model = MultiIndexerModel(models=models)
        self._copy_params_to(model)
        return model


class MultiIndexerModel(Model):
    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    inputCols = Param("inputCols", "raw id columns", TC.toListString)
    outputCols = Param("outputCols", "indexed id columns",
                       TC.toListString)
    resetPerPartition = Param("resetPerPartition", "per-tenant ids",
                              TC.toBoolean, default=True)
    models = StageListParam("models",
                            "fitted per-column IdIndexerModels")

    def get_indexer(self, input_col: str):
        """The fitted IdIndexerModel for one column (reference
        ``MultiIndexerModel.get_indexer``)."""
        for m in self.get("models"):
            if m.get("inputCol") == input_col:
                return m
        raise KeyError(f"no indexer for column {input_col!r}")

    def _transform(self, df):
        out = df
        for m in self.get("models"):
            out = m.transform(out)
        return out


class ConnectedComponents(Transformer):
    """Assign each (user, resource) edge its bipartite connected
    component (reference ``cyber/utils`` ``ConnectedComponents``): the
    access-anomaly recipe models each component independently, since
    scores across disconnected access graphs are incomparable."""

    partitionKey = Param("partitionKey", "tenant column", TC.toString)
    userCol = Param("userCol", "user column", TC.toString,
                    default="user")
    resCol = Param("resCol", "resource column", TC.toString,
                   default="res")
    componentCol = Param("componentCol", "output component id column",
                         TC.toString, default="component")

    def _transform(self, df):
        tenants = df[self.get("partitionKey")]
        users = df[self.get("userCol")]
        ress = df[self.get("resCol")]
        # union-find over (tenant, 'u', user) and (tenant, 'r', res)
        parent: dict = {}

        def find(a):
            root = a
            while parent.setdefault(root, root) != root:
                root = parent[root]
            while parent[a] != root:       # path compression
                parent[a], a = root, parent[a]
            return root

        def union(a, b):
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[rb] = ra

        n = len(df)
        for i in range(n):
            union((tenants[i], "u", users[i]),
                  (tenants[i], "r", ress[i]))
        labels: dict = {}
        out = np.zeros(n, np.int64)
        for i in range(n):
            root = find((tenants[i], "u", users[i]))
            out[i] = labels.setdefault(root, len(labels))
        return df.with_column(self.get("componentCol"), out)
