"""Access-anomaly detection via collaborative filtering.

Reference ``cyber/anomaly/collaborative_filtering.py``: per-tenant ALS
factorization of the (user, resource) access matrix; the anomaly score of
an access is the (standardized, negated) predicted affinity — users
accessing resources far from their latent profile score high.
``complement_access.py``: sample (user, resource) pairs NOT seen, used to
calibrate/evaluate.

TPU shape: the ALS alternating ridge solves are batched
``jnp.linalg.solve`` calls over all users (resp. items) at once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, DataFrame, Estimator, Model, Param, \
    Transformer, TypeConverters as TC


@functools.partial(jax.jit, static_argnames=("rank",))
def _als_step(mat, fixed, reg, *, rank: int):
    """Solve factors for every row of ``mat`` given the ``fixed`` factor
    matrix: (FᵀF + λI)⁻¹ Fᵀ mat_rowᵀ, batched via one solve."""
    gram = fixed.T @ fixed + reg * jnp.eye(rank)
    rhs = mat @ fixed                      # [n, rank]
    return jnp.linalg.solve(gram[None], rhs[..., None])[..., 0]


def _als(mat: np.ndarray, rank: int, reg: float, iters: int,
         seed: int) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    U, I = mat.shape
    u = jnp.asarray(rng.normal(scale=0.1, size=(U, rank)), jnp.float32)
    v = jnp.asarray(rng.normal(scale=0.1, size=(I, rank)), jnp.float32)
    m = jnp.asarray(mat, jnp.float32)
    for _ in range(iters):
        u = _als_step(m, v, reg, rank=rank)
        v = _als_step(m.T, u, reg, rank=rank)
    return np.asarray(u), np.asarray(v)


class AccessAnomaly(Estimator):
    tenantCol = Param("tenantCol", "tenant column", TC.toString,
                      default="tenant")
    userCol = Param("userCol", "indexed user column (1-based)",
                    TC.toString, default="user")
    resCol = Param("resCol", "indexed resource column (1-based)",
                   TC.toString, default="res")
    likelihoodCol = Param("likelihoodCol",
                          "access count/likelihood column ('' = 1.0)",
                          TC.toString, default="")
    rankParam = Param("rankParam", "latent dimension", TC.toInt, default=10)
    regParam = Param("regParam", "ALS ridge strength", TC.toFloat,
                     default=0.1)
    maxIter = Param("maxIter", "ALS iterations", TC.toInt, default=10)
    seed = Param("seed", "init seed", TC.toInt, default=0)
    outputCol = Param("outputCol", "anomaly score column", TC.toString,
                      default="anomaly_score")

    def _fit(self, df):
        tenants = np.asarray(df[self.get("tenantCol")])
        users = np.asarray(df[self.get("userCol")], np.int64)
        res = np.asarray(df[self.get("resCol")], np.int64)
        lcol = self.get("likelihoodCol")
        vals = (np.asarray(df[lcol], np.float64) if lcol
                else np.ones(len(users)))

        factors: dict = {}
        for t in set(tenants.tolist()):
            m = tenants == t
            U = int(users[m].max()) + 1
            I = int(res[m].max()) + 1
            mat = np.zeros((U, I), np.float32)
            np.add.at(mat, (users[m], res[m]), vals[m])
            mat = np.log1p(mat)
            u_f, v_f = _als(mat, self.get("rankParam"),
                            self.get("regParam"), self.get("maxIter"),
                            self.get("seed"))
            # standardization stats from observed accesses
            pred = (u_f[users[m]] * v_f[res[m]]).sum(axis=1)
            factors[t] = (u_f, v_f, float(pred.mean()),
                          float(pred.std() or 1.0))
        model = AccessAnomalyModel(factors=factors)
        self._copy_params_to(model)
        return model


class AccessAnomalyModel(Model):
    tenantCol = Param("tenantCol", "tenant column", TC.toString,
                      default="tenant")
    userCol = Param("userCol", "indexed user column", TC.toString,
                    default="user")
    resCol = Param("resCol", "indexed resource column", TC.toString,
                   default="res")
    outputCol = Param("outputCol", "anomaly score column", TC.toString,
                      default="anomaly_score")
    factors = ComplexParam("factors",
                           "tenant -> (user_f, res_f, mean, std)")

    def _transform(self, df):
        tenants = np.asarray(df[self.get("tenantCol")])
        users = np.asarray(df[self.get("userCol")], np.int64)
        res = np.asarray(df[self.get("resCol")], np.int64)
        out = np.zeros(len(users))
        for t, (u_f, v_f, mean, std) in self.get("factors").items():
            m = tenants == t
            uu = np.clip(users[m], 0, u_f.shape[0] - 1)
            rr = np.clip(res[m], 0, v_f.shape[0] - 1)
            pred = (u_f[uu] * v_f[rr]).sum(axis=1)
            # low predicted affinity → high anomaly score
            out[m] = -(pred - mean) / std
        return df.with_column(self.get("outputCol"), out)


class ComplementAccessTransformer(Transformer):
    """Sample (tenant, user, resource) triples NOT present in the data
    (reference ``complement_access.py``)."""

    tenantCol = Param("tenantCol", "tenant column", TC.toString,
                      default="tenant")
    indexedColNamesArr = Param("indexedColNamesArr",
                               "indexed id columns to complement",
                               TC.toListString, default=["user", "res"])
    complementsetFactor = Param("complementsetFactor",
                                "complement samples per observed row",
                                TC.toInt, default=2)
    seed = Param("seed", "sampling seed", TC.toInt, default=0)

    def _transform(self, df):
        rng = np.random.default_rng(self.get("seed"))
        tcol = self.get("tenantCol")
        cols = self.get("indexedColNamesArr")
        tenants = np.asarray(df[tcol])
        data = {c: np.asarray(df[c], np.int64) for c in cols}
        out_rows = {tcol: [], **{c: [] for c in cols}}
        for t in set(tenants.tolist()):
            m = tenants == t
            seen = set(zip(*(data[c][m] for c in cols)))
            maxes = {c: int(data[c][m].max()) for c in cols}
            want = int(m.sum()) * self.get("complementsetFactor")
            produced = 0  # per-tenant quota, not the global row count
            tries = 0
            while produced < want and tries < want * 20:
                tries += 1
                cand = tuple(int(rng.integers(1, maxes[c] + 1))
                             for c in cols)
                if cand not in seen:
                    seen.add(cand)
                    produced += 1
                    out_rows[tcol].append(t)
                    for c, v in zip(cols, cand):
                        out_rows[c].append(v)
        n = len(out_rows[tcol])
        tenant_arr = np.empty(n, object)
        tenant_arr[:] = out_rows[tcol]
        return DataFrame({tcol: tenant_arr,
                          **{c: np.asarray(out_rows[c], np.int64)
                             for c in cols}})
