"""CyberML: access-anomaly detection.

Reference ``src/main/python/mmlspark/cyber/`` (~2k LoC, Python-only —
SURVEY §2.10): ALS-based collaborative filtering over (tenant, user,
resource) access logs (``anomaly/collaborative_filtering.py``),
complement sampling (``complement_access.py``), per-tenant indexers and
scalers (``feature/``).
"""

from .feature import (ConnectedComponents, IdIndexer,
                      IdIndexerModel, MultiIndexer,
                      MultiIndexerModel, StandardScalarScaler,
                      LinearScalarScaler)
from .anomaly import AccessAnomaly, AccessAnomalyModel, \
    ComplementAccessTransformer

__all__ = ["ConnectedComponents", "IdIndexer", "IdIndexerModel",
           "MultiIndexer", "MultiIndexerModel", "StandardScalarScaler",
           "LinearScalarScaler", "AccessAnomaly", "AccessAnomalyModel",
           "ComplementAccessTransformer"]
