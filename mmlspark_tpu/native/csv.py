"""CSV → columnar DataFrame through the native parser.

The data-loading front door for tabular training (the reference pushes
this into each native engine's loader; here one loader feeds everything).
Numeric cells parse to float32 (NaN for missing/non-numeric — the GBDT
missing-value convention); requested string columns are decoded in Python.
"""

from __future__ import annotations

import ctypes
import io

import numpy as np

from ..core import DataFrame
from .loader import get_fastio


def parse_csv_bytes(data: bytes, has_header: bool = True,
                    n_threads: int = 0) -> tuple[np.ndarray, list[str]]:
    """bytes → (float32 [rows, cols] matrix, column names).

    The native parser splits on raw commas; quoted fields would desync it
    from Python's csv module, so any quote character routes the whole file
    through the quote-aware path — one parsing discipline per file.
    """
    if b'"' in data:
        return _parse_quoted(data, has_header)
    lib = get_fastio()
    first_line = data.split(b"\n", 1)[0].decode("utf-8", "replace")
    names = [c.strip() for c in first_line.split(",")] if has_header else []
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        lib.csv_dims(data, len(data), int(has_header),
                     ctypes.byref(rows), ctypes.byref(cols))
        out = np.empty((rows.value, cols.value), np.float32)
        if n_threads <= 0:
            import os
            n_threads = min(8, os.cpu_count() or 1)
        lib.csv_parse(data, len(data), int(has_header), rows.value,
                      cols.value,
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      n_threads)
        mat = out
    else:  # NumPy fallback
        mat = np.genfromtxt(io.BytesIO(data), delimiter=",",
                            skip_header=1 if has_header else 0,
                            dtype=np.float32, ndmin=2)
    if not names:
        names = [f"Column_{i}" for i in range(mat.shape[1])]
    return mat, names


def _parse_quoted(data: bytes, has_header: bool) -> \
        tuple[np.ndarray, list[str]]:
    import csv as _csv
    rows = list(_csv.reader(io.StringIO(data.decode("utf-8", "replace"))))
    rows = [r for r in rows if r]
    names = [c.strip() for c in rows[0]] if has_header and rows else []
    body = rows[1:] if has_header else rows
    cols = len(names) or (len(body[0]) if body else 0)
    mat = np.full((len(body), cols), np.nan, np.float32)
    for i, r in enumerate(body):
        for j in range(min(len(r), cols)):
            try:
                mat[i, j] = float(r[j])
            except ValueError:
                pass
    if not names:
        names = [f"Column_{i}" for i in range(cols)]
    return mat, names


def read_csv(path: str, has_header: bool = True,
             features_col: str | None = None,
             label_col: str | None = None,
             string_cols: tuple[str, ...] = ()) -> DataFrame:
    """Load a CSV as a DataFrame.

    Default: one numeric column per CSV column. ``features_col`` assembles
    every non-label numeric column into a single 2-D feature column (the
    shape the estimators consume). ``string_cols`` are re-decoded as python
    strings (object columns).
    """
    with open(path, "rb") as f:
        data = f.read()
    mat, names = parse_csv_bytes(data, has_header)

    str_values: dict[str, np.ndarray] = {}
    if string_cols:
        import csv as _csv
        import io as _io
        reader = _csv.reader(_io.StringIO(data.decode("utf-8", "replace")))
        rows = list(reader)
        if has_header:
            rows = rows[1:]
        for c in string_cols:
            j = names.index(c)
            col = np.empty(len(rows), object)
            col[:] = [r[j] if j < len(r) else None for r in rows]
            str_values[c] = col

    cols: dict[str, np.ndarray] = {}
    if features_col:
        feature_idx = [j for j, nm in enumerate(names)
                       if nm != label_col and nm not in string_cols]
        cols[features_col] = np.ascontiguousarray(mat[:, feature_idx])
        if label_col is not None:
            cols[label_col] = mat[:, names.index(label_col)]
    else:
        for j, nm in enumerate(names):
            if nm not in string_cols:
                cols[nm] = mat[:, j]
    cols.update(str_values)
    return DataFrame(cols)
