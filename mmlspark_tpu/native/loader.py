"""NativeLoader — compile-on-first-use + ctypes binding.

Reference ``core/env/NativeLoader.java``: resources → temp dir →
``System.load``; one load per JVM, thread-safe. Here: source → cached .so
keyed by source hash → ``ctypes.CDLL``; one per process.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_CACHE_DIR = os.environ.get("MMLSPARK_TPU_NATIVE_CACHE",
                            "/tmp/mmlspark_tpu_native")


class NativeLoader:
    """Build + load one shared library from shipped C++ source."""

    _lock = threading.Lock()
    _loaded: dict[str, ctypes.CDLL] = {}

    def __init__(self, name: str, sources: list[str],
                 extra_flags: tuple[str, ...] = ()):
        self.name = name
        self.sources = [os.path.join(_SRC_DIR, s) for s in sources]
        self.extra_flags = extra_flags

    def _so_path(self) -> str:
        h = hashlib.sha256()
        for s in self.sources:
            with open(s, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.extra_flags).encode())
        return os.path.join(_CACHE_DIR,
                            f"lib{self.name}_{h.hexdigest()[:16]}.so")

    def _build(self, so_path: str) -> None:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        # per-process temp name so concurrent builders never share an
        # artifact; os.replace publishes whichever finishes atomically
        tmp = f"{so_path}.{os.getpid()}.build"
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-std=c++17", "-pthread", *self.extra_flags,
               *self.sources, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp, so_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load(self) -> ctypes.CDLL:
        with NativeLoader._lock:
            if self.name in NativeLoader._loaded:
                return NativeLoader._loaded[self.name]
            so = self._so_path()
            if not os.path.exists(so):
                self._build(so)
            lib = ctypes.CDLL(so)
            NativeLoader._loaded[self.name] = lib
            return lib


_libs: dict[str, ctypes.CDLL | None] = {}


def _lazy_native(name: str, sources: list[str], configure):
    """Shared lazy loader: one build+load per process, honoring the
    ``MMLSPARK_TPU_DISABLE_NATIVE=1`` kill-switch; returns None when the
    toolchain is unavailable (callers fall back to Python paths)."""
    if name in _libs:
        return _libs[name]
    if os.environ.get("MMLSPARK_TPU_DISABLE_NATIVE", "") == "1":
        _libs[name] = None
        return None
    try:
        lib = NativeLoader(name, sources).load()
        configure(lib)
    except Exception:
        _libs[name] = None
        return None
    _libs[name] = lib
    return lib


def get_vwhash():
    """The batch VW-hashing library (vwhash.cpp), or None."""
    def configure(lib):
        i64 = ctypes.c_int64
        u32 = ctypes.c_uint32
        lib.vw_murmur3_32.argtypes = [ctypes.c_char_p, i64, u32]
        lib.vw_murmur3_32.restype = u32
        lib.vw_hash_strings.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(i64), i64,   # buf, offsets, n
            ctypes.c_char_p, i64, u32,                   # prefix, len, seed
            ctypes.c_int, ctypes.c_int,                  # bits, mode
            ctypes.POINTER(i64),                         # out CSR offsets
            ctypes.c_int,                                # sum_collisions
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32)]
        lib.vw_hash_strings.restype = None

    return _lazy_native("vwhash", ["vwhash.cpp"], configure)


def get_httpfront():
    """The native epoll HTTP serving front (httpfront.cpp), or None."""
    def configure(lib):
        i64 = ctypes.c_int64
        u64 = ctypes.c_uint64
        lib.hf_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int)]
        lib.hf_start.restype = i64
        lib.hf_poll.argtypes = [i64, ctypes.POINTER(u64), i64,
                                ctypes.c_int]
        lib.hf_poll.restype = i64
        lib.hf_req_info.argtypes = [i64, u64, ctypes.c_char_p, i64,
                                    ctypes.c_char_p, i64,
                                    ctypes.POINTER(i64),
                                    ctypes.POINTER(i64)]
        lib.hf_req_info.restype = ctypes.c_int
        lib.hf_req_body.argtypes = [i64, u64, ctypes.c_char_p]
        lib.hf_req_body.restype = i64
        lib.hf_req_headers.argtypes = [i64, u64, ctypes.c_char_p]
        lib.hf_req_headers.restype = i64
        lib.hf_reply.argtypes = [i64, u64, ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, i64]
        lib.hf_reply.restype = ctypes.c_int
        lib.hf_stop.argtypes = [i64]
        lib.hf_stop.restype = None

    return _lazy_native("httpfront", ["httpfront.cpp"], configure)


def get_fastio():
    """The fastio library with argtypes configured, or None."""
    def configure(lib):
        i64 = ctypes.c_int64
        lib.csv_dims.argtypes = [ctypes.c_char_p, i64, ctypes.c_int,
                                 ctypes.POINTER(i64), ctypes.POINTER(i64)]
        lib.csv_dims.restype = ctypes.c_int
        lib.csv_parse.argtypes = [ctypes.c_char_p, i64, ctypes.c_int, i64,
                                  i64, ctypes.POINTER(ctypes.c_float),
                                  ctypes.c_int]
        lib.csv_parse.restype = ctypes.c_int
        lib.read_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p, i64]
        lib.read_file.restype = i64
        lib.file_size.argtypes = [ctypes.c_char_p]
        lib.file_size.restype = i64

    return _lazy_native("fastio", ["fastio.cpp"], configure)
