"""Native runtime: C++ IO with a NativeLoader-style bootstrap.

Reference ``core/env/NativeLoader.java:28-110``: extract the shared object
shipped in the jar to a temp dir and ``System.load`` it once per JVM. Here
the shared object is built from the shipped C++ source on first use (the
toolchain is part of the image), cached by source hash, and loaded with
ctypes once per process. Pure-NumPy fallbacks keep everything working when
no compiler is present.
"""

from .loader import NativeLoader, get_fastio
from .csv import read_csv, parse_csv_bytes

__all__ = ["NativeLoader", "get_fastio", "read_csv", "parse_csv_bytes"]
