// Native HTTP serving front: a single-reactor epoll server with a
// C ABI for ctypes.
//
// Role of the reference's per-executor WorkerServer HTTP listener
// (continuous/HTTPSourceV2.scala:475+), rebuilt as native code for the
// serving hot path: the Python http.server front costs a thread per
// connection plus several GIL hand-offs per request, which is where the
// serving tail latency (p99) lives. Here one reactor thread owns all
// sockets; Python sees only (id, method, path, body) tuples via a
// polling call and replies by id.
//
// ABI (all thread-safe):
//   hf_start(host, port, &out_port)      -> handle (>0) or -errno
//   hf_poll(h, ids, max_n, timeout_ms)   -> n ready request ids
//   hf_req_info(h, id, meth, mcap, path, pcap, &body_len, &hdr_len)
//   hf_req_body(h, id, buf)              -> body_len copied
//   hf_req_headers(h, id, buf)           -> raw header bytes copied
//   hf_reply(h, id, status, extra_hdr_lines, body, len) -> 0
//   hf_stop(h)
//
// Requests are parsed HTTP/1.1 with keep-alive and pipelining; replies
// are single-writev responses with Connection: keep-alive. TCP_NODELAY
// is set on every accepted socket (the Nagle/delayed-ACK stall class —
// see serving/server.py LowLatencyHandlerMixin).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cctype>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>

namespace {

struct Conn {
    int fd;
    uint64_t gen;        // accept generation: guards fd-reuse delivery
    std::string in;      // unparsed bytes
    std::string out;     // unflushed response bytes
    bool closing = false;
    // One request in flight at a time: replies are generated in
    // completion order (the pipeline may answer out of order), so
    // parsing the next pipelined request only after the current one's
    // response is queued keeps per-connection response order correct.
    bool in_flight = false;
};

struct Req {
    uint64_t id;
    int conn_fd;         // owning connection (may die before reply)
    uint64_t conn_gen;   // must match Conn.gen at delivery time
    std::string method, path, headers_raw, body;
    bool keepalive = true;
};

struct Server {
    int listen_fd = -1, epoll_fd = -1, event_fd = -1;
    ~Server() {
        if (event_fd >= 0) ::close(event_fd);
        if (epoll_fd >= 0) ::close(epoll_fd);
    }
    std::thread loop;
    std::atomic<bool> stop{false};

    std::mutex mu;
    std::condition_variable cv;
    std::deque<uint64_t> ready;                    // ids awaiting poll
    std::unordered_map<uint64_t, Req> reqs;        // in flight
    std::deque<std::pair<uint64_t, std::string>> replies;  // id, raw bytes
    uint64_t next_id = 1;
    uint64_t next_gen = 1;

    std::unordered_map<int, Conn> conns;           // reactor-thread only
};

std::mutex g_mu;
std::unordered_map<int64_t, std::shared_ptr<Server>> g_servers;
int64_t g_next_handle = 1;

void flush_out(Server& s, Conn& c);

constexpr size_t kMaxHeaderBytes = 64 * 1024;
constexpr size_t kMaxBodyBytes = size_t(64) << 20;  // 64 MiB
// hard per-connection buffer cap, enforced in the recv path regardless
// of parse state — an in-flight request must not suspend flood control
constexpr size_t kMaxConnBuffer = kMaxBodyBytes + 2 * kMaxHeaderBytes;

bool parse_one(Conn& c, Server& s) {
    // returns true if a complete request was consumed from c.in
    if (c.in_flight) return false;  // strict request-at-a-time per conn
    size_t hdr_end = c.in.find("\r\n\r\n");
    if (hdr_end == std::string::npos) {
        if (c.in.size() > kMaxHeaderBytes) {  // header flood: drop conn
            c.closing = true;
            c.in.clear();
        }
        return false;
    }
    size_t line_end = c.in.find("\r\n");
    std::string line = c.in.substr(0, line_end);
    size_t sp1 = line.find(' '), sp2 = line.rfind(' ');
    if (sp1 == std::string::npos || sp2 <= sp1) {  // malformed: drop conn
        c.closing = true;
        c.in.clear();
        return false;
    }
    std::string method = line.substr(0, sp1);
    std::string path = line.substr(sp1 + 1, sp2 - sp1 - 1);

    size_t clen = 0;
    bool keepalive = true;
    size_t pos = line_end + 2;
    while (pos < hdr_end) {
        size_t eol = c.in.find("\r\n", pos);
        std::string h = c.in.substr(pos, eol - pos);
        pos = eol + 2;
        size_t colon = h.find(':');
        if (colon == std::string::npos) continue;
        std::string key = h.substr(0, colon);
        for (auto& ch : key) ch = (char)tolower((unsigned char)ch);
        std::string val = h.substr(colon + 1);
        size_t b = val.find_first_not_of(' ');
        val = (b == std::string::npos) ? "" : val.substr(b);
        if (key == "content-length") {
            // reject negatives (would wrap) and unbounded bodies
            if (val.empty() || val[0] == '-' ||
                val.find_first_not_of("0123456789") != std::string::npos) {
                c.closing = true;
                c.in.clear();
                return false;
            }
            clen = (size_t)strtoull(val.c_str(), nullptr, 10);
            if (clen > kMaxBodyBytes) {
                // explicit 413 before close: an abrupt reset would look
                // like a network fault and get retried forever. Only
                // BUFFERED here — flush_out can close and erase the
                // Conn, and our caller still holds the reference.
                c.out += "HTTP/1.1 413 Payload Too Large\r\n"
                         "Content-Length: 0\r\nConnection: close\r\n\r\n";
                c.closing = true;
                c.in.clear();
                return false;
            }
        }
        if (key == "connection") {
            for (auto& ch : val) ch = (char)tolower((unsigned char)ch);
            if (val == "close") keepalive = false;
        }
    }
    size_t total = hdr_end + 4 + clen;
    if (c.in.size() < total) return false;  // body not yet complete

    Req r;
    r.conn_fd = c.fd;
    r.conn_gen = c.gen;
    r.method = std::move(method);
    r.path = std::move(path);
    r.headers_raw = c.in.substr(line_end + 2, hdr_end - line_end - 2);
    r.body = c.in.substr(hdr_end + 4, clen);
    r.keepalive = keepalive;
    c.in.erase(0, total);
    c.in_flight = true;
    {
        std::lock_guard<std::mutex> lk(s.mu);
        r.id = s.next_id++;
        uint64_t id = r.id;
        s.reqs.emplace(id, std::move(r));
        s.ready.push_back(id);
    }
    s.cv.notify_one();
    return true;
}

void flush_out(Server& s, Conn& c) {
    while (!c.out.empty()) {
        ssize_t w = ::send(c.fd, c.out.data(), c.out.size(), MSG_NOSIGNAL);
        if (w > 0) {
            c.out.erase(0, (size_t)w);
        } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            epoll_event ev{};
            ev.events = EPOLLIN | EPOLLOUT;
            ev.data.fd = c.fd;
            epoll_ctl(s.epoll_fd, EPOLL_CTL_MOD, c.fd, &ev);
            return;
        } else {
            c.closing = true;
            return;
        }
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = c.fd;
    epoll_ctl(s.epoll_fd, EPOLL_CTL_MOD, c.fd, &ev);
    if (c.closing) {  // close-after-flush (Connection: close)
        epoll_ctl(s.epoll_fd, EPOLL_CTL_DEL, c.fd, nullptr);
        ::close(c.fd);
        s.conns.erase(c.fd);
    }
}

void close_conn(Server& s, int fd) {
    epoll_ctl(s.epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
    ::close(fd);
    s.conns.erase(fd);
}

void reactor(Server* s) {
    epoll_event evs[64];
    while (!s->stop.load(std::memory_order_relaxed)) {
        int n = epoll_wait(s->epoll_fd, evs, 64, 100);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            if (fd == s->listen_fd) {
                for (;;) {
                    int cfd = accept4(s->listen_fd, nullptr, nullptr,
                                      SOCK_NONBLOCK);
                    if (cfd < 0) break;
                    int one = 1;
                    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one,
                               sizeof one);
                    epoll_event ev{};
                    ev.events = EPOLLIN;
                    ev.data.fd = cfd;
                    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, cfd, &ev);
                    Conn c{};
                    c.fd = cfd;
                    c.gen = s->next_gen++;
                    s->conns[cfd] = std::move(c);
                }
                continue;
            }
            if (fd == s->event_fd) {
                uint64_t junk;
                while (read(s->event_fd, &junk, 8) == 8) {}
                // drain pending replies into connection buffers
                std::deque<std::pair<uint64_t, std::string>> pending;
                struct Target { int fd; uint64_t gen; bool keepalive; };
                std::deque<Target> target;
                {
                    std::lock_guard<std::mutex> lk(s->mu);
                    pending.swap(s->replies);
                    for (auto& pr : pending) {
                        auto it = s->reqs.find(pr.first);
                        if (it == s->reqs.end()) {
                            target.push_back({-1, 0, true});
                        } else {
                            target.push_back({it->second.conn_fd,
                                              it->second.conn_gen,
                                              it->second.keepalive});
                            s->reqs.erase(it);
                        }
                    }
                }
                for (size_t k = 0; k < pending.size(); k++) {
                    auto it = s->conns.find(target[k].fd);
                    // generation check: a reused fd number is a
                    // DIFFERENT client — never deliver across reuse
                    if (it == s->conns.end() ||
                        it->second.gen != target[k].gen)
                        continue;  // client gone
                    Conn& c = it->second;
                    c.out += pending[k].second;
                    if (!target[k].keepalive) c.closing = true;
                    flush_out(*s, c);
                    // response queued: this connection may now parse its
                    // next buffered (pipelined) request
                    if (s->conns.find(target[k].fd) != s->conns.end()) {
                        c.in_flight = false;
                        while (parse_one(c, *s)) {}
                        if (!c.out.empty()) flush_out(*s, c);
                        auto it2 = s->conns.find(target[k].fd);
                        if (it2 != s->conns.end() && it2->second.closing
                            && it2->second.out.empty())
                            close_conn(*s, target[k].fd);
                    }
                }
                continue;
            }
            auto it = s->conns.find(fd);
            if (it == s->conns.end()) continue;
            Conn& c = it->second;
            if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
                close_conn(*s, fd);
                continue;
            }
            if (evs[i].events & EPOLLOUT) flush_out(*s, c);
            if (s->conns.find(fd) == s->conns.end()) continue;
            if (evs[i].events & EPOLLIN) {
                char buf[65536];
                for (;;) {
                    ssize_t r = ::recv(fd, buf, sizeof buf, 0);
                    if (r > 0) {
                        c.in.append(buf, (size_t)r);
                        if (c.in.size() > kMaxConnBuffer) {
                            close_conn(*s, fd);
                            break;
                        }
                    } else if (r == 0) {  // peer closed
                        close_conn(*s, fd);
                        break;
                    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
                        break;
                    } else {
                        close_conn(*s, fd);
                        break;
                    }
                }
                if (s->conns.find(fd) != s->conns.end()) {
                    while (parse_one(c, *s)) {}
                    if (!c.out.empty()) flush_out(*s, c);
                    // flush_out may have closed + erased: re-look-up
                    auto it2 = s->conns.find(fd);
                    if (it2 != s->conns.end() && it2->second.closing &&
                        it2->second.out.empty())
                        close_conn(*s, fd);
                }
            }
        }
    }
}

std::shared_ptr<Server> get(int64_t h) {
    // shared_ptr: a caller mid-hf_reply keeps the Server alive across a
    // concurrent hf_stop (stop closes sockets; memory lives until the
    // last caller returns)
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_servers.find(h);
    return it == g_servers.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t hf_start(const char* host, int port, int* out_port) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        ::close(fd);
        return -EINVAL;
    }
    if (bind(fd, (sockaddr*)&addr, sizeof addr) < 0 ||
        listen(fd, 1024) < 0) {
        int e = errno;
        ::close(fd);
        return -e;
    }
    socklen_t alen = sizeof addr;
    getsockname(fd, (sockaddr*)&addr, &alen);
    if (out_port) *out_port = (int)ntohs(addr.sin_port);

    auto sp = std::make_shared<Server>();
    Server* s = sp.get();
    s->listen_fd = fd;
    s->epoll_fd = epoll_create1(0);
    s->event_fd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, fd, &ev);
    ev.data.fd = s->event_fd;
    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->event_fd, &ev);
    s->loop = std::thread(reactor, s);

    std::lock_guard<std::mutex> lk(g_mu);
    int64_t h = g_next_handle++;
    g_servers[h] = sp;
    return h;
}

int64_t hf_poll(int64_t h, uint64_t* ids, int64_t max_n, int timeout_ms) {
    auto s = get(h);
    if (!s) return -1;
    std::unique_lock<std::mutex> lk(s->mu);
    if (s->ready.empty())
        s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                       [&] { return !s->ready.empty(); });
    int64_t n = 0;
    while (n < max_n && !s->ready.empty()) {
        ids[n++] = s->ready.front();
        s->ready.pop_front();
    }
    return n;
}

int hf_req_info(int64_t h, uint64_t id, char* method, int64_t mcap,
                char* path, int64_t pcap, int64_t* body_len,
                int64_t* headers_len) {
    auto s = get(h);
    if (!s) return -1;
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->reqs.find(id);
    if (it == s->reqs.end()) return -1;
    snprintf(method, (size_t)mcap, "%s", it->second.method.c_str());
    snprintf(path, (size_t)pcap, "%s", it->second.path.c_str());
    *body_len = (int64_t)it->second.body.size();
    *headers_len = (int64_t)it->second.headers_raw.size();
    return 0;
}

int64_t hf_req_headers(int64_t h, uint64_t id, char* buf) {
    auto s = get(h);
    if (!s) return -1;
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->reqs.find(id);
    if (it == s->reqs.end()) return -1;
    memcpy(buf, it->second.headers_raw.data(),
           it->second.headers_raw.size());
    return (int64_t)it->second.headers_raw.size();
}

int64_t hf_req_body(int64_t h, uint64_t id, char* buf) {
    auto s = get(h);
    if (!s) return -1;
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->reqs.find(id);
    if (it == s->reqs.end()) return -1;
    memcpy(buf, it->second.body.data(), it->second.body.size());
    return (int64_t)it->second.body.size();
}

int hf_reply(int64_t h, uint64_t id, int status, const char* extra_hdrs,
             const char* body, int64_t len) {
    // extra_hdrs: zero or more pre-formatted "Key: Value\r\n" lines
    // (the pipeline's response headers, minus the reserved ones below)
    auto s = get(h);
    if (!s) return -1;
    std::string resp;
    {
        std::lock_guard<std::mutex> lk(s->mu);
        auto it = s->reqs.find(id);
        if (it == s->reqs.end()) return -1;  // already answered / gone
        bool ka = it->second.keepalive;
        char hdr[128];
        int hl = snprintf(hdr, sizeof hdr, "HTTP/1.1 %d %s\r\n",
                          status, status < 400 ? "OK" : "Error");
        resp.assign(hdr, (size_t)hl);
        if (extra_hdrs && *extra_hdrs) resp += extra_hdrs;
        hl = snprintf(hdr, sizeof hdr,
                      "Content-Length: %lld\r\nConnection: %s\r\n\r\n",
                      (long long)len, ka ? "keep-alive" : "close");
        resp.append(hdr, (size_t)hl);
        resp.append(body, (size_t)len);
        s->replies.emplace_back(id, std::move(resp));
    }
    uint64_t one = 1;
    ssize_t ignored = write(s->event_fd, &one, 8);
    (void)ignored;
    return 0;
}

void hf_stop(int64_t h) {
    std::shared_ptr<Server> s;
    {
        std::lock_guard<std::mutex> lk(g_mu);
        auto it = g_servers.find(h);
        if (it == g_servers.end()) return;
        s = it->second;
        g_servers.erase(it);
    }
    s->stop.store(true);
    uint64_t one = 1;
    ssize_t ignored = write(s->event_fd, &one, 8);
    (void)ignored;
    s->loop.join();
    for (auto& kv : s->conns) ::close(kv.first);
    ::close(s->listen_fd);
    // epoll_fd / event_fd close in ~Server when the last concurrent
    // hf_reply/hf_poll holding a shared_ptr returns — a racing write to
    // event_fd must hit the (dead) eventfd, never a reused fd number
}

}  // extern "C"
