// Native HTTP load generator for the serving benches.
//
// The serving bench's loaded rows drive N keep-alive connections in a
// closed loop. A Python http.client worker costs ~0.25 ms of GIL-held
// work per request — at 16-way that caps the CLIENT at ~4k req/s and
// the measurement reports the load generator, not the server (and the
// client threads steal the GIL from the very server they measure).
// This is the classic reason load tests use wrk/ab; neither ships in
// this image, so this is the minimal equivalent: one OS thread per
// connection, blocking sockets with SO_RCVTIMEO/SO_SNDTIMEO (a server
// that accepts but never replies becomes a transport failure, not a
// thread the bench watchdog cannot kill), TCP_NODELAY, strict
// request-response (no pipelining), per-request wall latency recorded.
//
// Counterpart of the reference's perf narrative for its serving layer
// (docs/mmlspark-serving.md "sub-millisecond latency"); no reference
// source equivalent — its load tests ran external tooling.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cctype>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct ConnResult {
  long errors = 0;   // non-200 responses or transport failures
  bool hard_fail = false;
};

// Per-operation I/O deadline. Applied as SO_RCVTIMEO/SO_SNDTIMEO so a
// recv/send against a stalled server fails (EAGAIN) instead of
// blocking forever; on Linux SO_SNDTIMEO also bounds connect(). A
// timeout surfaces through the existing n<=0 transport-failure paths.
constexpr long kIoTimeoutSec = 5;

int connect_to(const char* host, int port) {
  // getaddrinfo so hostnames ('localhost') work, not just IPv4
  // literals — an unresolvable host is a failed connection, never a
  // silent fallthrough.
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  const std::string service = std::to_string(port);
  if (::getaddrinfo(host, service.c_str(), &hints, &res) != 0 ||
      res == nullptr)
    return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    timeval tv{};
    tv.tv_sec = kIoTimeoutSec;
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  return fd;
}

bool send_all(int fd, const char* buf, size_t len) {
  while (len > 0) {
    ssize_t n = ::send(fd, buf, len, 0);
    if (n <= 0) return false;
    buf += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Case-insensitive scan for a numeric header value within [pos, end).
// Returns the parsed value or `fallback` when the header is absent.
double scan_numeric_header(const std::string& buf, size_t header_end,
                           const char* name, size_t name_len,
                           double fallback) {
  for (size_t pos = 0; pos < header_end;) {
    size_t eol = buf.find("\r\n", pos);
    if (eol == std::string::npos || eol > header_end) eol = header_end;
    if (eol - pos > name_len) {
      bool match = true;
      for (size_t i = 0; i < name_len; ++i)
        if (std::tolower(buf[pos + i]) != name[i]) { match = false; break; }
      if (match) return std::strtod(buf.c_str() + pos + name_len, nullptr);
    }
    pos = eol + 2;
  }
  return fallback;
}

// Read one HTTP/1.1 response; returns status code or -1 on transport
// error. Handles Content-Length bodies (the serving fronts always set
// it); `carry` holds bytes read past the current response (defensive —
// strict request-response means there should be none). `retry_after_s`,
// when non-null, receives the Retry-After header in seconds (0 when
// absent) — the sched subsystem's 429/503 sheds always set it.
// `t_first`, when non-null, receives the time the FIRST byte of this
// response arrived (generation mode: a streaming-shaped server sends
// headers as soon as the first token exists, so first-byte time is the
// client-observed TTFT; carried-over bytes count as immediate).
int read_response(int fd, std::string& carry,
                  double* retry_after_s = nullptr,
                  Clock::time_point* t_first = nullptr) {
  std::string buf = std::move(carry);
  carry.clear();
  bool got_first = !buf.empty();
  if (got_first && t_first) *t_first = Clock::now();
  char tmp[8192];
  size_t header_end;
  while ((header_end = buf.find("\r\n\r\n")) == std::string::npos) {
    ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
    if (n <= 0) return -1;
    if (!got_first) {
      got_first = true;
      if (t_first) *t_first = Clock::now();
    }
    buf.append(tmp, static_cast<size_t>(n));
  }
  int status = -1;
  if (buf.size() >= 12 && buf.compare(0, 5, "HTTP/") == 0)
    status = std::atoi(buf.c_str() + 9);
  size_t clen = static_cast<size_t>(scan_numeric_header(
      buf, header_end, "content-length:", 15, 0.0));
  if (retry_after_s)
    *retry_after_s = scan_numeric_header(buf, header_end,
                                         "retry-after:", 12, 0.0);
  size_t need = header_end + 4 + clen;
  while (buf.size() < need) {
    ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
    if (n <= 0) return -1;
    buf.append(tmp, static_cast<size_t>(n));
  }
  if (buf.size() > need) carry = buf.substr(need);
  return status;
}

// Cap on how long a Retry-After instruction is honored: the bench's
// retry exists to measure the shed/retry contract, not to park a
// closed-loop thread for a server-chosen eternity.
constexpr double kMaxRetryAfterSec = 2.0;

// Per-request W3C-style traceparent header: trace id =
// <prefix><conn:4hex><req:8hex>, so the Python summary can RECONSTRUCT
// the trace id of any (connection, request) slot — the p99-slowest
// requests become flight-recorder lookup keys without shipping ids
// back through the FFI.
std::string trace_header(const std::string& prefix, int conn, long req) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%04x%08lx", conn,
                static_cast<unsigned long>(req));
  return "Traceparent: 00-" + prefix + buf + "-0001-01\r\n";
}

void run_conn(const char* host, int port, const std::string& head,
              const std::string& body, const std::string& trace_prefix,
              const std::string& tenant_header, int conn_idx, long nreq,
              int retry_shed, double* lat_ms, int* status_out,
              double* ttft_ms, ConnResult* res) {
  int fd = connect_to(host, port);
  if (fd < 0) {
    res->hard_fail = true;
    res->errors = nreq;
    for (long i = 0; i < nreq; ++i) {
      lat_ms[i] = -1.0;
      if (status_out) status_out[i] = -1;
      if (ttft_ms) ttft_ms[i] = -1.0;
    }
    return;
  }
  std::string carry;
  // the tenant header is fixed PER CONNECTION (lg_run5): one closed
  // loop = one tenant, so the Python summary can split percentiles and
  // shed counts per tenant from connection-major matrices alone
  std::string request = head + tenant_header + "\r\n" + body;
  for (long i = 0; i < nreq; ++i) {
    if (!trace_prefix.empty())
      request = head + tenant_header
          + trace_header(trace_prefix, conn_idx, i) + "\r\n" + body;
    auto t0 = Clock::now();
    auto tf = t0;
    int status = -1;
    double retry_after = 0.0;
    if (send_all(fd, request.data(), request.size()))
      status = read_response(fd, carry, &retry_after,
                             ttft_ms ? &tf : nullptr);
    auto t1 = Clock::now();
    bool retried = false;
    if (retry_shed && (status == 429 || status == 503)) {
      // honor the shed's Retry-After with ONE bounded re-attempt;
      // the recorded latency is the re-attempt's round trip (the
      // back-off wait is the server's instruction, not its latency).
      // Same traceparent: one logical request, one trace.
      double wait = retry_after > 0 ? retry_after : 0.05;
      if (wait > kMaxRetryAfterSec) wait = kMaxRetryAfterSec;
      timespec ts;
      ts.tv_sec = static_cast<time_t>(wait);
      ts.tv_nsec = static_cast<long>((wait - ts.tv_sec) * 1e9);
      ::nanosleep(&ts, nullptr);
      t0 = Clock::now();
      tf = t0;
      status = -1;
      if (send_all(fd, request.data(), request.size()))
        status = read_response(fd, carry, nullptr,
                               ttft_ms ? &tf : nullptr);
      t1 = Clock::now();
      retried = true;
    }
    // transport failures record -1, NOT time-until-failure: a dead
    // server fails sends in ~0.05 ms and near-zero "latencies" would
    // otherwise pollute the percentiles and count as completions.
    // Non-200 HTTP replies are real round trips — latency stands,
    // error counted; the per-request status lets the Python side
    // separate sheds (429) from successes instead of folding them.
    // A retried request reports status + 1000 (e.g. 1200 = 200 on
    // the bounded re-attempt), so retry traffic stays distinguishable
    // from first-offer load in the summary.
    lat_ms[i] = status < 0 ? -1.0
        : std::chrono::duration<double, std::milli>(t1 - t0).count();
    // TTFT mirrors the latency conventions: -1 on transport failure,
    // and a retried request reports the re-attempt's first byte (same
    // reasoning — the back-off wait is the server's instruction).
    if (ttft_ms)
      ttft_ms[i] = status < 0 ? -1.0
          : std::chrono::duration<double, std::milli>(tf - t0).count();
    if (status_out)
      status_out[i] = (retried && status >= 0) ? status + 1000 : status;
    if (status != 200) {
      ++res->errors;
      if (status < 0) {  // transport death: reconnect once, else bail
        ::close(fd);
        fd = connect_to(host, port);
        if (fd < 0) {
          for (long j = i + 1; j < nreq; ++j) {
            lat_ms[j] = -1.0;
            if (status_out) status_out[j] = -1;
            if (ttft_ms) ttft_ms[j] = -1.0;
          }
          res->errors += nreq - i - 1;
          res->hard_fail = true;
          return;
        }
        carry.clear();
      }
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// Drive `nconn` keep-alive connections of `nreq` serial POSTs each.
// lat_ms must hold nconn*nreq doubles (connection-major; failed slots
// are -1); status_out, when non-null, receives the per-request HTTP
// status (-1 = transport failure) so the caller can split successes
// from sheds (429) and errors instead of folding them into one number.
// retry_shed != 0 honors Retry-After on 429/503 with one bounded
// re-attempt; such requests report status + 1000 (1200 = 200 on the
// re-attempt) so retry traffic is distinguishable from first-offer
// load. trace_prefix, when non-empty, stamps every request with a
// deterministic traceparent (<prefix><conn:4hex><req:8hex>) so outliers
// can be looked up in the server's flight recorder. tenants, when
// non-empty, is a comma-separated list: connection c stamps
// "X-Tenant: <tenants[c % n]>" on every request (one tenant per
// connection, so the Python summary can split its per-tenant columns
// from connection-major matrices). ttft_ms, when non-null, must hold
// nconn*nreq doubles (connection-major) and receives each request's
// time-to-first-byte — the generation-mode TTFT: an LLM serving front
// answers when the first token exists, so first-byte time is what a
// client perceives as time-to-first-token (-1 on transport failure; a
// retried request reports the re-attempt's first byte, matching
// lat_ms). Returns total non-200/transport errors, or -1 when every
// connection failed to even connect.
long lg_run6(const char* host, int port, int nconn, long nreq,
             const char* path, const unsigned char* body, long body_len,
             int retry_shed, const char* trace_prefix,
             const char* tenants, double* lat_ms, int* status_out,
             double* ttft_ms, double* wall_s) {
  // head stops before the blank line: the per-connection X-Tenant and
  // per-request traceparent (and the terminating \r\n) are appended
  // per connection/send
  std::string head;
  head.reserve(256);
  head += "POST ";
  head += path;
  head += " HTTP/1.1\r\nHost: bench\r\nContent-Length: ";
  head += std::to_string(body_len);
  head += "\r\nConnection: keep-alive\r\n";
  std::string payload(reinterpret_cast<const char*>(body),
                      static_cast<size_t>(body_len));
  std::string prefix(trace_prefix ? trace_prefix : "");
  std::vector<std::string> tenant_headers;
  if (tenants && tenants[0]) {
    std::string list(tenants);
    size_t pos = 0;
    while (pos <= list.size()) {
      size_t comma = list.find(',', pos);
      if (comma == std::string::npos) comma = list.size();
      if (comma > pos)
        tenant_headers.push_back(
            "X-Tenant: " + list.substr(pos, comma - pos) + "\r\n");
      pos = comma + 1;
    }
  }
  if (tenant_headers.empty()) tenant_headers.push_back("");

  std::vector<ConnResult> results(static_cast<size_t>(nconn));
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(nconn));
  auto t0 = Clock::now();
  for (int c = 0; c < nconn; ++c)
    threads.emplace_back(run_conn, host, port, std::cref(head),
                         std::cref(payload), std::cref(prefix),
                         std::cref(tenant_headers[
                             static_cast<size_t>(c)
                             % tenant_headers.size()]),
                         c, nreq, retry_shed,
                         lat_ms + static_cast<long>(c) * nreq,
                         status_out ? status_out
                             + static_cast<long>(c) * nreq : nullptr,
                         ttft_ms ? ttft_ms
                             + static_cast<long>(c) * nreq : nullptr,
                         &results[static_cast<size_t>(c)]);
  for (auto& t : threads) t.join();
  auto t1 = Clock::now();
  if (wall_s) *wall_s = std::chrono::duration<double>(t1 - t0).count();

  long errors = 0;
  int hard = 0;
  for (auto& r : results) {
    errors += r.errors;
    hard += r.hard_fail ? 1 : 0;
  }
  if (hard == nconn) return -1;
  return errors;
}

// Back-compat entry point (no time-to-first-byte reporting).
long lg_run5(const char* host, int port, int nconn, long nreq,
             const char* path, const unsigned char* body, long body_len,
             int retry_shed, const char* trace_prefix,
             const char* tenants, double* lat_ms, int* status_out,
             double* wall_s) {
  return lg_run6(host, port, nconn, nreq, path, body, body_len,
                 retry_shed, trace_prefix, tenants, lat_ms, status_out,
                 nullptr, wall_s);
}

// Back-compat entry point (no per-connection X-Tenant stamping).
long lg_run4(const char* host, int port, int nconn, long nreq,
             const char* path, const unsigned char* body, long body_len,
             int retry_shed, const char* trace_prefix, double* lat_ms,
             int* status_out, double* wall_s) {
  return lg_run5(host, port, nconn, nreq, path, body, body_len,
                 retry_shed, trace_prefix, "", lat_ms, status_out,
                 wall_s);
}

// Back-compat entry point (no traceparent stamping).
long lg_run3(const char* host, int port, int nconn, long nreq,
             const char* path, const unsigned char* body, long body_len,
             int retry_shed, double* lat_ms, int* status_out,
             double* wall_s) {
  return lg_run4(host, port, nconn, nreq, path, body, body_len,
                 retry_shed, "", lat_ms, status_out, wall_s);
}

// Back-compat entry point (no Retry-After re-attempts).
long lg_run2(const char* host, int port, int nconn, long nreq,
             const char* path, const unsigned char* body, long body_len,
             double* lat_ms, int* status_out, double* wall_s) {
  return lg_run3(host, port, nconn, nreq, path, body, body_len, 0,
                 lat_ms, status_out, wall_s);
}

// Back-compat entry point (no per-request statuses).
long lg_run(const char* host, int port, int nconn, long nreq,
            const char* path, const unsigned char* body, long body_len,
            double* lat_ms, double* wall_s) {
  return lg_run2(host, port, nconn, nreq, path, body, body_len, lat_ms,
                 nullptr, wall_s);
}

}  // extern "C"
