// Native IO runtime: multithreaded CSV parsing + bulk file reading.
//
// Role of the reference's native data path: LightGBM/VW ingest data through
// C++ loaders behind JNI, and NativeLoader.java extracts + System.load()s
// the shared objects (core/env/NativeLoader.java:28-110). Here the native
// layer feeds the columnar DataFrame: CSV bytes -> float32 column-major
// matrix (NaN for missing/non-numeric), parallelized by row ranges.
//
// C ABI only (ctypes-friendly): no exceptions across the boundary.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <thread>
#include <vector>

extern "C" {

// Count rows (newlines outside the header) and columns in the first line.
// Returns 0 on success.
int csv_dims(const char* data, int64_t len, int has_header,
             int64_t* out_rows, int64_t* out_cols) {
    if (len <= 0) { *out_rows = 0; *out_cols = 0; return 0; }
    int64_t cols = 1;
    int64_t i = 0;
    for (; i < len && data[i] != '\n'; ++i)
        if (data[i] == ',') ++cols;
    int64_t lines = 0;
    const char* p = data;
    const char* end = data + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!nl) { if (end - p > 0) ++lines; break; }
        if (nl - p > 0) ++lines;  // skip empty lines
        p = nl + 1;
    }
    *out_rows = lines - (has_header ? 1 : 0);
    *out_cols = cols;
    return 0;
}

static inline const char* next_line(const char* p, const char* end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    return nl ? nl + 1 : end;
}

// Parse one row range [row_begin, row_end) starting at byte offset
// `start` into out[row * cols + col]. Non-numeric / empty cells -> NaN.
static void parse_range(const char* data, const char* end,
                        const char* start, int64_t row_begin,
                        int64_t row_end, int64_t cols, float* out) {
    const char* p = start;
    for (int64_t r = row_begin; r < row_end && p < end;) {
        if (*p == '\n') { ++p; continue; }  // empty line
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        int64_t c = 0;
        const char* cell = p;
        while (cell <= line_end && c < cols) {
            const char* comma = static_cast<const char*>(
                memchr(cell, ',', static_cast<size_t>(line_end - cell)));
            const char* cell_end = comma ? comma : line_end;
            float v;
            if (cell_end == cell) {
                v = NAN;
            } else {
                char* parsed_end = nullptr;
                v = strtof(cell, &parsed_end);
                if (parsed_end == cell) v = NAN;
            }
            out[r * cols + c] = v;
            ++c;
            if (!comma) break;
            cell = comma + 1;
        }
        for (; c < cols; ++c) out[r * cols + c] = NAN;
        p = line_end < end ? line_end + 1 : end;
        ++r;
    }
}

// Parse the full CSV into a preallocated [rows, cols] float32 buffer.
// Threads split by row ranges (each scans to its start line first).
int csv_parse(const char* data, int64_t len, int has_header,
              int64_t rows, int64_t cols, float* out, int n_threads) {
    const char* end = data + len;
    const char* body = data;
    if (has_header) body = next_line(body, end);
    if (rows <= 0) return 0;
    if (n_threads <= 0) n_threads = 1;
    if (n_threads > rows) n_threads = static_cast<int>(rows);

    // find the starting byte of each thread's row range
    std::vector<const char*> starts(static_cast<size_t>(n_threads));
    std::vector<int64_t> row_begins(static_cast<size_t>(n_threads));
    int64_t per = rows / n_threads;
    {
        const char* p = body;
        int64_t row = 0;
        for (int t = 0; t < n_threads; ++t) {
            int64_t target = static_cast<int64_t>(t) * per;
            while (row < target && p < end) {
                if (*p != '\n') ++row;
                else { ++p; continue; }
                p = next_line(p, end);
            }
            starts[static_cast<size_t>(t)] = p;
            row_begins[static_cast<size_t>(t)] = row;
        }
    }
    std::vector<std::thread> pool;
    for (int t = 0; t < n_threads; ++t) {
        int64_t rb = row_begins[static_cast<size_t>(t)];
        int64_t re = (t + 1 == n_threads) ? rows
                     : row_begins[static_cast<size_t>(t + 1)];
        pool.emplace_back(parse_range, data, end,
                          starts[static_cast<size_t>(t)], rb, re, cols,
                          out);
    }
    for (auto& th : pool) th.join();
    return 0;
}

// Read a whole file into a caller buffer; returns bytes read or -1.
int64_t read_file(const char* path, char* buf, int64_t cap) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    int64_t total = 0;
    while (total < cap) {
        size_t got = fread(buf + total, 1,
                           static_cast<size_t>(cap - total), f);
        if (got == 0) break;
        total += static_cast<int64_t>(got);
    }
    fclose(f);
    return total;
}

int64_t file_size(const char* path) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fclose(f);
    return static_cast<int64_t>(sz);
}

}  // extern "C"
