// Batch VW-compatible feature hashing.
//
// Role of the reference's Scala-native featurizer hot loop
// (vw/VowpalWabbitMurmurWithPrefix.scala + vw/featurizer/*): hashing is
// reimplemented natively so featurization never bottlenecks on the
// interpreter. MurmurHash3 x86_32, bit-identical to mmlspark_tpu.vw.murmur
// (verified by parity tests).
//
// Interface: one concatenated UTF-8 buffer + per-row input offsets;
// outputs are caller-allocated CSR buffers — row r writes its entries at
// out_idx/out_val[out_offsets[r] .. out_offsets[r+1]) and reports the
// filled count in out_n[r]. Rows are processed in parallel with
// std::thread.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

static inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

extern "C" uint32_t vw_murmur3_32(const uint8_t* data, int64_t len,
                                  uint32_t seed) {
    const uint32_t c1 = 0xCC9E2D51u, c2 = 0x1B873593u;
    uint32_t h = seed;
    const int64_t nblocks = len / 4;
    for (int64_t i = 0; i < nblocks; i++) {
        uint32_t k;
        std::memcpy(&k, data + 4 * i, 4);  // little-endian hosts
        k *= c1;
        k = rotl32(k, 15);
        k *= c2;
        h ^= k;
        h = rotl32(h, 13);
        h = h * 5 + 0xE6546B64u;
    }
    const uint8_t* tail = data + nblocks * 4;
    uint32_t k = 0;
    switch (len & 3) {
        case 3: k ^= (uint32_t)tail[2] << 16; [[fallthrough]];
        case 2: k ^= (uint32_t)tail[1] << 8;  [[fallthrough]];
        case 1:
            k ^= tail[0];
            k *= c1;
            k = rotl32(k, 15);
            k *= c2;
            h ^= k;
    }
    h ^= (uint32_t)len;
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

namespace {

// append (idx, 1.0) into the row's slice, summing on duplicate idx when
// sum_collisions (linear scan — per-row entry counts are small)
inline void emit(int32_t idx, float value, int32_t* row_idx, float* row_val,
                 int32_t& count, int32_t W, bool sum_collisions) {
    if (sum_collisions) {
        for (int32_t j = 0; j < count; j++) {
            if (row_idx[j] == idx) {
                row_val[j] += value;
                return;
            }
        }
    }
    if (count < W) {
        row_idx[count] = idx;
        row_val[count] = value;
        count++;
    }
}

struct Job {
    const char* buf;
    const int64_t* offsets;
    const char* prefix;
    int64_t prefix_len;
    uint32_t ns_hash;
    uint32_t mask;
    int mode;  // 0 = categorical prefix+value, 1 = whitespace token split
    const int64_t* out_offsets;  // CSR: row r writes [out_offsets[r],
                                 // out_offsets[r+1]) — O(nnz) memory
    bool sum_collisions;
    int32_t* out_idx;
    float* out_val;
    int32_t* out_n;
};

void hash_rows(const Job& job, int64_t lo, int64_t hi) {
    std::string scratch;
    scratch.reserve(256);
    for (int64_t r = lo; r < hi; r++) {
        const char* s = job.buf + job.offsets[r];
        const int64_t len = job.offsets[r + 1] - job.offsets[r];
        int32_t* row_idx = job.out_idx + job.out_offsets[r];
        float* row_val = job.out_val + job.out_offsets[r];
        const int32_t W =
            (int32_t)(job.out_offsets[r + 1] - job.out_offsets[r]);
        int32_t count = 0;
        auto hash_token = [&](const char* tok, int64_t tok_len) {
            scratch.assign(job.prefix, (size_t)job.prefix_len);
            scratch.append(tok, (size_t)tok_len);
            const uint32_t h = vw_murmur3_32(
                (const uint8_t*)scratch.data(), (int64_t)scratch.size(),
                job.ns_hash);
            emit((int32_t)(h & job.mask), 1.0f, row_idx, row_val, count,
                 W, job.sum_collisions);
        };
        if (job.mode == 0) {
            // categorical: even an empty value is a feature (prefix-only
            // hash) — None rows never reach this function
            hash_token(s, len);
        } else {
            // explicit ASCII-space split ONLY: the Python side already
            // Unicode-tokenized and re-joined with ' '; locale-dependent
            // std::isspace could misclassify UTF-8 continuation bytes
            auto is_sep = [](char c) { return c == ' '; };
            int64_t i = 0;
            while (i < len) {
                while (i < len && is_sep(s[i])) i++;
                int64_t start = i;
                while (i < len && !is_sep(s[i])) i++;
                if (i > start) hash_token(s + start, i - start);
            }
        }
        job.out_n[r] = count;
    }
}

}  // namespace

extern "C" void vw_hash_strings(const char* buf, const int64_t* offsets,
                                int64_t n, const char* prefix,
                                int64_t prefix_len, uint32_t ns_hash,
                                int num_bits, int mode,
                                const int64_t* out_offsets,
                                int sum_collisions, int32_t* out_idx,
                                float* out_val, int32_t* out_n) {
    Job job{buf, offsets, prefix, prefix_len, ns_hash,
            (uint32_t)((1u << num_bits) - 1), mode, out_offsets,
            sum_collisions != 0, out_idx, out_val, out_n};
    const int64_t min_per_thread = 2048;
    int threads = (int)std::min<int64_t>(
        std::thread::hardware_concurrency() ?
            std::thread::hardware_concurrency() : 1,
        std::max<int64_t>(1, n / min_per_thread));
    if (threads <= 1) {
        hash_rows(job, 0, n);
        return;
    }
    std::vector<std::thread> pool;
    const int64_t chunk = (n + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(n, lo + chunk);
        if (lo >= hi) break;
        pool.emplace_back([&job, lo, hi] { hash_rows(job, lo, hi); });
    }
    for (auto& th : pool) th.join();
}
