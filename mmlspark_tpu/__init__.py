"""mmlspark_tpu — TPU-native framework with the capabilities of MMLSpark.

Compute path: JAX / XLA / Pallas / pjit. Public API: SparkML-shaped
Estimator/Transformer/Pipeline stages over a columnar DataFrame.
"""

__version__ = "0.1.0"

from .core import (DataFrame, Pipeline, PipelineModel, Transformer, Estimator,
                   Model, load_stage)

__all__ = ["DataFrame", "Pipeline", "PipelineModel", "Transformer",
           "Estimator", "Model", "load_stage", "__version__"]
