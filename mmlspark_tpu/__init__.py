"""mmlspark_tpu — TPU-native framework with the capabilities of MMLSpark.

Compute path: JAX / XLA / Pallas / pjit. Public API: SparkML-shaped
Estimator/Transformer/Pipeline stages over a columnar DataFrame.
"""

__version__ = "0.1.0"

from .core import (DataFrame, Pipeline, PipelineModel, Transformer, Estimator,
                   Model, load_stage)

# Subpackages (imported lazily by users):
#   lightgbm  — GBDT engine + estimators        (reference lightgbm/)
#   vw        — sparse online learning          (reference vw/)
#   dl, models, image — DL inference/training   (reference cntk/, image/,
#                                                opencv/, downloader/)
#   parallel  — mesh/collectives/ring attention (reference L3 comm layer)
#   featurize, stages — data prep               (reference featurize/, stages/)
#   train, automl — auto-training + sweeps      (reference train/, automl/)
#   nn, recommendation, isolationforest, lime — learners long tail
#   io        — binary/image readers, writers   (reference io/)
#   obs, sched, resilience — serving/ops planes (metrics+tracing,
#                            admission control, retry/breaker/faults)

__all__ = ["DataFrame", "Pipeline", "PipelineModel", "Transformer",
           "Estimator", "Model", "load_stage", "__version__"]
