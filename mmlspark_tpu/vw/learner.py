"""Sparse linear online learner — the engine under the VW estimators.

Reference: native VW's online SGD reached via ``example.learn()`` row-by-row
(``vw/VowpalWabbitBase.scala:280-291``), with AllReduce weight averaging
across workers per pass (``:434-461``). TPU formulation:

- features are padded COO (indices [n, k] int32 with -1 padding,
  values [n, k] f32);
- one ``lax.scan`` over minibatches per pass; each step computes batch
  predictions via gather + segment-sum and applies a scatter-add update —
  VW's per-example updates become per-minibatch (batch size 1 recovers
  exact online behavior at a throughput cost, and is the default for
  parity);
- AdaGrad per-weight scaling reproduces VW's ``--adaptive`` default;
  ``power_t`` decay reproduces the invariant schedule's t^-p factor;
- multi-pass + mesh → weights ``pmean``-averaged across shards per pass,
  the collective that replaces the spanning tree.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from ..parallel import collectives as _coll
from ..parallel.compat import shard_map as _shard_map


@dataclasses.dataclass
class VWConfig:
    num_bits: int = 18
    loss_function: str = "squared"     # squared | logistic | quantile | hinge
    learning_rate: float = 0.5
    power_t: float = 0.5
    l1: float = 0.0
    l2: float = 0.0
    num_passes: int = 1
    adaptive: bool = True
    initial_weight: float = 0.0
    link: str = "identity"
    quantile_tau: float = 0.5
    batch_size: int = 256


def _loss_grad(loss: str, tau: float):
    """d loss / d prediction (pre-link raw score). Labels follow VW
    conventions: logistic/hinge expect y ∈ {-1, +1}."""
    if loss == "squared":
        return lambda p, y: 2.0 * (p - y)
    if loss == "logistic":
        return lambda p, y: -y / (1.0 + jnp.exp(y * p))
    if loss == "hinge":
        return lambda p, y: jnp.where(y * p < 1.0, -y, 0.0)
    if loss == "quantile":
        return lambda p, y: jnp.where(p > y, 1.0 - tau, -tau)
    raise ValueError(f"unknown loss {loss!r}")


@dataclasses.dataclass
class VWModelState:
    weights: np.ndarray        # [2^bits] float32
    bias: float
    config: VWConfig

    def predict_raw(self, indices: np.ndarray, values: np.ndarray):
        w = jnp.asarray(self.weights)
        indices = _strip_to_table(indices, self.config.num_bits)
        return np.asarray(_predict_raw(w, jnp.asarray(self.bias),
                                       jnp.asarray(indices),
                                       jnp.asarray(values)))


def _strip_to_table(indices: np.ndarray, num_bits: int) -> np.ndarray:
    """Mask feature indices into the 2^num_bits weight table, keeping -1
    padding. VW strips anything above its bit budget — including the
    featurizer's preserveOrderNumBits position prefix, which exists for
    downstream consumers, not the learner ('will be stripped when
    passing to VW', reference VowpalWabbitFeaturizer.scala transform).
    Without the strip, out-of-table indices silently drop from XLA
    scatter/gather and those features never train."""
    indices = np.asarray(indices)
    if indices.size and indices.max(initial=0) < (1 << num_bits):
        return indices
    mask = (1 << num_bits) - 1
    return np.where(indices >= 0, indices & mask, -1).astype(np.int32)


@jax.jit
def _predict_raw(w, bias, indices, values):
    mask = indices >= 0
    safe = jnp.where(mask, indices, 0)
    return bias + jnp.sum(jnp.where(mask, w[safe] * values, 0.0), axis=1)


def train(indices: np.ndarray, values: np.ndarray, labels: np.ndarray,
          weights: np.ndarray | None, cfg: VWConfig,
          initial: VWModelState | None = None,
          mesh=None, mesh_axis: str = "dp") -> VWModelState:
    """Train the sparse linear model. indices/values [n, k], labels [n]."""
    n, k = indices.shape
    dim = 1 << cfg.num_bits
    bs = max(1, min(cfg.batch_size, n))
    n_batches = -(-n // bs)
    n_pad = n_batches * bs - n

    # pad rows with weight 0 (never influence updates)
    indices = _strip_to_table(indices, cfg.num_bits)
    idx = np.pad(indices, ((0, n_pad), (0, 0)), constant_values=-1)
    val = np.pad(values, ((0, n_pad), (0, 0)))
    y = np.pad(np.asarray(labels, np.float32), (0, n_pad))
    ex_w = np.ones(n, np.float32) if weights is None \
        else np.asarray(weights, np.float32)
    ex_w = np.pad(ex_w, (0, n_pad))

    batches = (idx.reshape(n_batches, bs, k),
               val.reshape(n_batches, bs, k).astype(np.float32),
               y.reshape(n_batches, bs),
               ex_w.reshape(n_batches, bs))

    grad_fn = _loss_grad(cfg.loss_function, cfg.quantile_tau)

    @jax.jit
    def run_pass(w, bias, g2, g2b, t0, batch_arrays):
        def step(carry, batch):
            w, bias, g2, g2b, t = carry
            bidx, bval, by, bw = batch
            mask = bidx >= 0
            safe = jnp.where(mask, bidx, 0)
            pred = bias + jnp.sum(
                jnp.where(mask, w[safe] * bval, 0.0), axis=1)
            dl = grad_fn(pred, by) * bw                    # [bs]
            t = t + jnp.sum(bw > 0)
            # lr schedule: AdaGrad already decays adaptive runs, so the
            # t^-power_t factor applies only to plain SGD (VW couples
            # power_t with its non-adaptive invariant update)
            if cfg.adaptive or cfg.power_t <= 0:
                eta = cfg.learning_rate
            else:
                eta = cfg.learning_rate * jnp.power(
                    jnp.maximum(t, 1.0), -cfg.power_t)
            gw = dl[:, None] * bval                        # [bs, k]
            gw = jnp.where(mask, gw, 0.0)
            if cfg.adaptive:
                g2 = g2.at[safe.ravel()].add(
                    jnp.where(mask, gw * gw, 0.0).ravel())
                scale = jax.lax.rsqrt(g2[safe] + 1e-12)
                upd = eta * gw * jnp.where(mask, scale, 0.0)
            else:
                upd = eta * gw / bs
            # Regularization follows VW's lazy/truncated-gradient scheme:
            # a weight is decayed/shrunk only when (and as often as) it is
            # touched, scaled by example weight — NOT the whole 2^bits
            # vector per minibatch, which would couple the effective
            # penalty to batch count and repeatedly shrink rare features.
            if cfg.l1 > 0 or cfg.l2 > 0:
                touch = jnp.zeros_like(w).at[safe.ravel()].add(
                    jnp.where(mask, bw[:, None], 0.0).ravel())
            if cfg.l2 > 0:
                w = w * jnp.power(1.0 - eta * cfg.l2, touch)
            w = w.at[safe.ravel()].add(-upd.ravel())
            gb = jnp.sum(dl)
            if cfg.adaptive:
                g2b = g2b + gb * gb
                bias = bias - eta * gb * jax.lax.rsqrt(g2b + 1e-12)
            else:
                bias = bias - eta * gb / bs
            if cfg.l1 > 0:
                w = jnp.sign(w) * jnp.maximum(
                    jnp.abs(w) - eta * cfg.l1 * touch, 0.0)
            return (w, bias, g2, g2b, t), None

        (w, bias, g2, g2b, t0), _ = jax.lax.scan(
            step, (w, bias, g2, g2b, t0), batch_arrays)
        return w, bias, g2, g2b, t0

    if initial is not None:
        w = jnp.asarray(initial.weights)
        bias = jnp.asarray(initial.bias)
    else:
        w = jnp.full(dim, cfg.initial_weight, jnp.float32)
        bias = jnp.zeros((), jnp.float32)
    g2 = jnp.zeros(dim, jnp.float32)
    g2b = jnp.zeros((), jnp.float32)
    t = jnp.zeros((), jnp.float32)

    if mesh is None:
        step_pass = run_pass
        batch_dev = tuple(jnp.asarray(b) for b in batches)
    else:
        # Distributed semantics of the reference
        # (``trainInternalDistributed``, ``VowpalWabbitBase.scala:434-461``):
        # each worker consumes its own shard of examples every pass, then
        # weights are averaged — spanning-tree AllReduce → pmean over the
        # mesh axis. Batches are padded to the shard count with zero-weight
        # batches (the empty-partition case).
        from jax.sharding import PartitionSpec as P
        n_dev = int(mesh.shape[mesh_axis])
        nb_pad = (-n_batches) % n_dev
        batch_dev = tuple(
            jnp.asarray(np.pad(b, ((0, nb_pad),) + ((0, 0),) * (b.ndim - 1),
                               constant_values=(-1 if b.dtype == np.int32
                                                else 0)))
            for b in batches)

        def local_pass(w, bias, g2, g2b, t, batch_arrays):
            w, bias, g2, g2b, t = run_pass(w, bias, g2, g2b, t,
                                           batch_arrays)
            mean = lambda v: _coll.allreduce(v, mesh_axis, op="mean")
            return mean(w), mean(bias), mean(g2), mean(g2b), mean(t)

        rep = P()
        step_pass = _shard_map(
            local_pass, mesh=mesh,
            in_specs=(rep, rep, rep, rep, rep, P(mesh_axis)),
            out_specs=(rep, rep, rep, rep, rep), check_vma=False)

    for _ in range(cfg.num_passes):
        w, bias, g2, g2b, t = step_pass(w, bias, g2, g2b, t, batch_dev)

    return VWModelState(weights=np.asarray(w), bias=float(bias), config=cfg)
