"""VowpalWabbitInteractions — quadratic/cubic feature crossing.

Reference ``vw/VowpalWabbitInteractions.scala`` (à la VW ``-q``/``--cubic``):
cross the features of two (or more) hashed namespaces into new hashed
features, weight = product of constituent weights.
"""

from __future__ import annotations

import itertools

import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCols, HasOutputCol
from .murmur import interaction_hash


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """Inputs are padded-COO column pairs (``<col>_indices``/``_values``)
    produced by VowpalWabbitFeaturizer; output is the crossed sparse
    columns under ``<outputCol>_indices``/``_values``.

    Index combine is the reference's FNV-1 recursion
    (``vw/VowpalWabbitInteractions.scala:49-66``): intermediates stay
    full 32-bit, the num_bits mask lands only on the final index.
    Colliding crossed indices are summed (or first-kept) per the
    ``sumCollisions`` param (``vw/VectorUtils.scala`` sortAndDistinct).
    """

    numBits = Param("numBits", "log2 feature space", TC.toInt, default=18)
    sumCollisions = Param("sumCollisions",
                          "sum values for colliding interaction indices "
                          "(else keep the first)", TC.toBoolean, default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="interactions")

    def _transform(self, df):
        cols = self.getInputCols()
        num_bits = self.get("numBits")
        sum_collisions = self.get("sumCollisions")
        n = len(df)
        per_col = [(np.asarray(df[f"{c}_indices"]),
                    np.asarray(df[f"{c}_values"], np.float32))
                   for c in cols]

        all_i, all_v = [], []
        for r in range(n):
            row_feats = []
            for idx, val in per_col:
                keep = idx[r] >= 0
                row_feats.append(list(zip(idx[r][keep].tolist(),
                                          val[r][keep].tolist())))
            crossed: dict[int, float] = {}
            for combo in itertools.product(*row_feats):
                h = interaction_hash((fi for fi, _ in combo), num_bits)
                v = 1.0
                for _, fv in combo:
                    v *= fv
                if h in crossed:
                    if sum_collisions:
                        crossed[h] += v
                else:
                    crossed[h] = v
            ri = sorted(crossed)
            rv = [crossed[i] for i in ri]
            all_i.append(ri)
            all_v.append(rv)

        width = max((len(r) for r in all_i), default=1) or 1
        indices = np.full((n, width), -1, np.int32)
        values = np.zeros((n, width), np.float32)
        for r, (ri, rv) in enumerate(zip(all_i, all_v)):
            indices[r, :len(ri)] = ri
            values[r, :len(rv)] = rv
        out = self.getOutputCol()
        return (df.with_column(f"{out}_indices", indices)
                  .with_column(f"{out}_values", values))
