"""VW-compatible MurmurHash3 (x86_32).

The reference reimplements VW's hashing natively in Scala so the featurizer
can run without JNI (``vw/VowpalWabbitMurmurWithPrefix.scala``,
``org.vowpalwabbit.spark.VowpalWabbitMurmur``); we do the same in Python.
VW semantics: feature strings hash with the namespace hash as seed; pure
integer feature names hash as ``int + seed`` (VW's ``hashstring`` treats
all-digit strings numerically when ``--hash strings`` is not set — the
reference's StringFeaturizer always string-hashes, which we follow).
"""

from __future__ import annotations

import numpy as np

_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86_32 over bytes → uint32."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * c1) & _M32
        k = _rotl32(k, 15)
        k = (k * c2) & _M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
    tail = data[nblocks * 4:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & _M32
        k = _rotl32(k, 15)
        k = (k * c2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def vw_hash(s: str, seed: int = 0) -> int:
    """VW ``hashstring``: all-digit strings hash numerically
    (``value + seed``), others murmur (VW src/hash.h semantics, which the
    reference's JNI VowpalWabbitMurmur.hash mirrors)."""
    stripped = s.strip()
    if stripped and all(c.isdigit() for c in stripped):
        return (int(stripped) + seed) & _M32
    return murmur3_32(s.encode("utf-8"), seed)


def vw_feature_hash(name: str, namespace_hash: int, num_bits: int) -> int:
    """Feature index = mask & murmur(name, namespaceHash) — the reference's
    per-featurizer pattern (``vw/featurizer/StringFeaturizer.scala``)."""
    mask = (1 << num_bits) - 1
    return mask & murmur3_32(name.encode("utf-8"), namespace_hash)


def namespace_hash(namespace: str, hash_seed: int = 0) -> int:
    """VW hashes the namespace string with the global seed
    (``VowpalWabbitBase`` hashSeed param)."""
    return vw_hash(namespace, hash_seed) if namespace else hash_seed


FNV_PRIME = 16777619


def interaction_hash(indices, num_bits: int) -> int:
    """VW/reference feature-interaction hash (FNV-1 combine,
    ``vw/VowpalWabbitInteractions.scala:49-66``): starting from 0, fold
    each constituent index with ``idx = idx * 16777619 ^ next`` in 32-bit
    wrap-around arithmetic; the num_bits mask is applied ONLY to the
    final combined index (intermediate combines stay full-width)."""
    h = 0
    for idx in indices:
        h = ((h * FNV_PRIME) & _M32) ^ (idx & _M32)
    return ((1 << num_bits) - 1) & h


def quadratic_hash(idx_a: int, idx_b: int, num_bits: int) -> int:
    """Two-way interaction index (FNV-1 combine, final-mask only)."""
    return interaction_hash((idx_a, idx_b), num_bits)
