"""Vowpal-Wabbit-equivalent sparse online learning.

Reference package ``vw/`` (SURVEY §2.4): JNI bindings over native VW
(``vw-jni 8.9.1``) — hashing featurizer, online SGD learners, contextual
bandit, spanning-tree AllReduce. TPU-native rebuild: the murmur hashing is
ported exactly (the reference itself reimplements VW's hash in Scala for
speed — ``VowpalWabbitMurmurWithPrefix.scala``); learning is minibatched
scatter-add SGD in XLA; the spanning-tree AllReduce becomes weight-averaging
``pmean`` over the mesh (``VowpalWabbitBase.scala:434-461``).
"""

from .murmur import murmur3_32, vw_hash, vw_feature_hash
from .featurizer import VowpalWabbitFeaturizer
from .interactions import VowpalWabbitInteractions
from .vector_zipper import VectorZipper
from .estimators import (VowpalWabbitClassifier, VowpalWabbitClassificationModel,
                         VowpalWabbitRegressor, VowpalWabbitRegressionModel)
from .contextual_bandit import (VowpalWabbitContextualBandit,
                                ContextualBanditMetrics)

__all__ = ["murmur3_32", "vw_hash", "vw_feature_hash",
           "VowpalWabbitFeaturizer", "VowpalWabbitInteractions", "VectorZipper",
           "VowpalWabbitClassifier", "VowpalWabbitClassificationModel",
           "VowpalWabbitRegressor", "VowpalWabbitRegressionModel",
           "VowpalWabbitContextualBandit", "ContextualBanditMetrics"]
