"""VowpalWabbitClassifier / VowpalWabbitRegressor pipeline stages.

Reference ``vw/VowpalWabbitBase.scala`` (param surface + training loops) and
``vw/VowpalWabbitClassifier.scala`` / ``VowpalWabbitRegressor.scala``.
The ``args`` passthrough (``VowpalWabbitBase.scala:81-86``) is parsed for
the common VW flags so existing VW command lines keep working.
"""

from __future__ import annotations

import re

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, \
    TypeConverters as TC
from ..core.contracts import (HasFeaturesCol, HasLabelCol, HasWeightCol,
                              HasProbabilityCol, HasRawPredictionCol)
from ..core.utils import stable_sigmoid
from .learner import VWConfig, VWModelState, train


class VowpalWabbitBaseParams(HasFeaturesCol, HasLabelCol, HasWeightCol):
    numBits = Param("numBits", "log2 feature space", TC.toInt, default=18)
    numPasses = Param("numPasses", "passes over the data", TC.toInt,
                      default=1)
    learningRate = Param("learningRate", "base learning rate", TC.toFloat,
                         default=0.5)
    powerT = Param("powerT", "lr decay exponent", TC.toFloat, default=0.5)
    l1 = Param("l1", "L1 regularization", TC.toFloat, default=0.0)
    l2 = Param("l2", "L2 regularization", TC.toFloat, default=0.0)
    hashSeed = Param("hashSeed", "hash seed", TC.toInt, default=0)
    adaptive = Param("adaptive", "AdaGrad-style per-weight rates (VW "
                     "--adaptive default)", TC.toBoolean, default=True)
    batchSize = Param("batchSize", "minibatch size (1 = exact online "
                      "updates)", TC.toInt, default=256)
    args = Param("args", "VW-style argument passthrough", TC.toString,
                 default="")
    initialModel = ComplexParam("initialModel", "warm-start model state",
                                default=None, has_default=True)
    numShards = Param("numShards", "device shards (0 = auto)", TC.toInt,
                      default=0)
    additionalFeatures = Param(
        "additionalFeatures", "extra sparse feature columns (their "
        "namespaces concatenate with featuresCol per row — reference "
        "VowpalWabbitBase.scala:59; interactions across namespaces come "
        "from VowpalWabbitInteractions)", TC.toListString, default=[])
    useBarrierExecutionMode = Param("useBarrierExecutionMode",
                                    "inert; SPMD is inherently barriered",
                                    TC.toBoolean, default=False)

    _ARG_MAP = {
        "-l": ("learning_rate", float), "--learning_rate": (
            "learning_rate", float),
        "--l1": ("l1", float), "--l2": ("l2", float),
        "-b": ("num_bits", int), "--bit_precision": ("num_bits", int),
        "--power_t": ("power_t", float),
        "--passes": ("num_passes", int),
        "--loss_function": ("loss_function", str),
        "--quantile_tau": ("quantile_tau", float),
        "--link": ("link", str),
    }

    def _parse_args(self) -> dict:
        """Parse the VW arg string (reference users pass raw VW command
        lines; ``VowpalWabbitBase.scala:81-86`` forwards them verbatim)."""
        out: dict = {}
        toks = self.get("args").split()
        flags = set()
        i = 0
        while i < len(toks):
            tok = toks[i]
            if tok in self._ARG_MAP and i + 1 < len(toks):
                name, conv = self._ARG_MAP[tok]
                out[name] = conv(toks[i + 1])
                i += 2
            elif tok in ("--adaptive", "--normalized", "--invariant",
                         "--holdout_off", "--quiet"):
                flags.add(tok)
                i += 1
            else:
                i += 1  # unknown args ignored, like VW's permissive CLI
        if "--adaptive" in flags:
            out["adaptive"] = True
        return out

    def _config(self, loss_default: str) -> VWConfig:
        cfg = VWConfig(
            num_bits=self.get("numBits"),
            loss_function=loss_default,
            learning_rate=self.get("learningRate"),
            power_t=self.get("powerT"),
            l1=self.get("l1"), l2=self.get("l2"),
            num_passes=self.get("numPasses"),
            adaptive=self.get("adaptive"),
            batch_size=self.get("batchSize"))
        for k, v in self._parse_args().items():
            setattr(cfg, k, v)
        return cfg

    def _one_feature_col(self, df, base):
        icol, vcol = f"{base}_indices", f"{base}_values"
        if icol in df.columns:
            return np.asarray(df[icol], np.int32), \
                np.asarray(df[vcol], np.float32)
        # dense fallback: feature j is index j (no hashing)
        dense = np.asarray(df[base], np.float32)
        n, f = dense.shape
        idx = np.broadcast_to(np.arange(f, dtype=np.int32), (n, f))
        return np.ascontiguousarray(idx), dense

    def _features(self, df):
        cols = [self.getFeaturesCol()] + list(
            self.get("additionalFeatures") or [])
        if len(cols) > 1:
            if len(set(cols)) != len(cols):
                # a duplicated namespace would scatter-add every feature
                # twice — silently doubling its weight updates
                raise ValueError(
                    f"duplicate feature columns in featuresCol + "
                    f"additionalFeatures: {cols}")
            missing = [c for c in cols
                       if f"{c}_indices" not in df.columns
                       and c not in df.columns]
            if missing:
                raise KeyError(
                    f"feature column(s) {missing} not in {df.columns}")
            # dense columns all map to indices 0..f-1 — concatenating
            # them would silently alias every column onto the same
            # weight slots; namespaces must be hashed (COO) to combine
            dense = [c for c in cols if f"{c}_indices" not in df.columns]
            if dense:
                raise ValueError(
                    f"additionalFeatures requires hashed sparse "
                    f"columns; {dense} are dense — run them through "
                    "VowpalWabbitFeaturizer first")
        parts = [self._one_feature_col(df, c) for c in cols]
        if len(parts) == 1:
            return parts[0]
        # concatenate namespaces along the per-row capacity axis
        idx = np.concatenate([p[0] for p in parts], axis=1)
        val = np.concatenate([p[1] for p in parts], axis=1)
        return idx, val

    def _mesh(self, n_rows: int):
        import jax
        from jax.sharding import Mesh
        ns = self.get("numShards")
        devices = jax.devices()
        if ns == 0:
            ns = len(devices) if n_rows >= 4096 and len(devices) > 1 else 1
        ns = min(ns, len(devices))
        if ns <= 1:
            return None
        return Mesh(np.asarray(devices[:ns]), ("dp",))


class _VWBaseEstimator(Estimator, VowpalWabbitBaseParams):
    _loss_default = "squared"

    def _prepare_labels(self, y: np.ndarray) -> np.ndarray:
        return y

    def _fit(self, df):
        idx, val = self._features(df)
        y = self._prepare_labels(
            np.asarray(df[self.getLabelCol()], np.float32))
        w = (np.asarray(df[self.getWeightCol()], np.float32)
             if self.isSet("weightCol") else None)
        cfg = self._config(self._loss_default)
        state = train(idx, val, y, w, cfg,
                      initial=self.get("initialModel"),
                      mesh=self._mesh(idx.shape[0]))
        model = self._make_model(state)
        self._copy_params_to(model)
        return model

    def fit_stream(self, batches):
        """Out-of-core online learning: each DataFrame batch continues
        from the previous batch's weights (the ``initialModel`` warm
        start VW is built around) — memory bounded by one batch."""
        state = self.get("initialModel")
        cfg = self._config(self._loss_default)
        seen = False
        for batch in batches:
            idx, val = self._features(batch)
            y = self._prepare_labels(
                np.asarray(batch[self.getLabelCol()], np.float32))
            w = (np.asarray(batch[self.getWeightCol()], np.float32)
                 if self.isSet("weightCol") else None)
            state = train(idx, val, y, w, cfg, initial=state,
                          mesh=self._mesh(idx.shape[0]))
            seen = True
        if not seen:
            raise ValueError("fit_stream received an empty batch stream")
        model = self._make_model(state)
        self._copy_params_to(model)
        model._resolve_parent(self)
        return model


class VowpalWabbitRegressionModel(Model, VowpalWabbitBaseParams):
    predictionCol = Param("predictionCol", "output column", TC.toString,
                          default="prediction")
    state = ComplexParam("state", "trained VWModelState")

    def _transform(self, df):
        idx, val = self._features(df)
        st: VWModelState = self.get("state")
        raw = st.predict_raw(idx, val)
        if st.config.link == "logistic":
            raw = stable_sigmoid(raw)
        return df.with_column(self.get("predictionCol"),
                              raw.astype(np.float32))


class VowpalWabbitRegressor(_VWBaseEstimator):
    _loss_default = "squared"

    def _make_model(self, state):
        return VowpalWabbitRegressionModel(state=state)


class VowpalWabbitClassificationModel(Model, VowpalWabbitBaseParams,
                                      HasRawPredictionCol,
                                      HasProbabilityCol):
    predictionCol = Param("predictionCol", "output column", TC.toString,
                          default="prediction")
    thresholds = Param("thresholds", "decision threshold on probability",
                       TC.toFloat, default=0.5)
    state = ComplexParam("state", "trained VWModelState")

    def _transform(self, df):
        idx, val = self._features(df)
        st: VWModelState = self.get("state")
        raw = st.predict_raw(idx, val)
        prob1 = stable_sigmoid(raw)
        probs = np.stack([1.0 - prob1, prob1], axis=1).astype(np.float32)
        pred = (prob1 >= self.get("thresholds")).astype(np.float32)
        return (df.with_column(self.getRawPredictionCol(),
                               np.stack([-raw, raw], axis=1)
                               .astype(np.float32))
                  .with_column(self.getProbabilityCol(), probs)
                  .with_column(self.get("predictionCol"), pred))


class VowpalWabbitClassifier(_VWBaseEstimator):
    """Binary classifier; labels {0,1} are mapped to VW's {-1,+1}
    (reference ``VowpalWabbitClassifier.scala`` trains with
    ``--loss_function logistic``; ``labelConversion=False`` for data
    already in {-1,+1})."""
    _loss_default = "logistic"

    labelConversion = Param(
        "labelConversion", "convert 0/1 labels to VW's -1/+1 "
        "(disable when labels already are -1/+1)", TC.toBoolean,
        default=True)

    def _prepare_labels(self, y: np.ndarray) -> np.ndarray:
        if not self.get("labelConversion"):
            bad = np.setdiff1d(np.unique(y), [-1.0, 1.0])
            if bad.size:
                raise ValueError(
                    "labelConversion=False requires -1/+1 labels; "
                    f"found {bad[:5].tolist()}")
            return np.asarray(y, np.float32)
        return np.where(y > 0, 1.0, -1.0).astype(np.float32)

    def _make_model(self, state):
        return VowpalWabbitClassificationModel(state=state)
