"""VowpalWabbitFeaturizer: hash columns into a sparse feature vector.

Reference ``vw/VowpalWabbitFeaturizer.scala`` + ``vw/featurizer/*`` (11
per-type featurizers: Numeric/String/Map/Seq/Vector/Boolean/StringSplit).
Output is the framework's padded-COO sparse convention: two fixed-width
2-D columns ``<out>_indices`` (int32, -1 padded) and ``<out>_values``
(float32, 0 padded) — the TPU-friendly encoding of VW's 2^numBits sparse
vectors (fixed shapes, scatter/segment-sum ready).
"""

from __future__ import annotations

import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCols, HasOutputCol
from .murmur import namespace_hash, vw_feature_hash, vw_hash, murmur3_32

_M32 = 0xFFFFFFFF


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    numBits = Param("numBits", "log2 of feature space size", TC.toInt,
                    default=18)
    sumCollisions = Param("sumCollisions", "sum values on hash collision",
                          TC.toBoolean, default=True)
    hashSeed = Param("hashSeed", "murmur seed", TC.toInt, default=0)
    stringSplitInputCols = Param(
        "stringSplitInputCols",
        "string columns split on whitespace into word features",
        TC.toListString, default=[], has_default=True)
    maxFeatures = Param("maxFeatures",
                        "fixed nnz capacity per row (padding width); 0 = "
                        "auto from data", TC.toInt, default=0,
                        has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="features")

    # ------------------------------------------------------------------
    def _row_features(self, colname: str, value, ns_hash: int,
                      num_bits: int, split: bool):
        """(indices, values) contributed by one cell — dispatch on type,
        mirroring the reference's per-type featurizers."""
        out_i, out_v = [], []
        if value is None:
            return out_i, out_v
        if isinstance(value, (bool, np.bool_)):
            # BooleanFeaturizer: presence feature when true
            if value:
                out_i.append(vw_feature_hash(colname, ns_hash, num_bits))
                out_v.append(1.0)
        elif isinstance(value, (int, float, np.integer, np.floating)):
            # NumericFeaturizer: index from column name, weight = value
            if float(value) != 0.0:
                out_i.append(vw_feature_hash(colname, ns_hash, num_bits))
                out_v.append(float(value))
        elif isinstance(value, str):
            if split:
                # StringSplitFeaturizer: each token a unit feature
                for tok in value.split():
                    out_i.append(vw_feature_hash(
                        colname + tok, ns_hash, num_bits))
                    out_v.append(1.0)
            else:
                # StringFeaturizer: categorical "col=value" unit feature
                out_i.append(vw_feature_hash(
                    colname + value, ns_hash, num_bits))
                out_v.append(1.0)
        elif isinstance(value, dict):
            # MapFeaturizer: key → "col+key", weight = mapped value
            for k, v in value.items():
                if float(v) != 0.0:
                    out_i.append(vw_feature_hash(
                        colname + str(k), ns_hash, num_bits))
                    out_v.append(float(v))
        elif isinstance(value, (list, tuple, np.ndarray)):
            arr = np.asarray(value)
            if arr.dtype.kind in "OUS":
                # SeqFeaturizer of strings
                for s in arr:
                    out_i.append(vw_feature_hash(
                        colname + str(s), ns_hash, num_bits))
                    out_v.append(1.0)
            else:
                # VectorFeaturizer: dense vector, index = hash(col) + slot
                base = vw_feature_hash(colname, ns_hash, num_bits)
                mask = (1 << num_bits) - 1
                for slot, v in enumerate(arr.ravel()):
                    if float(v) != 0.0:
                        out_i.append((base + slot) & mask)
                        out_v.append(float(v))
        else:
            raise TypeError(
                f"unsupported feature type {type(value).__name__} in "
                f"column {colname!r}")
        return out_i, out_v

    def _transform(self, df):
        cols = self.getInputCols()
        num_bits = self.get("numBits")
        seed = self.get("hashSeed")
        split_cols = set(self.get("stringSplitInputCols"))
        ns_hash = seed  # default (empty) namespace, VW semantics
        sum_collisions = self.get("sumCollisions")

        n = len(df)
        all_i: list[list[int]] = []
        all_v: list[list[float]] = []
        col_data = {c: df[c] for c in list(cols) + list(split_cols - set(cols))}
        for r in range(n):
            row_i: list[int] = []
            row_v: list[float] = []
            for c, data in col_data.items():
                i, v = self._row_features(c, data[r], ns_hash, num_bits,
                                          c in split_cols)
                row_i += i
                row_v += v
            if sum_collisions and len(set(row_i)) != len(row_i):
                agg: dict[int, float] = {}
                for i, v in zip(row_i, row_v):
                    agg[i] = agg.get(i, 0.0) + v
                row_i, row_v = list(agg), list(agg.values())
            all_i.append(row_i)
            all_v.append(row_v)

        width = self.get("maxFeatures") or max(
            (len(r) for r in all_i), default=1) or 1
        indices = np.full((n, width), -1, np.int32)
        values = np.zeros((n, width), np.float32)
        for r, (ri, rv) in enumerate(zip(all_i, all_v)):
            k = min(len(ri), width)
            indices[r, :k] = ri[:k]
            values[r, :k] = rv[:k]
        out = self.getOutputCol()
        return (df.with_column(f"{out}_indices", indices)
                  .with_column(f"{out}_values", values))
