"""VowpalWabbitFeaturizer: hash columns into a sparse feature vector.

Reference ``vw/VowpalWabbitFeaturizer.scala`` + ``vw/featurizer/*`` (11
per-type featurizers: Numeric/String/Map/Seq/Vector/Boolean/StringSplit).
Output is the framework's padded-COO sparse convention: two fixed-width
2-D columns ``<out>_indices`` (int32, -1 padded) and ``<out>_values``
(float32, 0 padded) — the TPU-friendly encoding of VW's 2^numBits sparse
vectors (fixed shapes, scatter/segment-sum ready).
"""

from __future__ import annotations

import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCols, HasOutputCol
from .murmur import namespace_hash, vw_feature_hash, vw_hash, murmur3_32

_M32 = 0xFFFFFFFF


def _row_positions(rows: np.ndarray, n: int):
    """Per-entry position within its (sorted) row: counts, and
    arange - exclusive-cumsum-starts gathered by row."""
    counts = np.bincount(rows, minlength=n) if rows.size else \
        np.zeros(n, np.int64)
    starts = np.zeros(n, np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    pos = np.arange(rows.size, dtype=np.int64) - starts[rows]
    return counts, pos


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    numBits = Param("numBits", "log2 of feature space size", TC.toInt,
                    default=18)
    sumCollisions = Param("sumCollisions", "sum values on hash collision",
                          TC.toBoolean, default=True)
    hashSeed = Param("hashSeed", "murmur seed", TC.toInt, default=0)
    stringSplitInputCols = Param(
        "stringSplitInputCols",
        "string columns split on whitespace into word features",
        TC.toListString, default=[], has_default=True)
    maxFeatures = Param("maxFeatures",
                        "fixed nnz capacity per row (padding width); 0 = "
                        "auto from data", TC.toInt, default=0,
                        has_default=True)
    prefixStringsWithColumnName = Param(
        "prefixStringsWithColumnName",
        "prefix hashed feature names with the column name (reference "
        "default; disabling matches raw-VW lines where only the value "
        "is hashed)", TC.toBoolean, default=True)
    preserveOrderNumBits = Param(
        "preserveOrderNumBits",
        "reserve the top bits of each index for the feature's position "
        "in its row (reference transform: index |= pos << "
        "(30 - preserveOrderNumBits); numBits + this must be <= 30)",
        TC.toInt, default=0)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="features")

    # ------------------------------------------------------------------
    def _row_features(self, colname: str, value, ns_hash: int,
                      num_bits: int, split: bool,
                      prefix: str | None = None):
        """(indices, values) contributed by one cell — dispatch on type,
        mirroring the reference's per-type featurizers. ``prefix`` is the
        reference's prefixName (empty when
        prefixStringsWithColumnName=False); sequences of strings never
        use it (VowpalWabbitFeaturizer.scala:81-82)."""
        if prefix is None:
            prefix = colname
        out_i, out_v = [], []
        if value is None:
            return out_i, out_v
        if isinstance(value, (bool, np.bool_)):
            # BooleanFeaturizer: presence feature when true
            if value:
                out_i.append(vw_feature_hash(prefix, ns_hash, num_bits))
                out_v.append(1.0)
        elif isinstance(value, (int, float, np.integer, np.floating)):
            # NumericFeaturizer: index from prefixName, weight = value
            if float(value) != 0.0:
                out_i.append(vw_feature_hash(prefix, ns_hash, num_bits))
                out_v.append(float(value))
        elif isinstance(value, str):
            if split:
                # StringSplitFeaturizer: each token a unit feature
                for tok in value.split():
                    out_i.append(vw_feature_hash(
                        prefix + tok, ns_hash, num_bits))
                    out_v.append(1.0)
            else:
                # StringFeaturizer: categorical "col=value" unit feature
                out_i.append(vw_feature_hash(
                    prefix + value, ns_hash, num_bits))
                out_v.append(1.0)
        elif isinstance(value, dict):
            # MapFeaturizer: key → "col+key", weight = mapped value
            for k, v in value.items():
                if float(v) != 0.0:
                    out_i.append(vw_feature_hash(
                        prefix + str(k), ns_hash, num_bits))
                    out_v.append(float(v))
        elif isinstance(value, (list, tuple, np.ndarray)):
            arr = np.asarray(value)
            if arr.dtype.kind in "OUS":
                # SeqFeaturizer of strings: NEVER prefixed (reference
                # VowpalWabbitFeaturizer.scala:81-82)
                for s in arr:
                    out_i.append(vw_feature_hash(
                        str(s), ns_hash, num_bits))
                    out_v.append(1.0)
            else:
                # VectorFeaturizer: dense vector, index = hash(name) + slot
                base = vw_feature_hash(prefix, ns_hash, num_bits)
                mask = (1 << num_bits) - 1
                for slot, v in enumerate(arr.ravel()):
                    if float(v) != 0.0:
                        out_i.append((base + slot) & mask)
                        out_v.append(float(v))
        else:
            raise TypeError(
                f"unsupported feature type {type(value).__name__} in "
                f"column {colname!r}")
        return out_i, out_v

    # ---------------------------------------------------- columnar fast paths
    def _string_coo(self, colname: str, arr, ns_hash: int, num_bits: int,
                    split: bool):
        """All-string column → COO triples; batch-hashed in C++
        (``native/src/vwhash.cpp``, the reference's Scala-native murmur
        hot loop) with a Python fallback.

        Semantics match ``_row_features`` exactly: None → no feature;
        "" → the ``colname`` categorical feature; split tokenization is
        Python's Unicode ``str.split()`` (done host-side — the C++ side
        only hashes, so native and fallback are bit-identical).
        """
        import ctypes

        from ..native.loader import get_vwhash
        valid_rows = np.asarray([i for i, x in enumerate(arr)
                                 if x is not None], np.int64)
        if split:
            # pre-tokenize with Python's Unicode split; tokens contain no
            # whitespace afterwards, so the ASCII-space re-split in C++
            # reproduces the exact token list
            cells = [" ".join(str(arr[i]).split()) for i in valid_rows]
        else:
            cells = [str(arr[i]) for i in valid_rows]
        m = len(cells)
        lib = get_vwhash()
        if lib is None:
            rows, idxs, vals = [], [], []
            for r, t in zip(valid_rows, cells):
                toks = t.split() if split else [t]
                for tok in toks:
                    rows.append(r)
                    idxs.append(vw_feature_hash(colname + tok, ns_hash,
                                                num_bits))
                    vals.append(1.0)
            return (np.asarray(rows, np.int64), np.asarray(idxs, np.int32),
                    np.asarray(vals, np.float32))
        blobs = [t.encode("utf-8") for t in cells]
        offsets = np.zeros(m + 1, np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        buf = b"".join(blobs)
        # CSR output: per-row capacity = its own token count, so memory
        # is O(total tokens) even when one document is huge
        if split:
            caps = np.asarray([0 if not t else t.count(" ") + 1
                               for t in cells], np.int64)
        else:
            caps = np.ones(m, np.int64)
        out_offsets = np.zeros(m + 1, np.int64)
        np.cumsum(caps, out=out_offsets[1:])
        total = int(out_offsets[-1])
        out_idx = np.full(total, -1, np.int32)
        out_val = np.zeros(total, np.float32)
        out_n = np.zeros(m, np.int32)
        lib.vw_hash_strings(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            m, colname.encode("utf-8"), len(colname.encode("utf-8")),
            ns_hash, num_bits, 1 if split else 0,
            out_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            # in-kernel premerge must not run when order bits are
            # active: positions are assigned AFTER this call, and the
            # reference merges only identical (index|pos) keys
            1 if (self.get("sumCollisions")
                  and not self.get("preserveOrderNumBits")) else 0,
            out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out_n.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        rows = np.repeat(valid_rows, out_n)
        # positions of the filled prefix of each row's CSR slice
        ends = np.cumsum(out_n.astype(np.int64))
        pick = (np.arange(int(ends[-1]) if out_n.size else 0,
                          dtype=np.int64)
                - np.repeat(ends - out_n, out_n)
                + np.repeat(out_offsets[:-1], out_n))
        return rows, out_idx[pick], out_val[pick]

    def _column_coo(self, colname: str, data, n: int, ns_hash: int,
                    num_bits: int, split: bool,
                    prefix: str | None = None):
        """One column → (rows, indices, values) COO triples, vectorized
        per dtype; exotic cell types fall back to the per-row
        dispatcher."""
        if prefix is None:
            prefix = colname
        arr = np.asarray(data)
        mask = (1 << num_bits) - 1
        if arr.ndim == 1 and arr.dtype.kind == "b":
            base = vw_feature_hash(prefix, ns_hash, num_bits)
            nz = np.flatnonzero(arr)
            return (nz.astype(np.int64),
                    np.full(nz.size, base, np.int32),
                    np.ones(nz.size, np.float32))
        if arr.ndim == 1 and arr.dtype.kind in "fiu":
            base = vw_feature_hash(prefix, ns_hash, num_bits)
            v = arr.astype(np.float32)
            nz = np.flatnonzero(v != 0.0)
            return (nz.astype(np.int64),
                    np.full(nz.size, base, np.int32), v[nz])
        if arr.ndim == 2 and arr.dtype.kind in "fiu":
            # VectorFeaturizer: index = hash(name) + slot
            base = vw_feature_hash(prefix, ns_hash, num_bits)
            slot_idx = ((base + np.arange(arr.shape[1], dtype=np.int64))
                        & mask).astype(np.int32)
            v = arr.astype(np.float32)
            r, cpos = np.nonzero(v)
            return r.astype(np.int64), slot_idx[cpos], v[r, cpos]
        if arr.dtype == object and all(
                x is None or isinstance(x, str) for x in arr):
            return self._string_coo(prefix, arr, ns_hash,
                                    num_bits, split)
        # mixed/object cells (dicts, sequences): per-row dispatch
        rows: list[int] = []
        idxs: list[int] = []
        vals: list[float] = []
        for r in range(n):
            i, v = self._row_features(colname, data[r], ns_hash,
                                      num_bits, split,
                                      prefix=prefix)
            rows.extend([r] * len(i))
            idxs.extend(i)
            vals.extend(v)
        return (np.asarray(rows, np.int64), np.asarray(idxs, np.int32),
                np.asarray(vals, np.float32))

    def _transform(self, df):
        cols = self.getInputCols()
        num_bits = self.get("numBits")
        seed = self.get("hashSeed")
        split_cols = set(self.get("stringSplitInputCols"))
        # reference: namespaceHash = murmur(outputCol, seed)
        # (VowpalWabbitFeaturizer.scala transform) — bit-parity of the
        # hashed indices with the reference requires the same namespace
        ns_hash = murmur3_32(self.getOutputCol().encode("utf-8"), seed)
        sum_collisions = self.get("sumCollisions")
        order_bits = self.get("preserveOrderNumBits")
        if order_bits and order_bits + num_bits > 30:
            raise ValueError(
                f"numBits ({num_bits}) + preserveOrderNumBits "
                f"({order_bits}) must be <= 30 (reference validation)")

        n = len(df)
        col_data = {c: df[c] for c in list(cols) + list(split_cols - set(cols))}
        # prefixStringsWithColumnName=False passes an empty prefix to
        # EVERY featurizer type, exactly like the reference
        # (getFeaturizer's prefixName) — note that with the shared
        # output-column namespace this merges same-typed numeric columns
        # onto one index, also like the reference
        use_prefix = self.get("prefixStringsWithColumnName")
        triples = [self._column_coo(c, data, n, ns_hash, num_bits,
                                    c in split_cols,
                                    prefix=None if use_prefix else "")
                   for c, data in col_data.items()]
        rows = np.concatenate([t[0] for t in triples]) if triples else \
            np.zeros(0, np.int64)
        idx = np.concatenate([t[1] for t in triples]) if triples else \
            np.zeros(0, np.int32)
        val = np.concatenate([t[2] for t in triples]) if triples else \
            np.zeros(0, np.float32)

        if order_bits and rows.size:
            # reference order preservation: stable-sort by row, then OR
            # each feature's row-position into the high bits — collisions
            # at different positions stay distinct, and sorting by the
            # combined index reproduces input order
            order0 = np.argsort(rows, kind="stable")
            rows, idx, val = rows[order0], idx[order0], val[order0]
            counts0, pos0 = _row_positions(rows, n)
            if counts0.max(initial=0) > (1 << order_bits):
                raise ValueError(
                    f"a row has {int(counts0.max())} features — too many "
                    f"for preserveOrderNumBits={order_bits} "
                    f"(max {1 << order_bits}, reference validation)")
            idx = (idx.astype(np.int64)
                   | (pos0 << (30 - order_bits))).astype(np.int32)

        if sum_collisions and rows.size:
            # merge duplicate (row, index) pairs, float64 accumulation
            key = (rows << 32) | idx.astype(np.int64)
            uniq, first, inv = np.unique(key, return_index=True,
                                         return_inverse=True)
            sums = np.zeros(uniq.size, np.float64)
            np.add.at(sums, inv, val.astype(np.float64))
            rows_u = (uniq >> 32).astype(np.int64)
            # within each row, keep FIRST-SEEN (input-column) order so
            # maxFeatures truncation keeps the same features it always did
            order = np.lexsort((first, rows_u))
            rows = rows_u[order]
            idx = (uniq & 0xFFFFFFFF).astype(np.int32)[order]
            val = sums.astype(np.float32)[order]
        else:
            order = np.argsort(rows, kind="stable")
            rows, idx, val = rows[order], idx[order], val[order]

        counts, pos = _row_positions(rows, n)
        width = self.get("maxFeatures") or max(int(counts.max(initial=0)),
                                               1)
        keep = pos < width
        indices = np.full((n, width), -1, np.int32)
        values = np.zeros((n, width), np.float32)
        indices[rows[keep], pos[keep]] = idx[keep]
        values[rows[keep], pos[keep]] = val[keep]
        out = self.getOutputCol()
        return (df.with_column(f"{out}_indices", indices)
                  .with_column(f"{out}_values", values))
