"""Contextual bandit learner + off-policy evaluation metrics.

Reference ``vw/VowpalWabbitContextualBandit.scala:106-309``: CB with
action-dependent features (one example per action, stacked per decision),
trained from logged (chosen action, cost, probability) triples via
importance weighting; ``ContextualBanditMetrics`` (:54-104) implements
IPS/SNIPS off-policy estimators.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, \
    TypeConverters as TC
from ..core.contracts import HasFeaturesCol
from .learner import VWConfig, VWModelState, train


@dataclasses.dataclass
class ContextualBanditMetrics:
    """IPS / SNIPS estimators (reference ``:54-104``). Lower cost is
    better, as in VW's CB convention."""
    total_events: int = 0
    weighted_cost: float = 0.0        # sum cost_i / p_i  (IPS numerator)
    importance_sum: float = 0.0       # sum 1 / p_i       (SNIPS denominator)

    def add_example(self, prob_logged: float, cost: float,
                    prob_pred: float = 1.0):
        """prob_pred: probability the evaluated policy picks the logged
        action (1.0 when it deterministically matches, 0 otherwise)."""
        self.total_events += 1
        iw = prob_pred / max(prob_logged, 1e-12)
        self.weighted_cost += cost * iw
        self.importance_sum += iw

    @property
    def ips(self) -> float:
        return self.weighted_cost / max(self.total_events, 1)

    @property
    def snips(self) -> float:
        return self.weighted_cost / max(self.importance_sum, 1e-12)


class VowpalWabbitContextualBandit(Estimator, HasFeaturesCol):
    """Train a per-action cost regressor from logged bandit data.

    Expected columns: shared+action features as padded COO
    (``<featuresCol>_indices/_values`` — one row per (decision, action),
    flattened), ``chosenActionCol`` (1-based, reference parity),
    ``probabilityCol`` (logging policy), ``labelCol`` (cost), and
    ``actionCol`` (this row's action id).
    """

    labelCol = Param("labelCol", "cost column", TC.toString, default="cost")
    chosenActionCol = Param("chosenActionCol", "chosen action (1-based)",
                            TC.toString, default="chosenAction")
    probabilityCol = Param("probabilityCol", "logging-policy probability",
                           TC.toString, default="probability")
    actionCol = Param("actionCol", "action id of this row (1-based)",
                      TC.toString, default="action")
    numBits = Param("numBits", "log2 feature space", TC.toInt, default=18)
    numPasses = Param("numPasses", "training passes", TC.toInt, default=1)
    learningRate = Param("learningRate", "learning rate", TC.toFloat,
                         default=0.5)
    batchSize = Param("batchSize", "minibatch size", TC.toInt, default=256)
    epsilon = Param("epsilon", "exploration rate of the epsilon-greedy "
                    "policy (reference setEpsilon, "
                    "VowpalWabbitContextualBandit.scala:134-139)",
                    TC.toFloat, default=0.05)

    def _fit(self, df):
        base = self.getFeaturesCol()
        idx = np.asarray(df[f"{base}_indices"], np.int32)
        val = np.asarray(df[f"{base}_values"], np.float32)
        action = np.asarray(df[self.get("actionCol")], np.int64)
        chosen = np.asarray(df[self.get("chosenActionCol")], np.int64)
        prob = np.asarray(df[self.get("probabilityCol")], np.float64)
        cost = np.asarray(df[self.get("labelCol")], np.float32)

        # IPS-weighted cost regression on the chosen rows (VW's cb_adf
        # reduction to regression: weight = 1/p for the observed action)
        mask = action == chosen
        ex_w = np.where(mask, 1.0 / np.clip(prob, 1e-12, None), 0.0) \
            .astype(np.float32)
        cfg = VWConfig(num_bits=self.get("numBits"),
                       loss_function="squared",
                       learning_rate=self.get("learningRate"),
                       num_passes=self.get("numPasses"),
                       batch_size=self.get("batchSize"))
        state = train(idx, val, cost, ex_w, cfg)
        model = VowpalWabbitContextualBanditModel(state=state)
        self._copy_params_to(model)
        return model


class VowpalWabbitContextualBanditModel(Model, HasFeaturesCol):
    state = ComplexParam("state", "trained VWModelState")
    predictionCol = Param("predictionCol", "predicted cost column",
                          TC.toString, default="prediction")
    actionCol = Param("actionCol", "action id of this row (1-based)",
                      TC.toString, default="action")

    def _transform(self, df):
        base = self.getFeaturesCol()
        st: VWModelState = self.get("state")
        raw = st.predict_raw(np.asarray(df[f"{base}_indices"], np.int32),
                             np.asarray(df[f"{base}_values"], np.float32))
        return df.with_column(self.get("predictionCol"),
                              raw.astype(np.float32))

    epsilon = Param("epsilon", "exploration rate of the epsilon-greedy "
                    "policy (copied from the estimator at fit)",
                    TC.toFloat, default=0.05)

    def best_actions(self, df, group_col: str = "decision") -> np.ndarray:
        """argmin predicted cost per decision group."""
        out = self.transform(df)
        groups = np.asarray(out[group_col])
        preds = out[self.get("predictionCol")]
        actions = np.asarray(out[self.get("actionCol")])
        best = {}
        for g, p, a in zip(groups, preds, actions):
            if g not in best or p < best[g][0]:
                best[g] = (p, a)
        return np.asarray([best[g][1] for g in
                           sorted(best, key=lambda x: str(x))])

    def action_probabilities(self, df,
                             group_col: str = "decision") -> "object":
        """Epsilon-greedy policy distribution (VW ``--cb_explore_adf
        --epsilon``): per decision, the argmin-cost action gets
        1 - ε + ε/K and every other action ε/K — the probabilities
        logged for the next round of off-policy training. Returns the
        scored DataFrame with a ``policyProbability`` column."""
        out = self.transform(df)
        groups = np.asarray(out[group_col])
        preds = np.asarray(out[self.get("predictionCol")])
        eps = self.get("epsilon")
        # one pass: group ids → inverse, grouped first-wins argmin via a
        # stable lexsort (no per-group rescan of the full array)
        _, inv = np.unique(groups, return_inverse=True)
        k_per = np.bincount(inv)
        order = np.lexsort((preds, inv))
        starts = np.r_[0, np.cumsum(k_per)[:-1]]
        greedy_rows = order[starts]
        probs = eps / k_per[inv].astype(np.float64)
        probs[greedy_rows] += 1.0 - eps
        return out.with_column("policyProbability", probs)
