"""VectorZipper — combine columns into one sequence column.

Reference ``vw/VectorZipper.scala``: zips one or more input columns into
an array column, the shape the contextual-bandit action-dependent-feature
pipelines feed (one sequence of per-action payloads per decision)."""

from __future__ import annotations

import numpy as np

from ..core import Transformer
from ..core.contracts import HasInputCols, HasOutputCol


class VectorZipper(Transformer, HasInputCols, HasOutputCol):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="zipped")

    def _transform(self, df):
        cols = [df[c] for c in self.getInputCols()]
        if not cols:
            raise ValueError("VectorZipper needs at least one inputCol")
        out = np.empty(len(df), object)
        out[:] = [[col[i] for col in cols] for i in range(len(df))]
        return df.with_column(self.getOutputCol(), out)
