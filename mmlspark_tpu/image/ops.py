"""Batched image kernels (jnp). All take/return float32 NHWC arrays.

The reference shells out to native OpenCV per row
(``opencv/ImageTransformer.scala:27-436``). On TPU the same operators are
whole-batch XLA programs: resize is a gather/matmul, blur a depthwise conv —
all fusable, all MXU/VPU work, no host round-trips.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def resize(images: jnp.ndarray, height: int, width: int,
           method: str = "linear") -> jnp.ndarray:
    """Reference ``ResizeImage`` stage (ImageTransformer.scala:42-73)."""
    B, _, _, C = images.shape
    return jax.image.resize(images, (B, height, width, C), method=method)


def crop(images: jnp.ndarray, x: int, y: int, height: int,
         width: int) -> jnp.ndarray:
    """Reference ``CropImage`` (ImageTransformer.scala:75-100): (x, y) is
    the top-left corner, x = column offset, y = row offset."""
    return images[:, y:y + height, x:x + width, :]


def center_crop(images: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    _, H, W, _ = images.shape
    y = max((H - height) // 2, 0)
    x = max((W - width) // 2, 0)
    return images[:, y:y + height, x:x + width, :]


def flip(images: jnp.ndarray, flip_code: int = 1) -> jnp.ndarray:
    """Reference ``Flip`` (ImageTransformer.scala:122-146); OpenCV codes:
    1 = horizontal (around y-axis), 0 = vertical, -1 = both."""
    if flip_code == 1:
        return images[:, :, ::-1, :]
    if flip_code == 0:
        return images[:, ::-1, :, :]
    return images[:, ::-1, ::-1, :]


def color_format(images: jnp.ndarray, conversion: str) -> jnp.ndarray:
    """Reference ``ColorFormat`` (ImageTransformer.scala:102-120). Images
    are BGR-ordered (Spark ImageSchema convention, kept for parity)."""
    if conversion in ("bgr2gray", "gray"):
        b, g, r = images[..., 0], images[..., 1], images[..., 2]
        # OpenCV luma weights
        gray = 0.114 * b + 0.587 * g + 0.299 * r
        return gray[..., None]
    if conversion == "bgr2rgb":
        return images[..., ::-1]
    raise ValueError(f"unsupported conversion {conversion!r}")


def _gaussian_kernel_1d(size: int, sigma: float) -> np.ndarray:
    # OpenCV: sigma<=0 → computed from kernel size
    if sigma <= 0:
        sigma = 0.3 * ((size - 1) * 0.5 - 1) + 0.8
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2
    k = np.exp(-x ** 2 / (2 * sigma ** 2))
    return (k / k.sum()).astype(np.float32)


def _depthwise_sep_conv(images: jnp.ndarray, kx: np.ndarray,
                        ky: np.ndarray) -> jnp.ndarray:
    """Separable depthwise convolution: 1-D kernels along W then H,
    SAME/edge-replicate padding like OpenCV BORDER_DEFAULT-ish."""
    C = images.shape[-1]
    px, py = len(kx) // 2, len(ky) // 2
    padded = jnp.pad(images, ((0, 0), (py, py), (px, px), (0, 0)),
                     mode="edge")
    wx = jnp.asarray(kx).reshape(1, len(kx), 1, 1)
    wx = jnp.tile(wx, (1, 1, 1, C))
    out = jax.lax.conv_general_dilated(
        padded, wx, (1, 1), "VALID", feature_group_count=C,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    wy = jnp.asarray(ky).reshape(len(ky), 1, 1, 1)
    wy = jnp.tile(wy, (1, 1, 1, C))
    return jax.lax.conv_general_dilated(
        out, wy, (1, 1), "VALID", feature_group_count=C,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def blur(images: jnp.ndarray, height: float, width: float) -> jnp.ndarray:
    """Reference ``Blur`` (ImageTransformer.scala:148-170): normalized box
    filter of size (width, height)."""
    kh, kw = int(height), int(width)
    kx = np.full(kw, 1.0 / kw, np.float32)
    ky = np.full(kh, 1.0 / kh, np.float32)
    return _depthwise_sep_conv(images, kx, ky)


def gaussian_blur(images: jnp.ndarray, aperture_size: int,
                  sigma: float) -> jnp.ndarray:
    """Reference ``GaussianKernel`` (ImageTransformer.scala:199-221)."""
    k = _gaussian_kernel_1d(aperture_size, sigma)
    return _depthwise_sep_conv(images, k, k)


def threshold(images: jnp.ndarray, thresh: float, max_val: float,
              threshold_type: str = "binary") -> jnp.ndarray:
    """Reference ``Threshold`` (ImageTransformer.scala:172-197); OpenCV
    threshold types."""
    t = {"binary": lambda x: jnp.where(x > thresh, max_val, 0.0),
         "binary_inv": lambda x: jnp.where(x > thresh, 0.0, max_val),
         "trunc": lambda x: jnp.minimum(x, thresh),
         "tozero": lambda x: jnp.where(x > thresh, x, 0.0),
         "tozero_inv": lambda x: jnp.where(x > thresh, 0.0, x)}
    if threshold_type not in t:
        raise ValueError(f"unknown threshold type {threshold_type!r}")
    return t[threshold_type](images)
