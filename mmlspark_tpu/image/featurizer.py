"""ImageFeaturizer — transfer-learning feature extraction.

Reference ``image/ImageFeaturizer.scala:40-197``: compose
ResizeImageTransformer + UnrollImage + CNTKModel, with ``cutOutputLayers``
selecting how many layers to cut off the pretrained net (1 = the
penultimate features). Here layers are named endpoints of the zoo model:
``cutOutputLayers=k`` picks ``layer_names[-(k+1)]`` (0 = logits,
1 = pooled features, 2 = stage4, ...).
"""

from __future__ import annotations

import numpy as np

from ..core import ComplexParam, Model, Param, Transformer, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from ..dl.model import TPUModel
from ..models.zoo import LoadedModel, ModelDownloader
from .stages import ResizeImageTransformer


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    modelName = Param("modelName", "zoo model name", TC.toString,
                      default="ResNet50", has_default=True)
    model = ComplexParam("model", "explicit LoadedModel (overrides name)",
                         default=None, has_default=True)
    cutOutputLayers = Param(
        "cutOutputLayers",
        "layers to cut from the top: 0 = logits, 1 = pooled features",
        TC.toInt, default=1, has_default=True)
    autoResize = Param("autoResize", "resize inputs to the model's input "
                       "size first", TC.toBoolean, default=True,
                       has_default=True)
    miniBatchSize = Param("miniBatchSize", "device batch size", TC.toInt,
                          default=64, has_default=True)
    transferDtype = Param(
        "transferDtype", "host->device wire dtype (see TPUModel); "
        "'auto' additionally narrows float inputs to bfloat16 here when "
        "the zoo model computes in bf16 (its first op is the cast, so "
        "the wire narrowing is lossless)", TC.toString, default="auto",
        has_default=True)
    pipelineDepth = Param(
        "pipelineDepth", "max in-flight device batches (see TPUModel)",
        TC.toInt, default=2, has_default=True)
    quantize = Param(
        "quantize", "score through the int8 post-training-quantized "
        "path (models.quantize_resnet: BN folded, per-channel int8 "
        "weights, dynamic int8 activations — 2x MXU rate on v5e); "
        "pooled endpoint (cutOutputLayers=1) only",
        TC.toBoolean, default=False, has_default=True)

    # class-level fallbacks: the serializer reconstructs without __init__
    _tpu_model = None
    _loaded_cache = None
    _quant_cache = None

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="features")
        self._tpu_model = None
        self._loaded_cache = None
        self._quant_cache = None

    def setModel(self, name_or_model):
        """Accepts a zoo name or a LoadedModel (reference
        ``setModel(ModelSchema)``, ``ImageFeaturizer.scala:81-85``)."""
        if isinstance(name_or_model, str):
            return self.set("modelName", name_or_model)
        return self.set("model", name_or_model)

    def _loaded(self) -> LoadedModel:
        m = self.get("model")
        if m is not None:
            return m
        # cache the zoo resolution per (name, model dir): a fresh
        # LoadedModel per transform would defeat the TPUModel jit cache
        # (new identity → retrace) and re-restore weights every call
        import os
        key = (self.get("modelName"),
               os.environ.get("MMLSPARK_TPU_MODEL_DIR", ""))
        if self._loaded_cache is None or self._loaded_cache[0] != key:
            self._loaded_cache = (
                key, ModelDownloader().download_by_name(key[0]))
        return self._loaded_cache[1]

    def _transform(self, df):
        loaded = self._loaded()
        layers = loaded.layer_names
        cut = self.get("cutOutputLayers")
        if not 0 <= cut < len(layers):
            raise ValueError(
                f"cutOutputLayers={cut} out of range for {layers}")
        endpoint = layers[-(cut + 1)]
        # resolve the wire dtype from the SOURCE module before any
        # quantize substitution: the int8 shim has no dtype attr, and
        # losing the bf16 wire narrowing would double host->device
        # bytes on exactly the tunnel-dominated path int8 accelerates
        wire = self.get("transferDtype")
        if wire == "auto" and getattr(loaded.module, "dtype", None) is not \
                None:
            import jax.numpy as jnp
            if loaded.module.dtype == jnp.bfloat16:
                wire = "bfloat16"
        if self.get("quantize"):
            from ..models.resnet import ResNet
            if not isinstance(loaded.module, ResNet):
                raise ValueError(
                    "quantize=True supports ResNet zoo models only "
                    f"(got {type(loaded.module).__name__}); the text "
                    "path is models.quantize_text_encoder")
            if endpoint != "pooled":
                raise ValueError(
                    "quantize=True scores the pooled endpoint only "
                    f"(cutOutputLayers=1); requested {endpoint!r}")
            loaded = self._quantized(loaded)

        col = self.getInputCol()
        if self.get("autoResize"):
            size = loaded.schema.input_size
            df = ResizeImageTransformer(
                inputCol=col, outputCol=col, height=size,
                width=size).transform(df)
        # reuse ONE TPUModel across transforms (its jitted apply is
        # cached per model identity — a fresh instance per call would
        # retrace and recompile every time)
        key = (id(loaded), endpoint, col, self.getOutputCol(),
               self.get("miniBatchSize"), wire)
        if self._tpu_model is None or self._tpu_model[0] != key:
            self._tpu_model = (key, TPUModel(
                model=loaded, inputCol=col,
                outputCol=self.getOutputCol(), outputNode=endpoint,
                minibatchSize=self.get("miniBatchSize"),
                transferDtype=wire))
        # depth rides OUTSIDE the cache key: it only shapes the host
        # loop, so tuning it must not retrace the compiled model
        self._tpu_model[1].set("pipelineDepth",
                               self.get("pipelineDepth"))
        return self._tpu_model[1].transform(df)

    def _quantized(self, loaded: LoadedModel) -> LoadedModel:
        """Cache the folded/int8 LoadedModel per source model: the
        shim's identity must stay stable or TPUModel retraces every
        transform."""
        if self._quant_cache is None or \
                self._quant_cache[0] is not loaded:
            from ..models.quantize import quantize_resnet
            q_forward, qparams = quantize_resnet(loaded.module,
                                                 loaded.variables)

            class _QuantShim:
                """Duck-typed module: TPUModel only calls
                ``apply(variables, batch, train)`` and reads a dict."""

                @staticmethod
                def apply(variables, batch, train=False):
                    return {"pooled": q_forward(variables["params"],
                                                batch)}

            self._quant_cache = (loaded, LoadedModel(
                schema=loaded.schema, module=_QuantShim(),
                variables={"params": qparams}))
        return self._quant_cache[1]

    @property
    def last_transform_stats(self) -> dict | None:
        """Timing breakdown of the last transform's device leg
        (``TPUModel.last_stats``): prep/dispatch/drain/total ms — the
        attribution that separates framework overhead from tunnel RTT."""
        return self._tpu_model[1].last_stats if self._tpu_model else None
