"""ImageTransformer: a pipeline of batched image operations.

Reference ``opencv/ImageTransformer.scala:27-436`` — a stage list
(``resize``, ``crop``, ``colorFormat``, ``flip``, ``blur``, ``threshold``,
``gaussianKernel``) applied per row through native OpenCV. Here the stage
list compiles into ONE jitted program applied to the whole batch; uniform
image sizes run fully batched, ragged inputs are grouped by shape so each
distinct shape compiles once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from . import ops


def images_to_batch(col: np.ndarray) -> tuple[np.ndarray, bool]:
    """Column of images → float32 NHWC batch.

    Accepts a 4-D numeric array (uniform) or an object array of HWC arrays.
    Returns (batch, was_uniform). Ragged inputs raise — callers group by
    shape first (see ImageTransformer._transform).
    """
    if isinstance(col, np.ndarray) and col.ndim == 4:
        return np.asarray(col, np.float32), True
    arrs = [np.asarray(a, np.float32) for a in col]
    shapes = {a.shape for a in arrs}
    if len(shapes) != 1:
        raise ValueError(f"ragged image shapes {shapes}")
    batch = np.stack(arrs)
    if batch.ndim == 3:  # grayscale HW → HWC
        batch = batch[..., None]
    return batch, False


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Chainable image ops, batched on device.

    >>> ImageTransformer().setInputCol("image").resize(224, 224).flip(1)
    """

    stages = Param("stages", "list of (op, kwargs) image stages",
                   TC.identity, default=[], has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="image")

    # -- fluent builders (reference ImageTransformer public API) -----------
    def _add(self, op: str, **kw):
        # stored as [op, kwargs] lists so the JSON round trip is identity
        self.set("stages", list(self.get("stages")) + [[op, kw]])
        return self

    def resize(self, height: int, width: int):
        return self._add("resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int):
        return self._add("crop", x=x, y=y, height=height, width=width)

    def colorFormat(self, format: str):
        return self._add("color_format", conversion=format)

    def flip(self, flipCode: int = 1):
        return self._add("flip", flip_code=flipCode)

    def blur(self, height: float, width: float):
        return self._add("blur", height=height, width=width)

    def threshold(self, threshold: float, maxVal: float,
                  thresholdType: str = "binary"):
        return self._add("threshold", thresh=threshold, max_val=maxVal,
                         threshold_type=thresholdType)

    def gaussianKernel(self, apertureSize: int, sigma: float):
        return self._add("gaussian_blur", aperture_size=apertureSize,
                         sigma=sigma)

    # -- execution ---------------------------------------------------------
    _OPS = {"resize": ops.resize, "crop": ops.crop, "flip": ops.flip,
            "color_format": ops.color_format, "blur": ops.blur,
            "threshold": ops.threshold, "gaussian_blur": ops.gaussian_blur}

    def _compiled(self):
        stage_list = tuple((op, tuple(sorted(kw.items())))
                           for op, kw in self.get("stages"))

        @functools.partial(jax.jit)
        def run(batch):
            x = batch
            for op, kw in stage_list:
                x = self._OPS[op](x, **dict(kw))
            return x
        return run

    def _transform(self, df):
        col = df[self.getInputCol()]
        run = self._compiled()
        if isinstance(col, np.ndarray) and col.ndim == 4:
            out = np.asarray(run(jnp.asarray(col, jnp.float32)))
            return df.with_column(self.getOutputCol(), out)
        # ragged: group rows by image shape; one compile per distinct shape
        arrs = [np.asarray(a, np.float32) for a in col]
        arrs = [a[..., None] if a.ndim == 2 else a for a in arrs]
        by_shape: dict[tuple, list[int]] = {}
        for i, a in enumerate(arrs):
            by_shape.setdefault(a.shape, []).append(i)
        results: list[np.ndarray | None] = [None] * len(arrs)
        for shape, idxs in by_shape.items():
            batch = jnp.asarray(np.stack([arrs[i] for i in idxs]))
            out = np.asarray(run(batch))
            for j, i in enumerate(idxs):
                results[i] = out[j]
        shapes = {r.shape for r in results}
        if len(shapes) == 1:
            new_col = np.stack(results)
        else:
            new_col = np.empty(len(results), object)
            new_col[:] = results
        return df.with_column(self.getOutputCol(), new_col)
