"""Image pipeline stages: resize, unroll, augment.

Reference ``image/`` package: ``ResizeImageTransformer.scala``,
``UnrollImage.scala`` (image → flat DenseVector in CHW order),
``ImageSetAugmenter.scala`` (left/right flip augmentation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import DataFrame, Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from ..core.dataframe import jittable_dtype
from . import ops
from .transforms import images_to_batch


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Reference ``image/ResizeImageTransformer.scala`` — the OpenCV-free
    resize used by ImageFeaturizer."""

    height = Param("height", "target height", TC.toInt)
    width = Param("width", "target width", TC.toInt)
    nChannels = Param("nChannels", "channel count override", TC.toInt,
                      default=None, has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="image")

    def _transform(self, df):
        col = df[self.getInputCol()]
        H, W = self.getHeight(), self.getWidth()
        if isinstance(col, np.ndarray) and col.ndim == 4:
            out = np.asarray(ops.resize(jnp.asarray(col, jnp.float32), H, W))
        else:
            imgs = []
            for a in col:
                a = np.asarray(a, np.float32)
                if a.ndim == 2:
                    a = a[..., None]
                imgs.append(np.asarray(ops.resize(
                    jnp.asarray(a)[None], H, W)[0]))
            out = np.stack(imgs)
        return df.with_column(self.getOutputCol(), out)


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image → flat feature vector in CHW order
    (reference ``image/UnrollImage.scala`` — CNTK expects channels-first;
    we keep the same layout so unrolled features are comparable).

    The unroll itself is a pure transpose+reshape, so it computes in
    jnp and carries a ``_trace`` form (ISSUE 11 straggler): a stacked
    numeric NHWC column fuses into the surrounding XLA segment. Object
    columns of per-row images still stack on host first
    (``images_to_batch``) on the eager path."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="unrolled")

    @staticmethod
    def _unroll(batch):
        """NHWC → [n, C*H*W], shared by the eager and traced paths."""
        return jnp.transpose(batch, (0, 3, 1, 2)) \
            .reshape(batch.shape[0], -1)

    def _transform(self, df):
        batch, _ = images_to_batch(df[self.getInputCol()])
        return df.with_column(self.getOutputCol(),
                              self._unroll(jnp.asarray(batch)))

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        # the traced form needs an already-stacked numeric NHWC column
        # (trailing [H, W, C]); object columns stay on the eager path,
        # where images_to_batch stacks (and grayscale-expands) on host
        return ic in schema and jittable_dtype(schema[ic][0]) \
            and len(schema[ic][1]) == 3

    def _trace(self, cols):
        out = dict(cols)
        batch = cols[self.getInputCol()].astype(jnp.float32)
        out[self.getOutputCol()] = self._unroll(batch)
        return out


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Decode encoded image bytes then unroll (reference
    ``image/UnrollImage.scala`` UnrollBinaryImage variant). Decoding uses
    torch-free pure-python PNG/JPEG via PIL if available, else raises."""

    height = Param("height", "resize height", TC.toInt, default=None,
                   has_default=True)
    width = Param("width", "resize width", TC.toInt, default=None,
                  has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="unrolled")

    def _transform(self, df):
        from ..io.binary import decode_image
        col = df[self.getInputCol()]
        imgs = [decode_image(b) for b in col]
        H, W = self.get("height"), self.get("width")
        out = []
        for a in imgs:
            a = np.asarray(a, np.float32)
            if a.ndim == 2:
                a = a[..., None]
            if H and W and a.shape[:2] != (H, W):
                a = np.asarray(ops.resize(jnp.asarray(a)[None], H, W)[0])
            out.append(np.transpose(a, (2, 0, 1)).reshape(-1))
        return df.with_column(self.getOutputCol(), np.stack(out))


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Dataset augmentation by flips (reference
    ``image/ImageSetAugmenter.scala``): emits the original rows plus one
    copy per enabled flip."""

    flipLeftRight = Param("flipLeftRight", "add L/R flipped copies",
                          TC.toBoolean, default=True, has_default=True)
    flipUpDown = Param("flipUpDown", "add U/D flipped copies",
                       TC.toBoolean, default=False, has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="image", outputCol="image")

    def _transform(self, df):
        batch, _ = images_to_batch(df[self.getInputCol()])
        out_frames = [df.with_column(self.getOutputCol(), batch)]
        x = jnp.asarray(batch)
        if self.get("flipLeftRight"):
            out_frames.append(df.with_column(
                self.getOutputCol(), np.asarray(ops.flip(x, 1))))
        if self.get("flipUpDown"):
            out_frames.append(df.with_column(
                self.getOutputCol(), np.asarray(ops.flip(x, 0))))
        base = out_frames[0]
        for extra in out_frames[1:]:
            base = base.union(extra)
        return base
