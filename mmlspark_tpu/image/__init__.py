"""Image processing + DL featurization stages.

Replaces two reference packages with XLA-native batched image math:
- ``opencv/ImageTransformer.scala`` (native OpenCV per-row UDFs) →
  :class:`ImageTransformer` (batched jnp/XLA ops);
- ``image/`` (UnrollImage, ResizeImageTransformer, ImageSetAugmenter,
  ImageFeaturizer over CNTK) → the same stages over the flax model zoo.
"""

from .transforms import ImageTransformer
from .stages import (ImageSetAugmenter, ResizeImageTransformer, UnrollImage,
                     UnrollBinaryImage, images_to_batch)
from .featurizer import ImageFeaturizer

__all__ = ["ImageTransformer", "ImageSetAugmenter", "ResizeImageTransformer",
           "UnrollImage", "UnrollBinaryImage", "ImageFeaturizer",
           "images_to_batch"]
