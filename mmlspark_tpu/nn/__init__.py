"""Nearest-neighbor search.

Reference ``nn/`` (SURVEY §2.10): ``BallTree`` / ``ConditionalBallTree``
with inner-product bound search, broadcast to executors, queried via
mapPartitions. On TPU brute-force batched matmul + top-k beats tree
traversal (the MXU does 10^12 dot products/sec; pointer chasing does not),
so KNN/ConditionalKNN are matmul + ``jax.lax.top_k`` — same API, same
results, hardware-right algorithm.
"""

from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]
