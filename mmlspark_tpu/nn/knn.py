"""KNN / ConditionalKNN — exact inner-product top-k by batched matmul.

Reference ``nn/KNN.scala`` + ``nn/BallTree.scala:31-55`` (inner-product
ball tree) and ``nn/ConditionalKNN.scala:31-110`` (per-query label
conditioning). The reference broadcasts a ball tree and walks it per query;
here the index is a dense [N, D] matrix resident on device and queries run
as [Q, D] @ [D, N] → top-k — exact, batched, MXU-bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, \
    TypeConverters as TC
from ..core.contracts import HasFeaturesCol, HasOutputCol
from ..core.utils import as_2d_features


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_inner(index, queries, k: int):
    scores = queries @ index.T                       # [Q, N]
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_conditional(index, labels_onehot, queries, allowed, k: int):
    """allowed: [Q, L] bool — per-query permitted labels
    (ConditionalKNN's conditioner)."""
    scores = queries @ index.T                       # [Q, N]
    ok = (allowed.astype(jnp.float32)
          @ labels_onehot.T.astype(jnp.float32)) > 0  # [Q, N]
    scores = jnp.where(ok, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


class KNN(Estimator, HasFeaturesCol, HasOutputCol):
    valuesCol = Param("valuesCol", "payload column carried with neighbors",
                      TC.toString, default="values")
    k = Param("k", "neighbors per query", TC.toInt, default=5)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="output")

    def _fit(self, df):
        feats = as_2d_features(df, self.getFeaturesCol())
        values = df[self.get("valuesCol")] \
            if self.get("valuesCol") in df.columns else None
        model = KNNModel(index=np.asarray(feats, np.float32),
                         values=values)
        self._copy_params_to(model)
        return model


class KNNModel(Model, HasFeaturesCol, HasOutputCol):
    index = ComplexParam("index", "[N, D] indexed vectors")
    values = ComplexParam("values", "payload per indexed row", default=None,
                          has_default=True)
    k = Param("k", "neighbors per query", TC.toInt, default=5)

    def _transform(self, df):
        q = as_2d_features(df, self.getFeaturesCol()).astype(np.float32)
        idx = self.get("index")
        dist, nbr = _topk_inner(jnp.asarray(idx), jnp.asarray(q),
                                min(self.get("k"), idx.shape[0]))
        dist, nbr = np.asarray(dist), np.asarray(nbr)
        vals = self.get("values")
        out = np.empty(len(q), object)
        out[:] = [
            [{"distance": float(d), "index": int(i),
              **({"value": vals[i]} if vals is not None else {})}
             for d, i in zip(drow, irow)]
            for drow, irow in zip(dist, nbr)]
        return df.with_column(self.getOutputCol(), out)


class ConditionalKNN(Estimator, HasFeaturesCol, HasOutputCol):
    valuesCol = Param("valuesCol", "payload column", TC.toString,
                      default="values")
    labelCol = Param("labelCol", "per-row conditioning label", TC.toString,
                     default="labels")
    conditionerCol = Param("conditionerCol",
                           "per-query set of permitted labels", TC.toString,
                           default="conditioner")
    k = Param("k", "neighbors per query", TC.toInt, default=5)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="output")

    def _fit(self, df):
        feats = as_2d_features(df, self.getFeaturesCol())
        labels = np.asarray(df[self.get("labelCol")])
        values = df[self.get("valuesCol")] \
            if self.get("valuesCol") in df.columns else None
        levels = sorted({v for v in labels.tolist()}, key=str)
        lab_idx = np.asarray([levels.index(v) for v in labels.tolist()])
        onehot = np.zeros((len(labels), len(levels)), np.float32)
        onehot[np.arange(len(labels)), lab_idx] = 1.0
        model = ConditionalKNNModel(
            index=np.asarray(feats, np.float32), values=values,
            labels=labels, labelLevels=levels, labelsOnehot=onehot)
        self._copy_params_to(model)
        return model


class ConditionalKNNModel(Model, HasFeaturesCol, HasOutputCol):
    index = ComplexParam("index", "[N, D] indexed vectors")
    values = ComplexParam("values", "payload per indexed row", default=None,
                          has_default=True)
    labels = ComplexParam("labels", "label per indexed row")
    labelLevels = ComplexParam("labelLevels", "ordered distinct labels")
    labelsOnehot = ComplexParam("labelsOnehot", "[N, L] one-hot labels")
    conditionerCol = Param("conditionerCol", "per-query permitted labels",
                           TC.toString, default="conditioner")
    k = Param("k", "neighbors per query", TC.toInt, default=5)

    def _transform(self, df):
        q = as_2d_features(df, self.getFeaturesCol()).astype(np.float32)
        levels = self.get("labelLevels")
        cond = df[self.get("conditionerCol")]
        allowed = np.zeros((len(q), len(levels)), bool)
        for r, permitted in enumerate(cond):
            items = permitted if isinstance(
                permitted, (list, tuple, set, np.ndarray)) else [permitted]
            for v in items:
                if v in levels:
                    allowed[r, levels.index(v)] = True
        idx = self.get("index")
        dist, nbr = _topk_conditional(
            jnp.asarray(idx), jnp.asarray(self.get("labelsOnehot")),
            jnp.asarray(q), jnp.asarray(allowed),
            min(self.get("k"), idx.shape[0]))
        dist, nbr = np.asarray(dist), np.asarray(nbr)
        vals = self.get("values")
        labels = self.get("labels")
        out = np.empty(len(q), object)
        out[:] = [
            [{"distance": float(d), "index": int(i), "label": labels[i],
              **({"value": vals[i]} if vals is not None else {})}
             for d, i in zip(drow, irow) if np.isfinite(d)]
            for drow, irow in zip(dist, nbr)]
        return df.with_column(self.getOutputCol(), out)
