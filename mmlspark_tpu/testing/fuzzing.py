"""Fuzzing traits — the reference's signature test strategy.

Reference ``core/test/fuzzing/Fuzzing.scala``:
- ``TestObject`` (:29-45): a stage plus fit/transform DataFrames;
- ``SerializationFuzzing`` (:222-298): save/load the stage, the fitted
  model, and a whole pipeline; assert identical transform outputs;
- ``ExperimentFuzzing`` (:192-220): run fit+transform, compare results;
- ``FuzzingTest`` meta-tests (:30-200): every stage in the ecosystem has a
  fuzzer, serializes, and has consistent param names.
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
import pkgutil
import tempfile
from typing import Any

import numpy as np

from ..core import DataFrame, Estimator, Transformer, load_stage
from ..core.pipeline import Model, PipelineStage


@dataclasses.dataclass
class TestObject:
    """Stage + data (reference ``TestObject[S]``)."""
    __test__ = False  # not itself a pytest collectible

    stage: Any
    fit_df: DataFrame
    transform_df: DataFrame | None = None

    @property
    def df(self) -> DataFrame:
        return self.transform_df if self.transform_df is not None \
            else self.fit_df


def _df_equal(a: DataFrame, b: DataFrame, rtol=1e-5) -> None:
    assert list(a.columns) == list(b.columns), (a.columns, b.columns)
    for c in a.columns:
        ca, cb = a[c], b[c]
        if getattr(ca, "dtype", None) == object or not np.issubdtype(
                np.asarray(ca).dtype, np.number):
            assert len(ca) == len(cb)
        else:
            np.testing.assert_allclose(np.asarray(ca, np.float64),
                                       np.asarray(cb, np.float64),
                                       rtol=rtol, atol=1e-6, err_msg=c)


def _fit_if_needed(stage, df):
    if isinstance(stage, Estimator):
        return stage.fit(df)
    return stage


def experiment_fuzzing(obj: TestObject) -> None:
    """Fit + transform runs and is deterministic
    (reference ``ExperimentFuzzing.testExperiments``)."""
    model = _fit_if_needed(obj.stage, obj.fit_df)
    out1 = model.transform(obj.df)
    out2 = model.transform(obj.df)
    assert len(out1) >= 0
    _df_equal(out1, out2)


def serialization_fuzzing(obj: TestObject) -> None:
    """Save/load round trips preserve behavior
    (reference ``SerializationFuzzing``)."""
    with tempfile.TemporaryDirectory() as tmp:
        # 1. raw stage round trip: params survive
        obj.stage.save(f"{tmp}/stage")
        reloaded = load_stage(f"{tmp}/stage")
        assert type(reloaded) is type(obj.stage)
        for p in type(obj.stage).params():
            if not p.complex and p.name in obj.stage._paramMap:
                assert reloaded.get(p.name) == obj.stage.get(p.name), p.name

        # 2. fitted model round trip: identical transform outputs
        model = _fit_if_needed(obj.stage, obj.fit_df)
        out_before = model.transform(obj.df)
        if isinstance(model, (Model, Transformer)):
            model.save(f"{tmp}/model")
            model2 = load_stage(f"{tmp}/model")
            _df_equal(out_before, model2.transform(obj.df))


_STAGE_PACKAGES = (
    "mmlspark_tpu.stages", "mmlspark_tpu.featurize",
    "mmlspark_tpu.lightgbm", "mmlspark_tpu.vw", "mmlspark_tpu.image",
    "mmlspark_tpu.dl", "mmlspark_tpu.train", "mmlspark_tpu.automl",
    "mmlspark_tpu.nn", "mmlspark_tpu.recommendation",
    "mmlspark_tpu.isolationforest", "mmlspark_tpu.lime",
    "mmlspark_tpu.cyber", "mmlspark_tpu.cognitive", "mmlspark_tpu.io.http",
)


def iter_stage_classes():
    """Every concrete public stage class in the framework — the meta-test
    enumeration (reference ``FuzzingTest`` pipelineStages reflection)."""
    seen = set()
    for pkg_name in _STAGE_PACKAGES:
        pkg = importlib.import_module(pkg_name)
        modules = [pkg]
        if hasattr(pkg, "__path__"):
            for info in pkgutil.iter_modules(pkg.__path__):
                modules.append(importlib.import_module(
                    f"{pkg_name}.{info.name}"))
        for mod in modules:
            for _, cls in inspect.getmembers(mod, inspect.isclass):
                if (issubclass(cls, PipelineStage)
                        and not cls.__name__.startswith("_")
                        and not inspect.isabstract(cls)
                        and cls.__module__.startswith("mmlspark_tpu")
                        and cls not in seen
                        and cls not in (Transformer, Estimator, Model,
                                        PipelineStage)):
                    seen.add(cls)
                    yield cls
