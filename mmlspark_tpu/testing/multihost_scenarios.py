"""Pod worker bodies for the multi-host harness.

Each public function here is a ``launch_pod`` target
(``mmlspark_tpu.testing.multihost_scenarios:<name>``): it runs on EVERY
rank of the pod after ``distributed_init``, takes one JSON payload
dict, and returns a JSON-serializable result dict the launcher collects
rank-ordered. The 2-process CPU harness test
(``tests/test_multihost.py``) and the multichip bench's crosshost
section (``testing/multichip_bench.py``) share these bodies, so the CI
assertion and the banked bench number are the same program.

The scenarios all build the SAME mesh shape regardless of process
count (``payload["mesh"]``, default ``[2, 4]``): 2 processes × 4 local
devices and 1 process × 8 local devices both yield a (dp=2, tp=4)
mesh running an identical program — the only variable left is the
process boundary, which is exactly what the crosshost efficiency and
trajectory-equality acceptances isolate.
"""

from __future__ import annotations

import hashlib
import time

import numpy as np

from ..parallel.multihost import (DCN_AXIS, ICI_AXIS, feed_process_local,
                                  fleet_result, this_process)


def _mesh(shape):
    """An explicit-shape dcn×ici mesh: devices sorted process-major (so
    the outer/dp axis walks processes) and reshaped to ``shape`` — the
    same layout :func:`~..parallel.multihost.pod_mesh` derives, but
    with the shape pinned so a 1-process run can reproduce a pod's
    mesh exactly."""
    import jax
    from jax.sharding import Mesh
    d0, d1 = int(shape[0]), int(shape[1])
    devs = sorted(jax.devices(),
                  key=lambda d: (getattr(d, "process_index", 0), d.id))
    if len(devs) != d0 * d1:
        raise RuntimeError(
            f"mesh shape {shape} needs {d0 * d1} devices, have "
            f"{len(devs)}")
    return Mesh(np.asarray(devs).reshape(d0, d1), (DCN_AXIS, ICI_AXIS))


def _my_rows(arr):
    """This process's contiguous block of a batch-leading host array —
    the rows ``feed_process_local`` expects each rank to contribute.
    Process-major device sort means dp block ``i`` belongs to process
    ``i``; a single process owns everything."""
    idx, cnt = this_process()
    if cnt == 1:
        return arr
    if arr.shape[0] % cnt:
        raise ValueError(
            f"batch {arr.shape[0]} must divide by process count {cnt}")
    per = arr.shape[0] // cnt
    return arr[idx * per:(idx + 1) * per]


def _ref_pipeline(a):
    """Single-jit reference for the fused serving pipeline: the same
    math as its two jit-safe UDF stages, used for the bit-equality
    check. Module-level (not a lambda inside the scenario) so the
    traced region graftcheck sees is exactly this body."""
    import jax.numpy as jnp
    return jnp.tanh(a * 2.0 + 1.0)


def _dp_allreduce(a):
    """The shard_map body for the crosshost byte count: one observed
    allreduce over the dp (DCN) axis."""
    from ..parallel import collectives
    return collectives.allreduce(a, DCN_AXIS)


# --------------------------------------------------------------- scenarios

def check_init(payload: dict) -> dict:
    """The ``distributed_init`` acceptance body: global mesh shape,
    process-local shard placement, and (via the harness rc) clean
    shutdown."""
    import jax
    idx, cnt = this_process()
    from ..parallel.multihost import pod_mesh
    mesh = pod_mesh()
    local = len(jax.local_devices())
    rows_per = 2
    stamped = np.full((rows_per, 3), idx, np.float32)
    garr = feed_process_local(mesh, stamped if cnt > 1
                              else np.full((rows_per * cnt, 3), 0.0,
                                           np.float32))
    shard_local = all(
        getattr(sh.device, "process_index", 0) == idx
        and float(np.asarray(sh.data).ravel()[0]) == float(idx)
        for sh in garr.addressable_shards) if cnt > 1 else True
    return {
        "process_index": idx,
        "process_count": cnt,
        "device_count": len(jax.devices()),
        "local_device_count": local,
        "mesh_axes": list(mesh.axis_names),
        "mesh_shape": [int(mesh.shape[DCN_AXIS]),
                       int(mesh.shape[ICI_AXIS])],
        "global_rows": int(garr.shape[0]),
        "fully_addressable": bool(garr.is_fully_addressable),
        "shard_local": bool(shard_local),
    }


def train_trajectory(payload: dict) -> dict:
    """The partitioned train step on the pod: rule-sharded BertEncoder
    TrainState, per-host batch feeding, seeded loss trajectory (the
    1-proc vs 2-proc atol-1e-5 acceptance), steady-state runtime-compile
    count, and (``bench_iters > 0``) images/sec for the crosshost
    scaling-efficiency ratio."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..dl.bert import BertEncoder
    from ..dl.train import (init_train_state, make_partitioned_train_step,
                            partition_train_state)
    from ..obs.profile import compile_tracker
    from ..parallel import compat
    from ..parallel.partition import partition_rules_for

    shape = payload.get("mesh") or [2, 4]
    steps = int(payload.get("steps", 3))
    B = int(payload.get("batch", 16))
    T = int(payload.get("seq_len", 16))
    seed = int(payload.get("seed", 0))
    bench_iters = int(payload.get("bench_iters", 0))
    width = int(payload.get("width", 64))

    mesh = _mesh(shape)
    # f32 end to end: the trajectory acceptance compares float losses
    # across runs at atol 1e-5, which bf16 compute would not hold
    module = BertEncoder(vocab=512, width=width, depth=2, heads=4,
                         mlp_dim=2 * width, max_len=T, pooler=False,
                         dtype=jnp.float32)
    tx = optax.adamw(1e-3)
    rng = np.random.default_rng(seed)
    batches = [(rng.integers(1, 512, size=(B, T)).astype(np.int32),
                rng.integers(0, 64, size=B).astype(np.int32))
               for _ in range(steps)]
    # every rank initializes the SAME full host params (same key) —
    # the shard_params multi-process contract
    state = init_train_state(module, jax.random.PRNGKey(seed),
                             jnp.asarray(batches[0][0][:1]), tx)
    state, shardings = partition_train_state(
        state, mesh, partition_rules_for("BertEncoder"))
    step = make_partitioned_train_step(module, tx, mesh, shardings,
                                       fetch="pooled")

    def feed(ids, labels):
        return (feed_process_local(mesh, _my_rows(ids)),
                feed_process_local(mesh, _my_rows(labels)))

    losses = []
    for i, (ids, labels) in enumerate(batches):
        gi, gl = feed(ids, labels)
        state, loss = step(state, gi, gl)
        losses.append(float(np.asarray(
            compat.process_allgather(loss)).ravel()[0]))
        if i == 0:
            # warmup over: the zero-runtime-compiles pod acceptance —
            # every later step must hit the compile cache
            compile_tracker.mark_steady()
    out = {"losses": losses, "process_count": this_process()[1],
           "mesh_shape": [int(s) for s in shape]}
    if bench_iters:
        gi, gl = feed(*batches[-1])

        def run(n):
            s, loss = state, None
            for _ in range(n):
                s, loss = step(s, gi, gl)
            jax.block_until_ready(loss)
            return s

        state = run(1)
        t0 = time.perf_counter()
        state = run(bench_iters)
        out["ips"] = B * bench_iters / (time.perf_counter() - t0)
    out["runtime_compiles"] = int(compile_tracker.runtime_compiles())
    compile_tracker.unmark_steady()
    return out


def fused_serving(payload: dict) -> dict:
    """The dp-sharded fused serving segment answering requests whose
    rows live on different hosts: compile a jit-safe elementwise
    pipeline against the pod mesh, feed each request per-host, execute
    via ``FusedSegment.run_sharded``, gather with ``process_allgather``.
    Reduction-free elementwise stages make the output bit-stable, so
    the rank-0 sha256 digest is the cross-run bit-equality witness
    (pod run vs single-host run of the same seed must match exactly)."""
    import jax
    import jax.numpy as jnp

    from ..core import DataFrame, compile_pipeline
    from ..core.compile import FusedSegment
    from ..parallel import compat
    from ..stages.basic import UDFTransformer

    shape = payload.get("mesh") or [2, 4]
    rows = int(payload.get("rows", 32))
    feats = int(payload.get("feats", 8))
    reqs = int(payload.get("requests", 8))
    seed = int(payload.get("seed", 0))

    mesh = _mesh(shape)
    stages = [
        UDFTransformer(inputCol="x", outputCol="scaled",
                       udf=lambda a: a * 2.0 + 1.0, jitSafe=True),
        UDFTransformer(inputCol="scaled", outputCol="score",
                       udf=lambda a: jnp.tanh(a), jitSafe=True),
    ]
    rng = np.random.default_rng(seed)
    example = DataFrame(
        {"x": rng.standard_normal((rows, feats)).astype(np.float32)})
    # weight-style rules RIGHT-align (partition.to_shardings), so the
    # row dim of a [rows, feats] column needs the explicit 2-entry form
    cp = compile_pipeline(stages, example, mesh=mesh,
                          rules=[(r".*", ("dp", None))],
                          service="podserve")
    seg = cp.plan[0]
    if not isinstance(seg, FusedSegment):
        raise RuntimeError(f"pipeline did not fuse: {cp.describe()}")

    def serve(xr):
        gx = feed_process_local(mesh, _my_rows(xr))
        out = seg.run_sharded({"x": gx})
        return compat.process_allgather(out["score"], tiled=True)

    warm_x = rng.standard_normal((rows, feats)).astype(np.float32)
    score = serve(warm_x)  # compile + the bit-equality witness
    ref = np.asarray(jax.jit(_ref_pipeline)(warm_x))
    bit_equal = bool(np.array_equal(np.asarray(score), ref))
    digest = hashlib.sha256(
        np.ascontiguousarray(np.asarray(score)).tobytes()).hexdigest()
    lat = []
    for _ in range(reqs):
        xr = rng.standard_normal((rows, feats)).astype(np.float32)
        t0 = time.perf_counter()
        serve(xr)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))]
    return {"bit_equal": bit_equal, "digest": digest,
            "p99_ms": round(p99 * 1e3, 3), "requests": reqs,
            "process_count": this_process()[1]}


def fleet_telemetry(payload: dict) -> dict:
    """The fleet-federation acceptance body: every rank produces the
    telemetry the fleet plane federates — profiled steps
    (``profile_step_seconds{...,process=<rank>}``), one instrumented
    cross-host allreduce (``collective_bytes_total``), and the memory
    profiler's gauges (``mem_hbm_*`` on real accelerators; absent, not
    raising, on CPU pods) — and ships it home on the result channel via
    :func:`~..parallel.multihost.fleet_result`. The launcher-side test
    merges the rank envelopes through ``obs.fleet.ingest_pod_results``
    and asserts one ``?scope=fleet`` exposition carries both ranks with
    zero label collisions."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..obs.memory import device_memory_stats
    from ..obs.profile import step_profiler
    from ..parallel import compat

    shape = payload.get("mesh") or [2, 4]
    steps = int(payload.get("steps", 3))
    rows = int(payload.get("rows", 64))
    mesh = _mesh(shape)
    x = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)
    gx = feed_process_local(mesh, _my_rows(x))
    fn = compat.jit(
        compat.shard_map(_dp_allreduce, mesh=mesh, in_specs=P(DCN_AXIS),
                         out_specs=P(DCN_AXIS)),
        name="fleet_allreduce")
    for _ in range(steps):
        with step_profiler.step("fleet_step") as h:
            h.done(fn(gx))
    _, cnt = this_process()
    return fleet_result({
        "process_count": cnt,
        "hbm_devices": len(device_memory_stats()),
        "local_devices": len(jax.local_devices()),
    })


def xprof_fanout(payload: dict) -> dict:
    """The pod xprof-capture fanout acceptance body (ISSUE 20): every
    rank runs a mesh-registered :class:`DistributedServingServer`
    (ports pinned by the payload — the launcher picks free ones); once
    the registry table holds every rank, rank 0 POSTs its OWN
    ``/debug/xprof?duration_ms=`` and the fanout handler must capture
    every OTHER rank over ``__fleet__`` while capturing locally. Each
    rank returns its local capture listing — the launcher asserts one
    rank-suffixed capture directory per rank from the single POST."""
    import http.client
    import json as _json

    import jax
    import jax.numpy as jnp

    from ..obs.xprof import xprof_captures
    from ..serving.distributed import (DistributedServingServer,
                                       DriverRegistry, RegistryClient)

    idx, cnt = this_process()
    registry_port = int(payload["registry_port"])
    worker_ports = [int(p) for p in payload["worker_ports"]]
    duration_ms = float(payload.get("duration_ms", 100.0))
    service = str(payload.get("service", "xprof-pod"))
    deadline = time.monotonic() + float(payload.get("timeout_s", 30.0))

    # a live backend is the capture precondition (_jax_ready): touch it
    jax.block_until_ready(jnp.zeros(1))

    driver = None
    if idx == 0:
        driver = DriverRegistry(port=registry_port,
                                heartbeat_timeout=0).start()
    client = RegistryClient(("127.0.0.1", registry_port))
    while time.monotonic() < deadline:
        try:
            client.workers(service)
            break
        except Exception:
            time.sleep(0.05)
    server = DistributedServingServer(
        service, ("127.0.0.1", registry_port), worker_id=f"rank{idx}",
        port=worker_ports[idx], load_report_interval=0.1).start()
    out: dict = {"process": idx, "worker_id": f"rank{idx}"}
    try:
        # every rank waits for the full table (fanout needs peers)
        while time.monotonic() < deadline:
            with server._lock:
                n = len(server._peers)
            if n >= cnt:
                break
            time.sleep(0.05)
        if idx == 0:
            conn = http.client.HTTPConnection(
                "127.0.0.1", worker_ports[0],
                timeout=duration_ms / 1e3 + 20.0)
            try:
                conn.request("POST",
                             f"/debug/xprof?duration_ms={duration_ms}"
                             f"&tag=pod")
                resp = conn.getresponse()
                out["fanout_status"] = resp.status
                out["fanout"] = _json.loads(resp.read())
            finally:
                conn.close()
        else:
            # the fanout's __fleet__ leg runs the capture on THIS
            # rank's handler thread; wait until it lands on disk
            while time.monotonic() < deadline:
                if xprof_captures.list_captures()["captures"]:
                    break
                time.sleep(0.05)
    finally:
        server.stop()
        if driver is not None:
            driver.stop()
    listing = xprof_captures.list_captures()
    out["captures"] = [c["capture"] for c in listing["captures"]]
    out["capture_root"] = listing["root"]
    return out


def collective_bytes(payload: dict) -> dict:
    """An explicit cross-host allreduce through the instrumented
    ``parallel.collectives`` wrapper: the GSPMD-inserted collectives of
    the train step bypass the obs byte series (they exist only inside
    the compiled program), so the crosshost byte number comes from a
    shard_map'd ``allreduce`` over the dp (DCN) axis — and lands in
    ``collective_bytes_total{...,process=<rank>}``, the new per-process
    label family."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..obs import registry as _reg
    from ..parallel import compat

    shape = payload.get("mesh") or [2, 4]
    rows = int(payload.get("rows", 512))
    mesh = _mesh(shape)
    x = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)
    gx = feed_process_local(mesh, _my_rows(x))
    fn = compat.jit(
        compat.shard_map(_dp_allreduce, mesh=mesh, in_specs=P(DCN_AXIS),
                         out_specs=P(DCN_AXIS)),
        name="crosshost_allreduce")
    out = fn(gx)
    jax.block_until_ready(out)
    idx, cnt = this_process()
    plab = {"process": str(idx)} if cnt > 1 else {}
    nbytes = _reg.counter(
        "collective_bytes_total",
        "per-shard payload bytes at collective issue, by op/axis").value(
        op="allreduce_sum", axis=DCN_AXIS, **plab)
    total = np.asarray(compat.process_allgather(out, tiled=True))
    return {"bytes": float(nbytes), "process": idx,
            "labelled": bool(plab), "checksum": float(total.sum())}
