"""Model equality assertion.

Reference ``core/utils/ModelEquality.scala`` — used by generated Python
tests to assert a stage and its (re)loaded counterpart are equivalent
(``fuzzing/Fuzzing.scala:166-172``).
"""

from __future__ import annotations

import numpy as np


def assert_model_equal(a, b) -> None:
    """Same class, same simple params, same complex-param array content."""
    assert type(a) is type(b), (type(a), type(b))
    for p in type(a).params():
        in_a, in_b = p.name in a._paramMap, p.name in b._paramMap
        assert in_a == in_b, f"param {p.name} set in only one model"
        if not in_a:
            continue
        va, vb = a.get(p.name), b.get(p.name)
        if isinstance(va, np.ndarray):
            np.testing.assert_allclose(va, np.asarray(vb), rtol=1e-6)
        elif not p.complex:
            assert va == vb, f"param {p.name}: {va!r} != {vb!r}"
