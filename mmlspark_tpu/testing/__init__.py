"""Test infrastructure: fuzzing traits + benchmark harness.

Reference L11 (SURVEY §4): ``core/test/fuzzing/Fuzzing.scala`` (every stage
gets serialization/experiment fuzzing via declared TestObjects, with
meta-tests enforcing ecosystem-wide coverage) and
``core/test/benchmarks/Benchmarks.scala`` (named metric values regression-
checked against CSVs with explicit tolerance).
"""

from .fuzzing import TestObject, experiment_fuzzing, serialization_fuzzing, \
    iter_stage_classes
from .benchmarks import Benchmarks
from .model_equality import assert_model_equal

__all__ = ["TestObject", "experiment_fuzzing", "serialization_fuzzing",
           "iter_stage_classes", "Benchmarks", "assert_model_equal"]
