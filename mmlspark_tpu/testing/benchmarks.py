"""Benchmark regression harness.

Reference ``core/test/benchmarks/Benchmarks.scala:16-130``: named metric
values with explicit tolerance recorded in CSVs
(``src/test/resources/benchmarks/benchmarks_<Suite>.csv``); the test
recomputes each metric and ``compareBenchmark`` asserts it matches within
precision. Same CSV format here: ``name,value,precision`` rows.

Timings come through the obs subsystem, not private stopwatches: a
``timed(...)`` region records into the process-wide registry
(``benchmark_seconds{name=...}``) and the benchmark row reads the value
back from that same histogram, so a benchmark timing is always also a
scrapeable series (``/metrics``, ``registry.snapshot()``) — one
measurement surface for benches, serving, and training alike.
"""

from __future__ import annotations

import contextlib
import csv
import os

from ..obs.metrics import registry as _registry


class Benchmarks:
    """Accumulate metrics, then compare (or regenerate) the CSV."""

    def __init__(self, csv_path: str):
        self.csv_path = csv_path
        self.recorded: list[tuple[str, float, float]] = []

    def add(self, name: str, value: float, precision: float) -> None:
        """Reference ``addBenchmark``."""
        self.recorded.append((name, float(value), float(precision)))

    @contextlib.contextmanager
    def timed(self, name: str, precision: float):
        """Time a region through the obs registry and record the row.

        The wall seconds land in the process-wide
        ``benchmark_seconds{name=...}`` histogram (scrapeable alongside
        serving/training series) and THIS region's duration becomes the
        CSV row — not an aggregate over the labeled series, which would
        fold warmup passes and prior in-process runs into the value."""
        hist = _registry.histogram(
            "benchmark_seconds", "benchmark timed-region wall seconds")
        with hist.time(name=name) as t:
            yield
        self.add(name, t.seconds, precision)

    def add_from_registry(self, name: str, sample: str,
                          precision: float, registry=None) -> None:
        """Record a registry sample (a ``snapshot()`` key, e.g.
        ``serving_requests_total{route="/"}``) as a benchmark row."""
        snap = (registry if registry is not None else _registry) \
            .snapshot()
        if sample not in snap:
            raise KeyError(
                f"registry sample {sample!r} not found; known samples "
                f"include {sorted(snap)[:8]}...")
        self.add(name, snap[sample], precision)

    def _load(self) -> dict[str, tuple[float, float]]:
        out = {}
        with open(self.csv_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                out[row[0]] = (float(row[1]), float(row[2]))
        return out

    def _write(self) -> None:
        os.makedirs(os.path.dirname(self.csv_path), exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.writer(f)
            for name, value, precision in self.recorded:
                w.writerow([name, repr(value), repr(precision)])

    def verify(self, regenerate: bool = False) -> None:
        """Reference ``verifyBenchmarks``: assert every recorded metric is
        within its recorded precision; regenerate=True (or a missing CSV)
        writes the file instead — the reference's workflow for adding new
        benchmark rows."""
        if regenerate or not os.path.exists(self.csv_path):
            self._write()
            return
        expected = self._load()
        errors = []
        for name, value, precision in self.recorded:
            if name not in expected:
                errors.append(f"missing benchmark row {name!r}")
                continue
            exp_val, exp_prec = expected[name]
            if abs(value - exp_val) > exp_prec:
                errors.append(
                    f"{name}: got {value}, expected {exp_val} ± {exp_prec}")
        if errors:
            raise AssertionError("benchmark regressions:\n"
                                 + "\n".join(errors))
