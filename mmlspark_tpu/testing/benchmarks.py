"""Benchmark regression harness.

Reference ``core/test/benchmarks/Benchmarks.scala:16-130``: named metric
values with explicit tolerance recorded in CSVs
(``src/test/resources/benchmarks/benchmarks_<Suite>.csv``); the test
recomputes each metric and ``compareBenchmark`` asserts it matches within
precision. Same CSV format here: ``name,value,precision`` rows.

Timings come through the obs subsystem, not private stopwatches: a
``timed(...)`` region records into the process-wide registry
(``benchmark_seconds{name=...}``) and the benchmark row reads the value
back from that same histogram, so a benchmark timing is always also a
scrapeable series (``/metrics``, ``registry.snapshot()``) — one
measurement surface for benches, serving, and training alike.
"""

from __future__ import annotations

import contextlib
import csv
import os
import threading
import time
from math import ceil as _ceil

from ..obs.metrics import registry as _registry


class Benchmarks:
    """Accumulate metrics, then compare (or regenerate) the CSV."""

    def __init__(self, csv_path: str):
        self.csv_path = csv_path
        self.recorded: list[tuple[str, float, float]] = []

    def add(self, name: str, value: float, precision: float) -> None:
        """Reference ``addBenchmark``."""
        self.recorded.append((name, float(value), float(precision)))

    @contextlib.contextmanager
    def timed(self, name: str, precision: float):
        """Time a region through the obs registry and record the row.

        The wall seconds land in the process-wide
        ``benchmark_seconds{name=...}`` histogram (scrapeable alongside
        serving/training series) and THIS region's duration becomes the
        CSV row — not an aggregate over the labeled series, which would
        fold warmup passes and prior in-process runs into the value."""
        hist = _registry.histogram(
            "benchmark_seconds", "benchmark timed-region wall seconds")
        with hist.time(name=name) as t:
            yield
        self.add(name, t.seconds, precision)

    def add_from_registry(self, name: str, sample: str,
                          precision: float, registry=None) -> None:
        """Record a registry sample (a ``snapshot()`` key, e.g.
        ``serving_requests_total{route="/"}``) as a benchmark row."""
        snap = (registry if registry is not None else _registry) \
            .snapshot()
        if sample not in snap:
            raise KeyError(
                f"registry sample {sample!r} not found; known samples "
                f"include {sorted(snap)[:8]}...")
        self.add(name, snap[sample], precision)

    def _load(self) -> dict[str, tuple[float, float]]:
        out = {}
        with open(self.csv_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                out[row[0]] = (float(row[1]), float(row[2]))
        return out

    def _write(self) -> None:
        os.makedirs(os.path.dirname(self.csv_path), exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.writer(f)
            for name, value, precision in self.recorded:
                w.writerow([name, repr(value), repr(precision)])

    def verify(self, regenerate: bool = False) -> None:
        """Reference ``verifyBenchmarks``: assert every recorded metric is
        within its recorded precision; regenerate=True (or a missing CSV)
        writes the file instead — the reference's workflow for adding new
        benchmark rows."""
        if regenerate or not os.path.exists(self.csv_path):
            self._write()
            return
        expected = self._load()
        errors = []
        for name, value, precision in self.recorded:
            if name not in expected:
                errors.append(f"missing benchmark row {name!r}")
                continue
            exp_val, exp_prec = expected[name]
            if abs(value - exp_val) > exp_prec:
                errors.append(
                    f"{name}: got {value}, expected {exp_val} ± {exp_prec}")
        if errors:
            raise AssertionError("benchmark regressions:\n"
                                 + "\n".join(errors))


class _SynthRequest:
    """A scheduler item for the overload scenario: carries the latch the
    arrival thread waits on plus the attributes the sched subsystem
    decorates (route/deadline/tenant/on_done)."""

    __slots__ = ("submitted", "done_at", "status", "route", "deadline",
                 "tenant", "cost", "on_done", "span", "queue_wait",
                 "_event")

    def __init__(self):
        self.submitted = time.monotonic()
        self.done_at = None
        self.status = None
        self.route = "/"
        self.deadline = None
        self.tenant = ""        # quota/tier bucket (sched.tenancy)
        self.cost = 0.0         # synthetic per-item service seconds
        self.on_done = None
        self.span = None        # request span (tracing scenarios)
        self.queue_wait = None  # stamped by the scheduler at pop
        self._event = threading.Event()

    def reply(self, status: int) -> bool:
        # reply-exactly-once latch, same contract as serving's
        # CachedRequest (the scheduler's expiry shed path calls this)
        if self._event.is_set():
            return False
        self.status = status
        self.done_at = time.monotonic()
        self._event.set()
        cb, self.on_done = self.on_done, None
        if cb is not None:
            cb()
        return True


def overload_scenario(*, service: str = "overload-bench",
                      deadline_s: float = 0.2,
                      item_service_s: float = 0.004,
                      max_queue: int = 64,
                      max_batch: int = 8,
                      rate_factor: float = 2.0,
                      n_requests: int = 400,
                      registry=None) -> dict:
    """Synthetic overload benchmark for the sched subsystem (ISSUE 2
    acceptance): offer load at ``rate_factor``× the sustainable rate
    into a :class:`~mmlspark_tpu.sched.RequestScheduler` backed by a
    deterministic executor (``item_service_s`` seconds per request,
    batched up to ``max_batch``), then read the ``sched_*`` series back
    from the obs registry.

    A correct scheduler under 2× overload must (a) bound the queue —
    admission sheds BEFORE depth runs away, (b) keep the latency of
    requests it chose to admit within the deadline budget — expiry
    sheds fire before execution, never after — and (c) shed the excess
    as 429s rather than timing everyone out. The returned dict carries
    the measured p99/max depth plus the registry readings
    (``sched_admitted_total``, ``sched_shed_total`` by reason,
    ``sched_queue_wait_seconds`` count) so benches can bank and tests
    can assert on either surface.
    """
    from ..obs.metrics import registry as _default
    from ..sched import RequestScheduler, Shed

    reg = registry if registry is not None else _default
    shed_answered: list[_SynthRequest] = []
    sched = RequestScheduler(
        service, max_queue=max_queue, deadline=deadline_s, registry=reg,
        on_shed=lambda item, reason, retry_after:
            (shed_answered.append(item), item.reply(429)))
    done: list[_SynthRequest] = []
    stop = threading.Event()
    depth_high = [0]

    def executor():
        while not stop.is_set() or sched.qsize():
            batch = sched.next_batch(max_batch=max_batch, max_wait=0.05)
            if not batch:
                continue
            t0 = time.monotonic()
            time.sleep(item_service_s * len(batch))  # deterministic work
            sched.estimator.observe(len(batch),
                                    time.monotonic() - t0)
            for item in batch:
                item.reply(200)
                done.append(item)

    worker = threading.Thread(target=executor, daemon=True)
    worker.start()
    interval = item_service_s / rate_factor
    admitted = shed_at_intake = 0
    # prime the service-time EWMA so predictive admission has a model
    # from the first request (a cold registry sheds nothing until the
    # first batch lands)
    sched.estimator.observe(1, item_service_s)
    for _ in range(n_requests):
        req = _SynthRequest()
        try:
            sched.submit(req)
            admitted += 1
        except Shed:
            shed_at_intake += 1
        depth_high[0] = max(depth_high[0], sched.qsize())
        time.sleep(interval)
    stop.set()
    sched.wake()
    worker.join(timeout=10)
    lat = sorted((r.done_at - r.submitted) for r in done
                 if r.done_at is not None)
    snap = reg.snapshot()

    def _series(prefix: str) -> dict:
        return {k: v for k, v in snap.items()
                if k.startswith(prefix) and f'service="{service}"' in k}

    return {
        "offered": n_requests,
        "admitted": admitted,
        "answered_200": len(lat),
        "shed_at_intake": shed_at_intake,
        "shed_after_queueing": len(shed_answered),
        "deadline_s": deadline_s,
        "max_queue": max_queue,
        "max_depth_seen": depth_high[0],
        # nearest-rank percentiles: ceil(q*n)-1 — int(n*0.99)-1 would
        # sit one rank low and hide exactly the tail samples a
        # deadline-SLO acceptance check exists to catch
        "p50_s": lat[max(_ceil(0.50 * len(lat)) - 1, 0)]
        if lat else float("nan"),
        "p99_s": lat[max(_ceil(0.99 * len(lat)) - 1, 0)]
        if lat else float("nan"),
        "sched_admitted_total": _series("sched_admitted_total"),
        "sched_shed_total": _series("sched_shed_total"),
        "sched_queue_wait_count": _series("sched_queue_wait_seconds_count"),
    }


def tracing_overhead_scenario(*, service: str = "tracing-bench",
                              n_requests: int = 200,
                              item_service_s: float = 0.005,
                              max_batch: int = 8,
                              reps: int = 3,
                              registry=None) -> dict:
    """Profiler-overhead guard (ISSUE 8 satellite): the same synthetic
    serving pipeline (RequestScheduler + deterministic executor — no
    HTTP socket, so loopback jitter cannot masquerade as tracing cost)
    measured with the full tracing+profiler stack OFF vs ON, asserting
    the instrumented p99 stays within 5%% of bare.

    ON means everything a traced serving request pays: a request span
    per item, the scheduler's ``sched.queue`` child span, a retroactive
    execute span, a cost-model feature-log record, a ``StepProfiler``
    step around each executor batch, and a flight-recorder
    ``note_request`` per reply. The modes run INTERLEAVED (off, on,
    off, on, ...) and each mode keeps its best-of-``reps`` p99 — the
    same min-of-runs discipline bench.py's loaded rows use: the
    per-rep minimum is the deterministic floor (service time + any
    instrumentation cost), so host contention and sleep jitter — which
    hit both modes but not symmetrically within one rep — cannot
    manufacture or mask overhead. Returns both p99s, ``overhead_pct``,
    and ``within_bound`` (the 5%% contract — asserted by the test AND
    banked in the bench JSON).
    """
    from ..obs.export import flight_recorder
    from ..obs.profile import StepProfiler, feature_log
    from ..obs.metrics import registry as _default
    from ..obs.tracing import tracer
    from ..sched import RequestScheduler

    reg = registry if registry is not None else _default
    profiler = StepProfiler(service=service, registry=reg)
    flight_recorder.install()

    def one_run(traced: bool) -> float:
        sched = RequestScheduler(f"{service}-{'on' if traced else 'off'}",
                                 registry=reg)
        done: list[_SynthRequest] = []
        stop = threading.Event()

        def executor():
            while not stop.is_set() or sched.qsize():
                batch = sched.next_batch(max_batch=max_batch,
                                         max_wait=0.05)
                if not batch:
                    continue
                if traced:
                    with profiler.step("tracing-bench.batch") as h:
                        time.sleep(item_service_s * len(batch))
                        h.done(None)
                else:
                    time.sleep(item_service_s * len(batch))
                for item in batch:
                    span = getattr(item, "span", None)
                    if span is not None:
                        tracer.emit_span(
                            "serving.execute", parent=span,
                            seconds=item_service_s * len(batch),
                            service=service, rows=len(batch))
                        feature_log.record(
                            service=service, route="/",
                            batch=len(batch),
                            queue_ms=(getattr(item, "queue_wait", 0.0)
                                      or 0.0) * 1e3,
                            execute_ms=item_service_s * len(batch)
                            * 1e3, trace_id=span.trace_id)
                    item.reply(200)
                    if span is not None:
                        span.set_attr("status", 200)
                        tracer.end_span(span)
                        flight_recorder.note_request(
                            span.trace_id,
                            time.monotonic() - item.submitted,
                            status=200)
                    done.append(item)

        worker = threading.Thread(target=executor, daemon=True)
        worker.start()
        # pace BELOW saturation: the executor's cost is linear in batch
        # size here, so an overloaded run would measure queue growth —
        # the one thing that is NOT tracing overhead — in both modes
        interval = item_service_s * 1.5
        for _ in range(n_requests):
            req = _SynthRequest()
            if traced:
                req.span = tracer.start_span(
                    "serving.request", parent=None, current=False,
                    service=service, route="/")
            try:
                sched.submit(req)
            except Exception:
                req.reply(503)
            time.sleep(interval)
        stop.set()
        sched.wake()
        worker.join(timeout=20)
        lat = sorted((r.done_at - r.submitted) for r in done
                     if r.done_at is not None and r.status == 200)
        if not lat:
            return float("nan")
        return lat[max(_ceil(0.99 * len(lat)) - 1, 0)]

    offs, ons = [], []
    for _ in range(reps):
        offs.append(one_run(False))
        ons.append(one_run(True))
    p99_off, p99_on = min(offs), min(ons)
    overhead_pct = (p99_on - p99_off) / p99_off * 100.0
    return {
        "n_requests": n_requests,
        "item_service_s": item_service_s,
        "reps": reps,
        "p99_off_s": p99_off,
        "p99_on_s": p99_on,
        "overhead_pct": overhead_pct,
        "bound_pct": 5.0,
        "within_bound": overhead_pct <= 5.0,
        "feature_records": len(feature_log),
    }


# span names a COMPLETE cross-process tree must contain for a request
# answered through the worker mesh (chaos acceptance): the driver-side
# request root + its queue wait, and the compute worker's execute +
# device spans, all under one trace id
COMPLETE_TRACE_SPANS = frozenset({"serving.request", "sched.queue",
                                  "worker.execute", "worker.device"})


def chaos_scenario(*, service: str = "chaos-bench", seed: int = 11,
                   n_requests: int = 40, n_workers: int = 3,
                   error_rate: float = 0.05,
                   latency_spike_s: float = 0.05,
                   latency_rate: float = 0.05,
                   kill_after_leases: int = 1,
                   request_timeout_s: float = 10.0,
                   trace_dir: str | None = None) -> dict:
    """Seeded chaos acceptance for the resilience subsystem (ISSUE 4):
    a real worker mesh (driver registry with heartbeat liveness, one
    ingest server, ``n_workers`` in-thread compute workers) driven under
    an armed fault schedule — one injected worker death mid-lease
    (``worker.death``, after ``kill_after_leases`` healthy leases), 5%%
    injected 503s and latency spikes on the client's ``http.send`` hop —
    while a closed-loop client offers ``n_requests`` through the
    resilience :class:`~mmlspark_tpu.resilience.RetryPolicy`.

    The contract measured: every accepted request is answered 200 (the
    killed worker's leases replay to survivors, injected 503s are
    re-offered per ``Retry-After``) or shed per policy (429/503 only);
    ZERO transport errors (status 0 / connection reset) reach the
    client. The returned dict carries the realized fault ``schedule`` —
    a pure function of the seed and per-point probe order, so re-running
    with the same seed reproduces it — plus the ``resilience_*`` /
    ``serving_lease_replays_total`` registry readings the acceptance
    asserts on.

    Fault decisions are per-point deterministic; the client runs
    single-threaded so the realized schedule is also totally ordered.

    Tracing (ISSUE 8 acceptance): every client request runs under a
    ``client.request`` root span, so the whole run is cross-process
    traced — the result reports, per answered request, whether its span
    tree is COMPLETE (:data:`COMPLETE_TRACE_SPANS` under one trace id)
    and samples one such tree; ``trace_dir`` additionally exports the
    collected spans as Chrome-trace/Perfetto JSON
    (``<trace_dir>/chaos_trace.json``).
    """
    import json as _json
    import os as _os

    import numpy as np

    from ..io.http.clients import send_request
    from ..io.http.schema import HTTPRequestData, HTTPResponseData
    from ..obs.export import SpanCollector, chrome_trace
    from ..obs.tracing import tracer
    from ..resilience import FaultRule, RetryPolicy, faults
    from ..serving import (DistributedServingServer, DriverRegistry,
                           remote_worker_loop)

    def echo(df):
        replies = np.empty(len(df), object)
        replies[:] = [HTTPResponseData(status_code=200,
                                       entity=(r.entity or b"").upper())
                      for r in df["request"]]
        return df.with_column("reply", replies)

    snap_before = _registry.snapshot()
    driver = DriverRegistry(heartbeat_timeout=0.75).start()
    server = DistributedServingServer(
        service, driver.address, lease_timeout=2.0, reply_timeout=15.0,
        load_report_interval=0.2).start()
    stop = threading.Event()
    workers = [threading.Thread(
        target=remote_worker_loop,
        args=(driver.address, service, echo),
        kwargs={"stop_event": stop, "heartbeat_interval": 0.2,
                "max_batch": 4, "worker_id": f"chaos-w{i}"},
        daemon=True) for i in range(n_workers)]
    rules = [
        FaultRule(point="worker.death", kind="kill", p=1.0,
                  after=kill_after_leases, times=1),
        FaultRule(point="http.send", kind="error", p=error_rate,
                  status=503, retry_after=0.05),
        FaultRule(point="http.send", kind="latency", p=latency_rate,
                  latency_s=latency_spike_s),
    ]
    policy = RetryPolicy(seed=seed, base_delay=0.02, max_delay=0.5,
                         max_attempts=5)
    statuses: list[int] = []
    trace_ids: list[str] = []
    url = f"http://{server.address[0]}:{server.address[1]}/"
    try:
        with SpanCollector() as collector, faults(seed, rules) as inj:
            for w in workers:
                w.start()
            for i in range(n_requests):
                # client-side root span: the trace id every downstream
                # hop (ingest, lease, worker, reply) joins
                with tracer.span("client.request", i=i) as sp:
                    trace_ids.append(sp.trace_id)
                    resp = send_request(
                        HTTPRequestData(url=url, method="POST",
                                        headers={},
                                        entity=f"req-{i}".encode()),
                        timeout=request_timeout_s, policy=policy)
                statuses.append(resp.status_code)
            schedule = inj.schedule()
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=5)
        server.stop()
        driver.stop()
    # span-tree completeness per answered request (trace acceptance)
    names = collector.names_by_trace()
    answered_trees = {t: sorted(n for n in names.get(t, set()) if n)
                      for t, s in zip(trace_ids, statuses)
                      if 200 <= s < 300}
    complete = {t for t, ns in answered_trees.items()
                if COMPLETE_TRACE_SPANS <= set(ns)}
    sampled = None
    trace_path = None
    if complete:
        sample_id = sorted(complete)[0]
        sampled = {"trace_id": sample_id,
                   "spans": answered_trees[sample_id]}
        if trace_dir is not None:
            spans = [d for d in collector.spans()
                     if d.get("traceId") in complete]
            trace_path = _os.path.join(trace_dir, "chaos_trace.json")
            with open(trace_path, "w") as f:
                _json.dump(chrome_trace(spans, extra_metadata={
                    "scenario": "chaos", "seed": seed,
                    "sampled_trace_id": sample_id}), f)
    snap = _registry.snapshot()

    def _delta(prefix: str) -> float:
        return sum(v - snap_before.get(k, 0.0)
                   for k, v in snap.items() if k.startswith(prefix))

    answered = sum(1 for s in statuses if 200 <= s < 300)
    policy_sheds = sum(1 for s in statuses if s in (429, 503))
    return {
        "offered": n_requests,
        "answered_200": answered,
        "policy_sheds": policy_sheds,
        "answered_traces": len(answered_trees),
        "complete_traces": len(complete),
        "sampled_trace": sampled,
        "trace_path": trace_path,
        "transport_errors": sum(1 for s in statuses if s == 0),
        "non_policy_errors": sum(
            1 for s in statuses
            if not (200 <= s < 300) and s not in (429, 503)),
        "schedule": schedule,
        "retries_taken": _delta("resilience_retry_total"),
        "faults_injected": _delta("resilience_faults_injected_total"),
        "lease_replays": _delta("serving_lease_replays_total"),
        "worker_deaths_detected": _delta("resilience_worker_deaths_total"),
        "breaker_state_present": any(
            k.startswith("resilience_breaker_state") for k in snap),
        "retry_total_present": any(
            k.startswith("resilience_retry_total") for k in snap),
        "lease_replays_present": any(
            k.startswith("serving_lease_replays_total") for k in snap),
    }


# --------------------------------------------------- mixed-tenant elasticity
# one synthetic tenant per reference workload family: cognitive HTTP
# featurizers (small, latency-sensitive), LightGBM scoring (medium), and
# continuous generation (heavy, throughput-oriented). cost_s is the
# per-item service time the synthetic executors charge; base/swing shape
# the diurnal rate base + swing*(1-cos(2*pi*t/period))/2; burst
# multiplies the rate inside the mid-period burst window (the 2x
# overload the best-effort tier must absorb).
MIXED_TENANTS = {
    "cognitive": dict(tier="gold", cost_s=0.002, base=40.0, swing=80.0,
                      burst=1.0),
    "lightgbm": dict(tier="silver", cost_s=0.005, base=15.0, swing=30.0,
                     burst=1.0),
    "generate": dict(tier="best_effort", cost_s=0.010, base=10.0,
                     swing=30.0, burst=2.0),
}

_BURST_WINDOW = (0.35, 0.65)   # fraction of each period the burst covers


def _diurnal_rate(spec: dict, t: float, period_s: float) -> float:
    import math as _math
    phase = (t % period_s) / period_s
    r = spec["base"] + spec["swing"] * 0.5 * (
        1.0 - _math.cos(2.0 * _math.pi * phase))
    if spec.get("burst", 1.0) > 1.0 and \
            _BURST_WINDOW[0] <= phase <= _BURST_WINDOW[1]:
        r *= spec["burst"]
    return r


def _arrival_schedule(spec: dict, period_s: float,
                      duration_s: float) -> list[float]:
    """Deterministic arrival times for one tenant (pure function of the
    spec — two runs offer the identical request sequence, which is what
    makes the realized fault schedule a pure function of the seed)."""
    out = []
    t = 0.0
    while True:
        t += 1.0 / max(_diurnal_rate(spec, t, period_s), 1e-6)
        if t >= duration_s:
            return out
        out.append(t)


def _pctl(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return float("nan")
    return sorted_vals[max(_ceil(q * len(sorted_vals)) - 1, 0)]


def mixed_tenant_scenario(*, service: str = "tenant-bench",
                          seed: int = 23,
                          period_s: float = 2.5, periods: int = 2,
                          cooloff_s: float = 1.5,
                          max_queue: int = 128, max_batch: int = 8,
                          worker_max: int = 4,
                          gold_slo_s: float = 0.6,
                          silver_slo_s: float = 1.2,
                          be_rate_cap: float = 30.0,
                          utilization_floor: float = 0.15,
                          slow_factor: float = 3.0,
                          predictive: bool = False,
                          registry=None) -> dict:
    """Long-running mixed-workload elasticity acceptance (ISSUE 9).

    Three tenants — cognitive HTTP (gold), LightGBM scoring (silver),
    continuous generation (best-effort) — offer diurnal load into ONE
    tenancy-enabled :class:`~mmlspark_tpu.sched.RequestScheduler`
    (weighted-fair dispatch, tier deadlines, per-tenant quotas), drained
    by an autoscaled pool of synthetic workers while a seeded fault
    schedule runs: one worker killed mid-lease (its batch replayed via
    ``put_front`` — the lease-replay contract), one worker persistently
    degraded (``worker.slow``: sick-but-alive), 5%% injected 503s and
    latency spikes on the client hop. The best-effort tenant doubles its
    offered rate inside each period's burst window (the 2x overload).

    The contract measured (and returned as ``within_*`` flags so the
    test and the bench JSON assert the same surface):

    - **gold p99 <= its SLO tier deadline and ZERO gold sheds** while
      best-effort absorbs the burst as 429s (rate-quota + queue-share
      sheds with Retry-After from ITS bucket's refill time);
    - **silver p99 <= its SLO**;
    - the autoscaler's worker count **tracks the diurnal curve** (up at
      peak, back down after) and **never acts during cooldown**;
    - all in-flight work on killed/drained workers **completes via the
      replay path** — every admitted request reaches a terminal state;
    - **utilization stays above the floor** (busy seconds / alive
      worker seconds): elasticity, not over-provisioning.

    Reproducible by seed: arrivals are precomputed (pure function of
    the specs) and fault decisions are pure functions of per-rule probe
    counts, so two runs realize the same ``schedule`` (compared sorted:
    thread interleaving may reorder firings across points, never change
    them).

    ``predictive=True`` (ISSUE 12) arms the autoscaler's trend-
    extrapolated capacity prediction, priced by the scheduler
    estimator's (cost-model-backed) per-item service time — the result
    additionally reports ``scale_up_lag_s``, the gap between the
    offered load's diurnal rise and the first scale-up (smaller =
    the pool leads the curve), with the gold-tier contract unchanged.
    """
    import queue as _queue

    from ..obs.metrics import registry as _default
    from ..resilience import FaultRule, WorkerKilled, faults
    from ..resilience.faults import injector as _inj
    from ..sched import (RequestScheduler, Shed, Tenancy, TenantQuota)
    from ..serving.autoscale import Autoscaler, AutoscaleConfig

    reg = registry if registry is not None else _default
    duration_s = period_s * periods
    tenancy = Tenancy(
        service,
        quotas={
            "cognitive": TenantQuota(tier="gold"),
            "lightgbm": TenantQuota(tier="silver"),
            "generate": TenantQuota(tier="best_effort",
                                    rate=be_rate_cap,
                                    burst=max(be_rate_cap / 3.0, 1.0),
                                    queue_share=0.25),
        },
        tier_deadlines={"gold": gold_slo_s, "silver": silver_slo_s},
        registry=reg)
    sched = RequestScheduler(
        service, max_queue=max_queue, tenancy=tenancy, registry=reg,
        on_shed=lambda item, reason, retry_after: item.reply(429))
    # prime the estimator so predictive admission has a model from the
    # first request (same rationale as overload_scenario)
    sched.estimator.observe(1, 0.004)
    m_deaths = reg.counter(
        "resilience_worker_deaths_total",
        "workers marked dead by registry heartbeat liveness, by service")
    m_replays = reg.counter(
        "serving_lease_replays_total",
        "requests replayed because their lease expired (worker death)")

    class _Worker:
        __slots__ = ("thread", "stop", "draining", "killed", "busy_s",
                     "items", "started", "ended")

        def __init__(self):
            self.thread = None
            self.stop = threading.Event()
            self.draining = False
            self.killed = False
            self.busy_s = 0.0
            self.items = 0
            self.started = time.monotonic()
            self.ended = None

    class _Pool:
        """Synthetic autoscalable worker pool with the mesh's lease
        semantics: a worker holds a lease on its executing batch; a
        killed worker strands it; the monitor detects the death, counts
        it like the registry's failure detector, and replays unanswered
        items to the FRONT of the queue (put_front — the resilience
        contract). Drained workers finish and reply their batch first."""

        def __init__(self):
            self._lock = threading.Lock()
            self.workers: dict[str, _Worker] = {}
            self.leases: dict[str, list] = {}
            self.replays = 0
            self._seq = 0

        def count(self):
            with self._lock:
                return sum(1 for w in self.workers.values()
                           if w.thread.is_alive() and not w.draining
                           and not w.killed)

        def scale_up(self):
            with self._lock:
                wid = f"w{self._seq}"
                self._seq += 1
                w = _Worker()
                w.thread = threading.Thread(
                    target=self._run, args=(wid, w), daemon=True)
                self.workers[wid] = w
                w.thread.start()
            return wid

        def scale_down(self):
            with self._lock:
                live = [(w.started, wid) for wid, w in
                        self.workers.items()
                        if w.thread.is_alive() and not w.draining
                        and not w.killed]
                if not live:
                    return None
                _, wid = max(live)   # newest first (LIFO)
                self.workers[wid].draining = True
                self.workers[wid].stop.set()
            return wid

        def _run(self, wid, w):
            try:
                while not w.stop.is_set():
                    batch = sched.next_batch(max_batch=max_batch,
                                             max_wait=0.05)
                    if not batch:
                        continue
                    with self._lock:
                        self.leases[wid] = batch
                    # injection points mirror the real compute loop:
                    # a kill strands the lease; a slow rule arms the
                    # persistent sick-but-alive degradation
                    _inj.apply("worker.death", key=wid)
                    _inj.apply("worker.slow", key=wid)
                    cost = sum(i.cost for i in batch) \
                        * _inj.degradation(wid)
                    time.sleep(cost)
                    w.busy_s += cost
                    w.items += len(batch)
                    sched.estimator.observe(len(batch), cost)
                    for item in batch:
                        tenancy.observe_latency(
                            item.tenant,
                            time.monotonic() - item.submitted)
                        item.reply(200)
                    with self._lock:
                        self.leases.pop(wid, None)
            except WorkerKilled:
                w.killed = True   # lease stays: the monitor replays it
            finally:
                w.ended = time.monotonic()

        def monitor(self, stop_ev):
            """The failure detector + lease replayer (what the driver
            registry and ingest lease monitor do in the real mesh)."""
            while not stop_ev.wait(0.05):
                dead = []
                with self._lock:
                    for wid, w in self.workers.items():
                        if wid in self.leases and (
                                w.killed or not w.thread.is_alive()):
                            dead.append((wid, self.leases.pop(wid)))
                for wid, batch in dead:
                    m_deaths.inc(1, service=service)
                    for item in batch:
                        if item._event.is_set():
                            continue
                        self.replays += 1
                        m_replays.inc(1, service=service)
                        try:
                            sched.put_front(item)
                        except _queue.Full:
                            item.reply(503)

        def stop(self):
            with self._lock:
                ws = list(self.workers.values())
            for w in ws:
                w.stop.set()
            sched.wake()
            for w in ws:
                w.thread.join(timeout=5)
                if w.ended is None:
                    w.ended = time.monotonic()

    pool = _Pool()
    auto = Autoscaler(
        service, pool,
        AutoscaleConfig(min_workers=1, max_workers=worker_max,
                        interval=0.1, queue_high=6.0, queue_low=1.5,
                        slo_high=0.8, slo_low=0.4, up_stable=2,
                        down_stable=5, cooldown=0.6,
                        predictive=predictive, lead_ticks=5,
                        history_ticks=8, wait_high=0.25),
        registry=reg, tenancy=tenancy,
        item_seconds=sched.estimator.item_seconds)

    rules = [
        # one worker killed mid-lease: the SECOND worker the autoscaler
        # spawns, a few batches in (match targets its stable id)
        FaultRule(point="worker.death", kind="kill", match="w1",
                  after=4, times=1),
        # one worker persistently degraded from its 4th batch on: the
        # sick-but-alive case capacity planning must absorb
        FaultRule(point="worker.slow", kind="slow", match="w0",
                  after=3, times=1, factor=slow_factor),
        # client-hop chaos: 5% injected 503s + 5% latency spikes
        FaultRule(point="client.send", kind="error", p=0.05,
                  status=503, retry_after=0.05),
        FaultRule(point="client.send", kind="latency", p=0.05,
                  latency_s=0.02),
    ]

    class _TenantResult:
        __slots__ = ("requests", "intake_sheds", "retry_afters",
                     "injected_503")

        def __init__(self):
            self.requests = []
            self.intake_sheds = {}
            self.retry_afters = []
            self.injected_503 = 0

    results = {name: _TenantResult() for name in MIXED_TENANTS}
    arrivals = {name: _arrival_schedule(spec, period_s, duration_s)
                for name, spec in MIXED_TENANTS.items()}
    samples: list[tuple[float, int]] = []
    stop_all = threading.Event()
    t0 = time.monotonic()

    def load(name, spec, res):
        for t_rel in arrivals[name]:
            wait = (t0 + t_rel) - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            # the client hop's injection point: latency spikes sleep
            # here; an injected error is a client-visible 503 (counted,
            # not re-offered — re-offers would make the probe count
            # interleaving-dependent and break schedule reproducibility)
            act = _inj.apply("client.send", key=name)
            if act is not None and act.kind == "error":
                res.injected_503 += 1
                continue
            req = _SynthRequest()
            req.cost = spec["cost_s"]
            try:
                sched.submit(req, tenant=name)
                res.requests.append(req)
            except Shed as s:
                res.intake_sheds[s.reason] = \
                    res.intake_sheds.get(s.reason, 0) + 1
                res.retry_afters.append(s.retry_after)

    def sampler():
        while not stop_all.wait(0.05):
            samples.append((time.monotonic() - t0, pool.count()))

    with faults(seed, rules, inj=_inj) as inj:
        auto.start()
        mon = threading.Thread(target=pool.monitor, args=(stop_all,),
                               daemon=True)
        mon.start()
        smp = threading.Thread(target=sampler, daemon=True)
        smp.start()
        loaders = [threading.Thread(target=load, args=(n, s, results[n]),
                                    daemon=True)
                   for n, s in MIXED_TENANTS.items()]
        for th in loaders:
            th.start()
        for th in loaders:
            th.join(timeout=duration_s + 30)
        # drain: every admitted request must reach a terminal state
        # (reply, expiry shed, or replay-then-reply)
        drain_end = time.monotonic() + 10.0
        while time.monotonic() < drain_end:
            if sched.qsize() == 0 and not pool.leases:
                break
            time.sleep(0.05)
        # cool-off with zero offered load: the autoscaler must walk the
        # pool back down the diurnal curve
        time.sleep(cooloff_s)
        schedule = inj.schedule()
        stop_all.set()
        auto.stop()
        pool.stop()
        mon.join(timeout=5)
        smp.join(timeout=5)

    load_end = duration_s
    per_tenant = {}
    for name, res in results.items():
        lat = sorted((r.done_at - r.submitted) for r in res.requests
                     if r.status == 200 and r.done_at is not None)
        expired = sum(1 for r in res.requests if r.status == 429)
        unanswered = sum(1 for r in res.requests if r.status is None)
        sheds = dict(res.intake_sheds)
        if expired:
            sheds["expired"] = expired
        offered = len(arrivals[name])
        total_shed = sum(sheds.values())
        per_tenant[name] = {
            "tier": MIXED_TENANTS[name]["tier"],
            "offered": offered,
            "injected_503": res.injected_503,
            "answered_200": len(lat),
            "sheds": sheds,
            "shed_total": total_shed,
            "shed_rate": total_shed / max(offered, 1),
            "unanswered": unanswered,
            "p50_s": _pctl(lat, 0.50),
            "p99_s": _pctl(lat, 0.99),
            "retry_after_max": max(res.retry_afters, default=0),
        }

    # -- autoscale trajectory ------------------------------------------------
    events = auto.event_log()
    ups = [e for e in events if e.direction == "up"]
    downs = [e for e in events if e.direction == "down"]
    replaces = [e for e in events if e.direction == "replace"]
    acted = sorted([e for e in events if e.direction in ("up", "down")],
                   key=lambda e: e.t)
    cooldown_violations = sum(
        1 for a, b in zip(acted, acted[1:])
        if b.t - a.t < auto.config.cooldown - 0.01)
    in_peak = [c for t, c in samples
               if t < load_end
               and 0.3 <= (t % period_s) / period_s <= 0.8]
    peak_max = max(in_peak, default=0)
    final_count = samples[-1][1] if samples else 0

    # -- scale-up lead/lag vs the diurnal rise (ISSUE 12) --------------------
    # load-rise time: first instant the total offered rate crosses
    # halfway between its trough and peak (pure function of the specs —
    # comparable across runs); lag = first up-event minus that instant.
    # Smaller (or negative) = the pool LEADS the curve.
    grid = [i * 0.01 for i in range(int(period_s * 100) + 1)]
    totals = [sum(_diurnal_rate(spec, t, period_s)
                  for spec in MIXED_TENANTS.values()) for t in grid]
    rise_level = min(totals) + 0.5 * (max(totals) - min(totals))
    load_rise_s = next((t for t, r in zip(grid, totals)
                        if r >= rise_level), 0.0)
    first_up_s = min((e.t - t0 for e in ups), default=None)
    scale_up_lag_s = (first_up_s - load_rise_s
                      if first_up_s is not None else None)

    # -- utilization ---------------------------------------------------------
    busy = sum(w.busy_s for w in pool.workers.values())
    alive = sum((w.ended - w.started) for w in pool.workers.values()
                if w.ended is not None)
    utilization = busy / alive if alive > 0 else 0.0
    per_item = {wid: w.busy_s / w.items
                for wid, w in pool.workers.items() if w.items}
    healthy = [v for wid, v in per_item.items() if wid != "w0"]
    sick_ratio = (per_item.get("w0", 0.0)
                  / (sorted(healthy)[len(healthy) // 2]
                     if healthy else 1.0))

    gold = per_tenant["cognitive"]
    silver = per_tenant["lightgbm"]
    be = per_tenant["generate"]
    total_unanswered = sum(p["unanswered"] for p in per_tenant.values())
    return {
        "seed": seed,
        "period_s": period_s,
        "periods": periods,
        "per_tenant": per_tenant,
        "gold_p99_s": gold["p99_s"],
        "gold_slo_s": gold_slo_s,
        "gold_sheds": gold["shed_total"],
        "silver_p99_s": silver["p99_s"],
        "silver_slo_s": silver_slo_s,
        "be_sheds": be["shed_total"],
        "be_retry_after_max": be["retry_after_max"],
        "within_gold_slo": bool(gold["p99_s"] <= gold_slo_s
                                and gold["shed_total"] == 0),
        "within_silver_slo": bool(silver["p99_s"] <= silver_slo_s),
        "be_absorbed_burst": bool(be["shed_total"] > 0),
        "workers_peak": peak_max,
        "workers_final": final_count,
        "predictive": bool(predictive),
        "load_rise_s": load_rise_s,
        "first_up_s": first_up_s,
        "scale_up_lag_s": scale_up_lag_s,
        "autoscale_ups": len(ups),
        "autoscale_downs": len(downs),
        "autoscale_replaces": len(replaces),
        "cooldown_violations": cooldown_violations,
        "scaled_with_diurnal": bool(peak_max >= 2 and len(ups) >= 1
                                    and len(downs) >= 1
                                    and final_count < peak_max),
        "lease_replays": pool.replays,
        "worker_killed": any(p == "worker.death" for p, *_ in schedule),
        "worker_degraded": any(p == "worker.slow" for p, *_ in schedule),
        "sick_worker_cost_ratio": sick_ratio,
        "unanswered": total_unanswered,
        "drained_completed": bool(total_unanswered == 0),
        "utilization": utilization,
        "utilization_floor": utilization_floor,
        "within_utilization_floor": bool(utilization
                                         >= utilization_floor),
        "count_samples": samples,
        "schedule": sorted(schedule),
    }


# ----------------------------------------------------- fleet telemetry chaos
def fleet_chaos_scenario(*, service: str = "fleet-bench", seed: int = 31,
                         n_workers: int = 3, base_step_s: float = 0.01,
                         slow_factor: float = 6.0, wave_size: int = 6,
                         warmup_waves: int = 4, max_flag_waves: int = 40,
                         max_recover_s: float = 20.0,
                         request_timeout_s: float = 20.0) -> dict:
    """Fleet-plane chaos acceptance (ISSUE 15): a real worker mesh
    (driver registry, one ingest, ``n_workers`` in-thread compute
    workers whose transform sleeps ``base_step_s`` per batch — a
    deterministic service time the slow-factor stretch is visible
    against) driven in waves while the fleet health plane watches.

    The trajectory measured, phase by phase:

    1. **healthy warmup** — ``GET /healthz`` (via
       :meth:`~mmlspark_tpu.obs.fleet.FleetHealth.healthz_payload`, the
       exact body the route serves) answers ``ok``;
    2. **injected straggler** — a ``worker.slow`` rule arms a
       persistent ``slow_factor`` degradation on one worker; the
       scenario counts waves (one health tick per wave) until
       ``fleet_straggler{worker=...}`` flips — the detection latency —
       and the :class:`~mmlspark_tpu.serving.autoscale.Autoscaler`,
       ticked on the same cadence, must record a ``replace`` event
       sourced from the straggler signal (``reason="straggler
       flagged"``). Healthz now answers ``degraded`` (still HTTP 200:
       a slow fleet must not be drained by its load balancer);
    3. **replacement** — a ``worker.death`` kill takes the flagged
       worker mid-lease: the lease monitor detects, replays its
       stranded batch to survivors, and evicts its fleet source (the
       ``remove_matching`` sweep also clears its step series from the
       shared registry), after which the detector unflags and healthz
       returns to ``ok``.

    Tenant traffic rides along under a :class:`~mmlspark_tpu.sched.\
Tenancy` (gold ``search`` / best-effort ``batch``) so the burn-rate
    side of the verdict is live: gold takes zero sheds (burn 0, below
    the page threshold throughout — the acceptance bound) while one
    controlled ``batch`` shed keeps ``slo_burn_rate`` visibly nonzero
    without ever crossing the degraded threshold at the final tick.

    Scenario isolation: worker ids are per-scenario, so any
    worker-labelled step series / fleet sources lingering from earlier
    scenarios in this process are scrubbed first — the straggler
    median must only see THIS run's ranks. On exit the scenario evicts
    its own sources the same way.
    """
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from ..io.http.clients import send_request
    from ..io.http.schema import HTTPRequestData, HTTPResponseData
    from ..obs.fleet import FleetHealth, fleet_aggregator, parse_sample
    from ..obs.memory import device_memory_stats
    from ..obs.tracing import tracer
    from ..resilience import FaultRule, faults
    from ..sched import Shed, Tenancy, TenantQuota
    from ..serving import (DistributedServingServer, DriverRegistry,
                           remote_worker_loop)
    from ..serving.autoscale import Autoscaler, AutoscaleConfig

    # -- scenario isolation: scrub residue from earlier runs ----------------
    for src in list(fleet_aggregator.sources()):
        fleet_aggregator.evict(src, reason="scenario_reset")
    stale = {labels["worker"] for k in _registry.snapshot()
             for _, labels in (parse_sample(k),) if "worker" in labels}
    for prefix in ("profile_step_seconds", "fleet_"):
        for m in _registry.metrics(prefix):
            for w in stale:
                m.remove_matching(worker=w)

    def stepped(df):
        time.sleep(base_step_s)   # deterministic per-batch service time
        replies = np.empty(len(df), object)
        replies[:] = [HTTPResponseData(status_code=200,
                                       entity=(r.entity or b"").upper())
                      for r in df["request"]]
        return df.with_column("reply", replies)

    ten = Tenancy(service, quotas={
        "search": TenantQuota(tier="gold"),
        "batch": TenantQuota(tier="best_effort"),
    })
    health = FleetHealth(fleet_aggregator, service=service)
    health.attach_tenancy(ten)

    class _FakePool:
        """Synthetic capacity counter: the autoscaler's straggler path
        only needs count/scale_up (real pools are chaos_scenario's
        business)."""

        def __init__(self, n):
            self.n = n

        def count(self):
            return self.n

        def scale_up(self):
            self.n += 1
            return f"replacement-{self.n}"

        def scale_down(self):
            self.n -= 1

    pool = _FakePool(n_workers)
    auto = Autoscaler(
        service, pool,
        AutoscaleConfig(min_workers=n_workers, max_workers=n_workers + 2,
                        interval=0.05, queue_high=1e9, queue_low=-1.0,
                        slo_high=1e9, slo_low=-1.0, cooldown=0.0),
        registry=_registry, tenancy=ten)

    straggler_spans: list = []

    def _sink(sp):
        if sp.name == "fleet.straggler":
            straggler_spans.append(sp)

    wids = [f"fleet-w{i}" for i in range(n_workers)]
    w0 = wids[0]
    driver = DriverRegistry(heartbeat_timeout=0.75).start()
    server = DistributedServingServer(
        service, driver.address, lease_timeout=2.0, reply_timeout=15.0,
        load_report_interval=0.2).start()
    stops = [threading.Event() for _ in wids]
    workers = [threading.Thread(
        target=remote_worker_loop,
        args=(driver.address, service, stepped),
        kwargs={"stop_event": stops[i], "heartbeat_interval": 0.1,
                "max_batch": 4, "worker_id": wids[i]},
        daemon=True) for i in range(n_workers)]
    url = f"http://{server.address[0]}:{server.address[1]}/"
    pump = ThreadPoolExecutor(max_workers=wave_size)
    shed_at = wave_size * warmup_waves   # first post-baseline request
    statuses: list[int] = []
    sheds: dict = {}
    seq = [0]

    def send_wave(count, tenant_for=None):
        futs = []
        for _ in range(count):
            i = seq[0]
            seq[0] += 1
            tenant = tenant_for or ("batch" if i % 4 == 0 else "search")
            if i == shed_at:
                # ONE controlled best-effort shed: slo_burn_rate gets
                # a visible numerator without the trajectory depending
                # on quota timing
                ten.count_shed("batch", "tenant_rate")
                sheds["batch"] = sheds.get("batch", 0) + 1
                continue
            try:
                ten.try_admit(tenant, "/", 0, 128)
            except Shed as s:
                sheds[tenant] = sheds.get(tenant, 0) + 1
                sheds[s.reason] = sheds.get(s.reason, 0) + 1
                continue
            t0 = time.monotonic()
            futs.append((tenant, t0, pump.submit(
                send_request,
                HTTPRequestData(url=url, method="POST", headers={},
                                entity=f"req-{i}".encode()),
                timeout=request_timeout_s)))
        for tenant, t0, f in futs:
            resp = f.result()
            statuses.append(resp.status_code)
            ten.release(tenant)
            ten.observe_latency(tenant, time.monotonic() - t0)

    ticks_to_flag = None
    recovered = False
    recover_waves = 0
    evicted = False
    schedule_a: list = []
    schedule_b: list = []
    tracer.add_sink(_sink)
    try:
        for w in workers:
            w.start()
        # phase 1: healthy warmup → baseline tick → verdict must be ok
        for _ in range(warmup_waves):
            send_wave(wave_size)
        status_start, _ = health.healthz_payload()
        verdict_start = health.verdict()
        auto.tick()
        # phase 2: arm the persistent degradation; tick per wave until
        # the flag flips (detection latency, in waves)
        with faults(seed, [FaultRule(point="worker.slow", kind="slow",
                                     match=w0, times=1,
                                     factor=slow_factor)]) as inj:
            for t_i in range(max_flag_waves):
                send_wave(wave_size)
                health.tick()
                auto.tick()
                if ("worker", w0) in health.stragglers.flagged():
                    ticks_to_flag = t_i + 1
                    break
            schedule_a = inj.schedule()
            status_flag, _ = health.healthz_payload()
            verdict_flag = health.verdict()
            auto.tick()
        # phase 3: kill the flagged worker mid-lease — the real death
        # path replays its batch, evicts its fleet source, and the
        # remove_matching sweep clears its series; verdict walks home
        with faults(seed + 1, [FaultRule(point="worker.death",
                                         kind="kill", match=w0,
                                         times=1)]) as inj2:
            deadline = time.monotonic() + max_recover_s
            while time.monotonic() < deadline:
                send_wave(wave_size)
                recover_waves += 1
                health.tick()
                auto.tick()
                gone = f"worker:{w0}" not in fleet_aggregator.sources()
                if gone and ("worker", w0) not in \
                        health.stragglers.flagged():
                    recovered = True
                    break
            schedule_b = inj2.schedule()
        evicted = f"worker:{w0}" not in fleet_aggregator.sources()
        # settle: batch-heavy traffic bounds the final burn ratio
        # (1 shed / >20 admits) well under the degraded threshold
        for _ in range(2):
            send_wave(10, tenant_for="batch")
        status_end, _ = health.healthz_payload()
        verdict_end = health.verdict()
    finally:
        tracer.remove_sink(_sink)
        for ev in stops:
            ev.set()
        for w in workers:
            w.join(timeout=5)
        server.stop()
        driver.stop()
        pump.shutdown(wait=False)
        for wid in wids:
            fleet_aggregator.evict(f"worker:{wid}",
                                   reason="scenario_end")

    burns = health.burn.latest()
    gold_burn = max(burns.get("search", {}).values(), default=0.0)
    be_burn = max(burns.get("batch", {}).values(), default=0.0)
    replaces = [e for e in auto.event_log()
                if e.direction == "replace"
                and e.reason == "straggler flagged"]
    verdicts = [verdict_start, verdict_flag, verdict_end]
    return {
        "seed": seed,
        "workers": n_workers,
        "slow_worker": w0,
        "slow_factor": slow_factor,
        "offered": seq[0],
        "answered_200": sum(1 for s in statuses if 200 <= s < 300),
        "transport_errors": sum(1 for s in statuses if s == 0),
        "sheds": dict(sheds),
        "ticks_to_flag": ticks_to_flag,
        "flagged": bool(ticks_to_flag is not None),
        "straggler_spans": len(straggler_spans),
        "verdicts": verdicts,
        "healthz_statuses": [status_start, status_flag, status_end],
        "healthz_flipped": bool(verdicts == ["ok", "degraded", "ok"]),
        "straggler_replaces": len(replaces),
        "workers_after_replace": pool.count(),
        "recovered": recovered,
        "recover_waves": recover_waves,
        "evicted": evicted,
        "worker_degraded": any(p == "worker.slow"
                               for p, *_ in schedule_a),
        "worker_killed": any(p == "worker.death"
                             for p, *_ in schedule_b),
        "gold_burn": gold_burn,
        "be_burn": be_burn,
        "page_burn": health.page_burn,
        "gold_under_page": bool(gold_burn < health.page_burn),
        "hbm_devices": len(device_memory_stats()),
        "mem_gauges_present": any(k.startswith("mem_hbm_")
                                  for k in _registry.snapshot()),
    }


# --------------------------------------------------- whole-pipeline fusion
def _fusion_pipelines(n_rows: int, width: int, seed: int = 7):
    """The two benchmark pipelines of the whole-pipeline-compilation
    acceptance (ISSUE 10): a featurize→infer→postproc chain shaped like
    the image-featurizer serving path (dense feature matrix through a
    model head), and a text featurize→encoder chain whose tokenizer is
    genuinely host-bound (string ops split the fused span)."""
    import numpy as np
    import jax.numpy as jnp

    from ..core import DataFrame, PipelineModel
    from ..featurize import CleanMissingData, VectorAssembler
    from ..stages import SelectColumns, UDFTransformer

    rng = np.random.default_rng(seed)

    # -- featurizer pipeline: clean → assemble → model head → postproc
    feat_df = DataFrame({
        "img": rng.normal(size=(n_rows, width)).astype(np.float32),
        "aux": np.where(rng.random(n_rows) < 0.25, np.nan,
                        rng.normal(size=n_rows)).astype(np.float32),
    })
    w1 = jnp.asarray(rng.normal(size=(width + 1, 128)) * 0.05,
                     jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(128, 10)) * 0.05, jnp.float32)
    clean = CleanMissingData(inputCols=["aux"],
                             cleaningMode="Mean").fit(feat_df)
    feat_pm = PipelineModel([
        clean,
        VectorAssembler(inputCols=["img", "aux"], outputCol="features",
                        handleInvalid="keep"),
        UDFTransformer(inputCol="features", outputCol="logits",
                       jitSafe=True,
                       udf=lambda f: jnp.tanh(f @ w1) @ w2),
        UDFTransformer(inputCol="logits", outputCol="pred", jitSafe=True,
                       udf=lambda z: jnp.argmax(z, axis=-1)
                       .astype(jnp.float32)),
        SelectColumns(cols=["pred"]),
    ])

    # -- text pipeline: host tokenizer → embed+encode (BERT-shaped) → pool
    seq, vocab, dim = 16, 512, 64
    texts = np.empty(n_rows, object)
    texts[:] = [" ".join(rng.choice(["the", "cat", "sat", "on", "mat",
                                     "dog", "ran", "fast", "tpu", "jit"],
                                    size=8)) for _ in range(n_rows)]
    text_df = DataFrame({"text": texts})

    def tokenize(col):
        # genuinely host-bound: python string hashing per token
        ids = np.zeros((len(col), seq), np.int32)
        for i, s in enumerate(col):
            for j, tok in enumerate(str(s).split()[:seq]):
                ids[i, j] = (hash(tok) & 0x7FFFFFFF) % vocab
        return ids

    emb = jnp.asarray(rng.normal(size=(vocab, dim)) * 0.05, jnp.float32)
    wq = jnp.asarray(rng.normal(size=(dim, dim)) * 0.05, jnp.float32)
    wo = jnp.asarray(rng.normal(size=(dim, 8)) * 0.05, jnp.float32)

    def encode(ids):
        x = emb[ids]                     # [n, seq, dim]
        a = jnp.einsum("nsd,de,nte->nst", x, wq, x)
        a = a / jnp.sqrt(jnp.float32(dim))
        x = x + jnp.einsum("nst,ntd->nsd", a, x)
        return jnp.tanh(x.mean(axis=1) @ wo)   # [n, 8]

    text_pm = PipelineModel([
        UDFTransformer(inputCol="text", outputCol="ids", udf=tokenize),
        UDFTransformer(inputCol="ids", outputCol="enc", jitSafe=True,
                       udf=encode),
        UDFTransformer(inputCol="enc", outputCol="score", jitSafe=True,
                       udf=lambda e: e.sum(axis=-1)),
        SelectColumns(cols=["score"]),
    ])
    return (feat_pm, feat_df, "pred"), (text_pm, text_df, "score")


def _bench_pipeline(pm, df, out_col: str, reps: int) -> dict:
    """Median e2e latency of eager per-stage vs compiled execution, the
    fused path's dispatch count, and bit-equivalence of the outputs."""
    import numpy as np

    cp = pm.compile(df)
    eager_out = pm.transform(df)
    fused_out = cp.transform(df)        # warmup = the one compile
    diff = float(np.max(np.abs(
        np.asarray(eager_out[out_col], np.float32)
        - np.asarray(fused_out[out_col], np.float32)))) \
        if len(df) else 0.0

    def _median(fn) -> float:
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(df)
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    eager_s = _median(pm.transform)
    fused_s = _median(cp.transform)
    return {
        "eager_ms": eager_s * 1e3,
        "fused_ms": fused_s * 1e3,
        "speedup": eager_s / max(fused_s, 1e-9),
        "segments": cp.compiled_segments,
        "eager_stages_in_plan": cp.eager_stages,
        # device dispatches for the traced portion + host stages that
        # still run between segments — the per-request dispatch count
        "dispatches": cp.compiled_segments + cp.eager_stages,
        "max_abs_diff": diff,
        "equivalent": bool(diff <= 1e-5),
        "plan": cp.describe(),
    }


def pipeline_fusion_scenario(*, n_rows: int = 64, width: int = 64,
                             reps: int = 30) -> dict:
    """Fused vs per-stage pipeline execution (whole-pipeline XLA
    compilation acceptance): the featurizer pipeline must fuse into ≤ 2
    dispatches per request and run ≥ 3× faster end to end than eager
    per-stage execution, bit-equivalent within 1e-5."""
    feat, text = _fusion_pipelines(n_rows, width)
    feat_r = _bench_pipeline(*feat, reps=reps)
    text_r = _bench_pipeline(*text, reps=reps)
    return {
        "featurizer": feat_r,
        "text": text_r,
        "featurizer_fused_le_2_dispatches": bool(
            feat_r["dispatches"] <= 2),
        "featurizer_speedup_ge_3x": bool(feat_r["speedup"] >= 3.0),
        "all_equivalent": bool(feat_r["equivalent"]
                               and text_r["equivalent"]),
    }


# --------------------------------------------------------------------- AOT
def _aot_bench_spec(n_rows: int, width: int, seed: int = 9):
    """A deterministic, fully param-fingerprintable serving pipeline
    (no callable params — those are AOT-ineligible by design) shaped
    like the featurizer serving path: clean → one-hot → assemble."""
    import numpy as np

    from ..core import DataFrame
    from ..featurize import CleanMissingData, VectorAssembler
    from ..featurize.vector import OneHotEncoderModel

    rng = np.random.default_rng(seed)
    aux = rng.normal(size=n_rows).astype(np.float32)
    aux[::5] = np.nan
    df = DataFrame({
        "img": rng.normal(size=(n_rows, width)).astype(np.float32),
        "aux": aux,
        "cat": rng.integers(0, 8, size=n_rows).astype(np.int32),
    })
    clean = CleanMissingData(inputCols=["aux"],
                             cleaningMode="Median").fit(df)
    stages = [
        clean,
        OneHotEncoderModel(inputCol="cat", outputCol="onehot",
                           categorySize=8, handleInvalid="keep"),
        VectorAssembler(inputCols=["img", "aux", "onehot"],
                        outputCol="features", handleInvalid="keep"),
    ]
    return stages, df


def aot_scale_up_scenario(*, n_rows: int = 64, width: int = 48,
                          reps: int = 80, seed: int = 9,
                          store_root: str | None = None) -> dict:
    """AOT executable-store acceptance (ISSUE 11): an autoscaler-added
    worker's first request must be as fast as its thousandth.

    The scenario builds the store once (the build step), measures a
    warmed worker's steady-state latency, then compares two scale-up
    events — each a FRESH :class:`~..core.compile.CompiledPipeline`
    whose fused segments have cold jit caches, exactly what a new
    worker process has:

    - **cold** (today's behavior, store uninstalled): the first request
      pays the full XLA compile at request latency;
    - **warm** (the tentpole): a real :class:`~..serving.autoscale
      .Autoscaler` decision scales the pool up, the new worker
      warm-loads the store, ``CompileTracker.mark_steady()`` arms the
      zero-runtime-compile assertion, and the first request must land
      within 2× the steady-state p99 with ``profile_runtime_compiles
      _total == 0`` and ≥ 1 store hit.

    Outputs are checked bit-equal (atol 0) between the AOT-loaded and
    runtime-compiled executables — same XLA program, same bits.
    """
    import shutil
    import tempfile

    import numpy as np

    from ..core import aot, compile_pipeline
    from ..obs.metrics import registry as _reg
    from ..obs.profile import compile_tracker
    from ..serving.autoscale import (Autoscaler, AutoscaleConfig,
                                     AutoscaleSignals)

    stages, example = _aot_bench_spec(n_rows, width, seed)

    def fresh_worker():
        """A new worker process's pipeline: fresh FusedSegments, cold
        jit caches (jit keys on the body's identity)."""
        return compile_pipeline(stages, example, service="aot-bench")

    def _sum(prefix):
        return sum(v for k, v in _reg.snapshot().items()
                   if k.startswith(prefix))

    owns_root = store_root is None
    root = store_root or tempfile.mkdtemp(prefix="mmlspark_tpu_aotb_")
    prev_store = aot.active_store()
    try:
        store = aot.AotStore(root)
        # -- the build step -------------------------------------------
        t0 = time.perf_counter()
        build_cp = fresh_worker()
        build_records = aot.build_pipeline(build_cp, example, store)
        build_wall_s = time.perf_counter() - t0

        # -- steady-state worker --------------------------------------
        aot.install(store)
        steady_cp = fresh_worker()
        steady_cp.warm_aot()
        ref = steady_cp.transform(example)  # warmed; also the reference
        lats = []
        for _ in range(reps):
            t0 = time.perf_counter()
            steady_cp.transform(example)
            lats.append(time.perf_counter() - t0)
        lats.sort()
        steady_p99_s = _pctl(lats, 0.99)

        # -- cold scale-up (the "before" picture) ---------------------
        aot.uninstall()
        cold_cp = fresh_worker()
        t0 = time.perf_counter()
        cold_out = cold_cp.transform(example)
        cold_first_s = time.perf_counter() - t0

        # -- warm scale-up through a real autoscaler decision ---------
        aot.install(store)
        hits0, miss0 = _sum("aot_store_hit_total"), \
            _sum("aot_store_miss_total")

        class _Pool:
            def __init__(self):
                self.workers = []

            def count(self):
                return len(self.workers)

            def scale_up(self):
                cp = fresh_worker()
                warmed = cp.warm_aot()
                self.workers.append((cp, warmed))
                return f"w{len(self.workers) - 1}"

            def scale_down(self):
                return self.workers.pop()[0] if self.workers else None

        pool = _Pool()
        scaler = Autoscaler(
            "aot-bench", pool,
            AutoscaleConfig(min_workers=1, max_workers=4, up_stable=1,
                            cooldown=0.0))
        scaler.ensure_min()
        decision = scaler.tick(AutoscaleSignals(queue_depth=1e4))
        new_cp, warmed = pool.workers[-1]
        compile_tracker.mark_steady()
        t0 = time.perf_counter()
        warm_out = new_cp.transform(example)
        warm_first_s = time.perf_counter() - t0
        runtime_compiles = compile_tracker.runtime_compiles()
        runtime_compiled = compile_tracker.runtime_compiled()
        compile_tracker.unmark_steady()
        hits = _sum("aot_store_hit_total") - hits0
        misses = _sum("aot_store_miss_total") - miss0

        equivalent = all(
            np.asarray(ref[c]).shape == np.asarray(warm_out[c]).shape
            and np.array_equal(np.asarray(ref[c]),
                               np.asarray(warm_out[c]))
            and np.array_equal(np.asarray(ref[c]),
                               np.asarray(cold_out[c]))
            for c in ref.columns)
        return {
            "build_wall_s": build_wall_s,
            "build_segments": sum(1 for r in build_records
                                  if r.get("built")),
            "store_entries": store.stats()["entries"],
            "steady_p99_s": steady_p99_s,
            "cold_first_s": cold_first_s,
            "warm_first_s": warm_first_s,
            "cold_over_steady": cold_first_s / max(steady_p99_s, 1e-9),
            "warm_over_steady": warm_first_s / max(steady_p99_s, 1e-9),
            "scale_decision": decision,
            "worker_warm_loaded": int(warmed),
            "store_hits": float(hits),
            "store_misses": float(misses),
            "runtime_compiles": int(runtime_compiles),
            "runtime_compiled": runtime_compiled,
            "equivalent": bool(equivalent),
            "warm_within_2x_steady": bool(
                warm_first_s <= 2.0 * steady_p99_s),
            "zero_runtime_compiles": bool(runtime_compiles == 0),
            "warm_hit_ge_1": bool(hits >= 1),
        }
    finally:
        compile_tracker.unmark_steady()
        if prev_store is not None:
            aot.install(prev_store)
        else:
            aot.uninstall()
        if owns_root:
            shutil.rmtree(root, ignore_errors=True)


# ------------------------------------------------- learned cost model
def synth_feature_rows(n_rows: int = 1200, *, seed: int = 5,
                       service: str = "costmodel-bench") -> list[dict]:
    """Deterministic FeatureLog-shaped rows with a known cost
    structure: three routes whose execute time depends on the padding
    bucket AND the entity size — the per-request signal a per-bucket
    EWMA cannot see, which is exactly where the learned model earns its
    keep. Noise is seeded; two calls produce identical rows."""
    import numpy as np

    from ..obs.profile import FEATURE_SCHEMA_VERSION
    from ..sched.policy import bucket_of

    rng = np.random.default_rng(seed)
    # route -> (base_ms, per-padded-row ms, per-KB ms)
    routes = {"/feat": (0.8, 0.05, 0.030),
              "/gbdt": (2.0, 0.15, 0.004),
              "/gen": (5.0, 0.40, 0.012)}
    names = sorted(routes)
    rows = []
    for i in range(n_rows):
        route = names[int(rng.integers(0, len(names)))]
        base, per_row, per_kb = routes[route]
        batch = int(rng.integers(1, 65))
        bucket = bucket_of(batch)
        entity_kb = float(rng.uniform(0.5, 200.0))
        depth = float(max(rng.normal(8.0, 4.0), 0.0))
        ms = (base + per_row * bucket + per_kb * entity_kb
              + float(rng.normal(0.0, 0.15)))
        rows.append({
            "service": service, "route": route, "batch": batch,
            "bucket": bucket, "padded_batch": bucket,
            "entity_bytes": entity_kb * 1024.0, "queue_depth": depth,
            "queue_ms": depth * 0.5, "execute_ms": max(ms, 0.05),
            "schema_version": FEATURE_SCHEMA_VERSION,
            "platform": "synthetic",
        })
    return rows


def costmodel_scenario(*, n_rows: int = 1200, seed: int = 5,
                       holdout: float = 0.25, registry=None) -> dict:
    """Learned-cost-model acceptance (ISSUE 12): train on the first
    (1 - holdout) of a synthetic FeatureLog stream, score BOTH brains
    on the held-out tail — the model predicts per row (bucket + entity
    bytes + depth), the EWMA baseline is a ``ServiceTimeEstimator`` fed
    the same training stream in arrival order, exactly as the scheduler
    trains it today. Banked: both MAEs and ``model_beats_ewma``."""
    from ..obs.metrics import registry as _default
    from ..perf.costmodel import CostModel
    from ..sched.policy import ServiceTimeEstimator

    reg = registry if registry is not None else _default
    service = "costmodel-bench"
    rows = synth_feature_rows(n_rows, seed=seed, service=service)
    n_train = int(len(rows) * (1.0 - holdout))
    train, held = rows[:n_train], rows[n_train:]

    model = CostModel(min_rows=32, registry=reg)
    used = model.fit(train)

    ewma = ServiceTimeEstimator(service, registry=reg)
    for r in train:
        ewma.observe(r["batch"], r["execute_ms"] / 1e3)

    model_abs, ewma_abs = [], []
    for r in held:
        actual = r["execute_ms"]
        pred = model.predict_batch_ms(
            service, r["batch"], route=r["route"],
            entity_bytes=r["entity_bytes"],
            queue_depth=r["queue_depth"], count=False)
        if pred is not None:
            model_abs.append(abs(pred - actual))
        est = ewma.estimate(r["batch"])
        if est is not None:
            ewma_abs.append(abs(est * 1e3 - actual))
    model_mae = (sum(model_abs) / len(model_abs)
                 if model_abs else float("nan"))
    ewma_mae = (sum(ewma_abs) / len(ewma_abs)
                if ewma_abs else float("nan"))

    # the fallback gate, exercised: a cold model must answer None
    cold = CostModel(min_rows=32, registry=reg)
    cold_pred = cold.predict_batch_ms(service, 8)
    return {
        "n_train": len(train), "n_holdout": len(held),
        "rows_used": used,
        "model_mae_ms": model_mae,
        "ewma_mae_ms": ewma_mae,
        "model_beats_ewma": bool(model_mae < ewma_mae),
        "model_covered": len(model_abs),
        "cold_falls_back": bool(cold_pred is None),
    }


def autoscale_lead_scenario(*, ticks: int = 200, period_ticks: int = 100,
                            base_rate: float = 2.0, swing: float = 30.0,
                            drain_per_worker: float = 4.0,
                            lead_ticks: int = 6,
                            registry=None) -> dict:
    """Predictive-autoscaling lead/lag acceptance (ISSUE 12), fully
    deterministic: a simulated diurnal arrival rate feeds a backlog
    that a synthetic pool drains at ``drain_per_worker`` per tick; the
    SAME simulation drives a reactive and a predictive
    :class:`~..serving.autoscale.Autoscaler` tick by tick. The metric
    is ticks between the load rise (the first tick arrivals exceed the
    minimum pool's drain capacity — when backlog starts building) and
    the first scale-up. Predictive must fire no later than reactive,
    and earlier once the trend is visible — scale-up LEADS the curve
    instead of trailing it."""
    import math as _math

    from ..obs.metrics import registry as _default
    from ..serving.autoscale import (Autoscaler, AutoscaleConfig,
                                     AutoscaleSignals)

    reg = registry if registry is not None else _default

    def rate(i: int) -> float:
        phase = (i % period_ticks) / period_ticks
        return base_rate + swing * 0.5 * (
            1.0 - _math.cos(2.0 * _math.pi * phase))

    def run(predictive: bool) -> dict:
        class _Pool:
            n = 1

            def count(self):
                return self.n

            def scale_up(self):
                self.n += 1

            def scale_down(self):
                self.n -= 1

        pool = _Pool()
        auto = Autoscaler(
            f"lead-{'pred' if predictive else 'react'}", pool,
            AutoscaleConfig(min_workers=1, max_workers=8,
                            queue_high=8.0, queue_low=1.0,
                            up_stable=2, down_stable=10, cooldown=0.0,
                            predictive=predictive,
                            lead_ticks=lead_ticks, history_ticks=8),
            registry=reg)
        backlog = 0.0
        rise_tick = up_tick = None
        for i in range(ticks):
            r = rate(i)
            if rise_tick is None and r > drain_per_worker:
                rise_tick = i   # backlog starts building here
            backlog = max(backlog + r - pool.n * drain_per_worker, 0.0)
            decision = auto.tick(AutoscaleSignals(queue_depth=backlog))
            if decision == "up" and up_tick is None:
                up_tick = i
        return {"rise_tick": rise_tick, "up_tick": up_tick,
                "lag_ticks": (up_tick - rise_tick
                              if up_tick is not None
                              and rise_tick is not None else None)}

    react = run(False)
    pred = run(True)
    both = (react["lag_ticks"] is not None
            and pred["lag_ticks"] is not None)
    return {
        "reactive": react,
        "predictive": pred,
        "lag_reactive_ticks": react["lag_ticks"],
        "lag_predictive_ticks": pred["lag_ticks"],
        "predictive_leads": bool(
            both and pred["lag_ticks"] < react["lag_ticks"]),
    }


def recorder_overhead_scenario(*, service: str = "recorder-bench",
                               n_requests: int = 600,
                               item_service_s: float = 0.002,
                               max_batch: int = 8,
                               reps: int = 3,
                               record_interval_s: float = 1.0,
                               registry_gauges: int = 120,
                               registry=None) -> dict:
    """History-plane overhead guard (ISSUE 16): the same synthetic
    serving pipeline as :func:`tracing_overhead_scenario` (scheduler +
    deterministic executor, no HTTP socket) measured with the
    time-series :class:`~mmlspark_tpu.obs.timeseries.Recorder` thread
    OFF vs ON at its production cadence (1 s), over a registry
    pre-seeded with ``registry_gauges`` extra gauge series so the
    snapshot walks a production-scale sample surface.

    The 1%% verdict is NOT read off the end-to-end p99 delta — a 1%%
    effect (~30 us here) sits below the host's run-to-run p99 drift,
    so an e2e diff would be a coin flip (the tracing guard's 5%% bound
    is already at that noise floor). Instead the bound is decomposed
    into two precisely measurable parts, and the e2e OFF/ON p99s ride
    along as reported context only:

    * ``overhead_pct`` — the recorder's amortized per-request share of
      p99: median synchronous tick cost (timed directly, us
      precision) x ``interarrival / record_interval_s``, over the
      pipeline's best-of-``reps`` bare p99.
    * ``affected_fraction`` — the collision geometry: a tick delays at
      most ~2 in-flight requests, so
      ``2 * interarrival / record_interval_s`` of requests can feel a
      tick at all. Kept below the 1%% tail cut, a colliding tick
      cannot reach the p99 statistic — the p99 request is a
      non-collided one paying only the amortized share."""
    from ..obs.metrics import MetricsRegistry
    from ..obs.timeseries import Recorder, TimeSeriesStore
    from ..sched import RequestScheduler

    reg = registry if registry is not None else MetricsRegistry()
    pad = reg.gauge("profile_bench_pad",
                    "synthetic sample surface for the overhead guard")
    for i in range(max(int(registry_gauges), 0)):
        pad.set(float(i), idx=str(i))

    def one_run(recording: bool) -> float:
        sched = RequestScheduler(
            f"{service}-{'on' if recording else 'off'}", registry=reg)
        rec = None
        if recording:
            rec = Recorder(TimeSeriesStore(reg), reg)
            rec.start(record_interval_s)
        done: list[_SynthRequest] = []
        stop = threading.Event()

        def executor():
            while not stop.is_set() or sched.qsize():
                batch = sched.next_batch(max_batch=max_batch,
                                         max_wait=0.05)
                if not batch:
                    continue
                time.sleep(item_service_s * len(batch))
                for item in batch:
                    item.reply(200)
                    done.append(item)

        worker = threading.Thread(target=executor, daemon=True)
        worker.start()
        interval = item_service_s * 1.5
        try:
            for _ in range(n_requests):
                req = _SynthRequest()
                try:
                    sched.submit(req)
                except Exception:
                    req.reply(503)
                time.sleep(interval)
            stop.set()
            sched.wake()
            worker.join(timeout=20)
        finally:
            if rec is not None:
                rec.stop()
        lat = sorted((r.done_at - r.submitted) for r in done
                     if r.done_at is not None and r.status == 200)
        if not lat:
            return float("nan")
        return lat[max(_ceil(0.99 * len(lat)) - 1, 0)]

    offs, ons = [], []
    for _ in range(reps):
        offs.append(one_run(False))
        ons.append(one_run(True))
    p99_off, p99_on = min(offs), min(ons)

    costs = []
    probe = Recorder(TimeSeriesStore(reg), reg)
    for _ in range(50):
        t0 = time.perf_counter()
        probe.tick()
        costs.append(time.perf_counter() - t0)
    costs.sort()
    tick_cost_s = costs[len(costs) // 2]

    interarrival = item_service_s * 1.5
    amortized_s = tick_cost_s * interarrival / record_interval_s
    overhead_pct = amortized_s / p99_off * 100.0
    affected_fraction = 2.0 * interarrival / record_interval_s
    return {
        "n_requests": n_requests,
        "item_service_s": item_service_s,
        "reps": reps,
        "record_interval_s": record_interval_s,
        "registry_gauges": registry_gauges,
        "p99_off_s": p99_off,
        "p99_on_s": p99_on,
        "tick_cost_s": tick_cost_s,
        "amortized_per_request_s": amortized_s,
        "affected_fraction": affected_fraction,
        "overhead_pct": overhead_pct,
        "bound_pct": 1.0,
        "within_bound": (overhead_pct <= 1.0
                         and affected_fraction <= 0.01),
    }


def regression_chaos_scenario(*, service: str = "regression-bench",
                              seed: int = 23, chaos: bool = True,
                              warmup: int = 8, inject_after: int = 12,
                              max_ticks: int = 40,
                              base_step_s: float = 0.010,
                              slow_factor: float = 6.0,
                              sustain_ticks: int = 3) -> dict:
    """Live perf-regression acceptance (ISSUE 16): a seeded synthetic
    training loop exports ``profile_mfu`` each tick; the recorder
    samples it into a private store and the CUSUM sentinel watches.
    With ``chaos=True`` a ``worker.slow`` fault (the resilience
    plane's persistent-degradation path, ``factor=slow_factor``) arms
    after ``inject_after`` ticks — MFU steps down by that factor and
    the sentinel must flip ``obs_regression_active{series=
    profile_mfu}`` within 20 recorder ticks of the step, after which
    ``FleetHealth`` (sentinel attached) reads DEGRADED. With
    ``chaos=False`` the identical replay must alarm exactly never —
    the detector is a pure fold over the value sequence, so the
    healthy trajectory is bit-identical run to run."""
    from ..obs.fleet import FleetAggregator, FleetHealth
    from ..obs.metrics import MetricsRegistry
    from ..obs.regression import RegressionSentinel, SeriesWatch, _pull_mfu
    from ..obs.timeseries import Recorder, TimeSeriesStore
    from ..resilience import FaultRule, faults

    reg = MetricsRegistry()
    store = TimeSeriesStore(reg)
    recorder = Recorder(store, reg)
    sent = RegressionSentinel(store, reg, watches=[
        SeriesWatch("profile_mfu", _pull_mfu, direction="lower_bad",
                    warmup=warmup)], sustain_ticks=sustain_ticks)
    health = FleetHealth(FleetAggregator(reg), registry=reg,
                         service=service, store=store)
    health.attach_sentinel(sent)
    g_mfu = reg.gauge("profile_mfu", "model FLOP utilization, by stage")
    from ..obs.attribution import peak_spec
    peak_flops = peak_spec("cpu").peak_flops   # the 1 Tflop/s cpu row
    flops_per_step = base_step_s * peak_flops * 0.42   # healthy MFU 0.42

    rules = []
    if chaos:
        rules = [FaultRule(point="worker.slow", kind="slow",
                           match="trainer", times=1, after=inject_after,
                           factor=slow_factor)]
    step_at = None
    alarm_tick = None
    degraded_tick = None
    events = 0
    mfu_trace: list = []
    with faults(seed, rules):
        from ..resilience.faults import injector
        for t in range(max_ticks):
            injector.apply("worker.slow", "trainer")
            slow = injector.degradation("trainer")
            if slow > 1.0 and step_at is None:
                step_at = t
            step_s = base_step_s * slow
            mfu = flops_per_step / (peak_flops * step_s)
            mfu_trace.append(round(mfu, 4))
            g_mfu.set(mfu, stage="train")
            recorder.tick()
            active = sent.tick()
            verdict = health.tick()
            if active and alarm_tick is None:
                alarm_tick = t
            if verdict == "degraded" and degraded_tick is None:
                degraded_tick = t
            if alarm_tick is not None and degraded_tick is not None \
                    and t >= alarm_tick + sustain_ticks:
                break
        snap = reg.snapshot()
        events = int(sum(v for k, v in snap.items()
                         if k.startswith("obs_regression_events_total")))
    return {
        "chaos": chaos,
        "seed": seed,
        "mfu_healthy": mfu_trace[0] if mfu_trace else None,
        "mfu_degraded": mfu_trace[-1] if mfu_trace else None,
        "step_at_tick": step_at,
        "alarm_tick": alarm_tick,
        "ticks_to_alarm": (alarm_tick - step_at
                           if alarm_tick is not None and step_at is not None
                           else None),
        "degraded_tick": degraded_tick,
        "events": events,
        "verdict_end": health.verdict(),
        "mfu_trace": mfu_trace,
    }


def llm_serving_scenario(*, service: str = "llm-bench", slots: int = 2,
                         block_len: int = 4, spec_k: int = 0,
                         n_prompts: int = 4, prompt_len: int = 12,
                         max_new_tokens: int = 6, vocab: int = 64,
                         seed: int = 17, registry=None) -> dict:
    """Generation benchmark for the LLM serving engine (ISSUE 17
    acceptance): warm a tiny causal LM's prefill+decode programs, serve
    a repeated-prefix workload through
    :class:`~mmlspark_tpu.serving.llm.LLMEngine`, and read the
    ``gen_*``/``kv_*`` series back from the obs registry.

    Three rounds over the SAME ``n_prompts`` prompts (shared
    ``block_len``-aligned prefix, distinct tails). Rounds 1-2 submit
    one sequence at a time and drain — TTFT is pure prefill, no
    slot-queue wait folded in: round 1 prefills cold, round 2 must hit
    the refcounted prefix cache, and the quantile split by the
    ``reuse`` label separates ``ttft_cold_p50_ms`` from
    ``ttft_warm_p50_ms`` (the measured TTFT improvement the paged
    cache exists to buy — a full-prompt hit prefills a 1-token
    suffix). TTFT quantiles are read BEFORE round 3 — the batched
    throughput round (all prompts at once, continuous batching), whose
    queue waits would otherwise pollute the warm column — which is
    what ``tokens_per_s`` measures. The whole serving run executes
    inside CompileTracker steady state, so a single runtime compile on
    a warmed worker fails the scenario rather than hiding in the
    latency columns.

    Returns tokens/sec, TTFT percentiles (registry
    ``gen_ttft_seconds`` quantiles split by the ``reuse`` label),
    prefix hit rate, spec-acceptance ratio (``spec_k > 0``), AOT
    fingerprint count, and the per-sequence outputs — callers bank the
    numbers and tests assert on either surface.
    """
    import jax.numpy as jnp
    import numpy as np

    from ..dl import MaskedLMModel, TextEncoder
    from ..dl.text_encoder import make_attention_fn
    from ..obs.metrics import registry as _default
    from ..obs.profile import compile_tracker
    from ..serving.llm import LLMEngine, _bucket_window

    import jax

    reg = registry if registry is not None else _default
    enc = TextEncoder(vocab=vocab, width=32, depth=1, heads=2,
                      mlp_dim=64, dtype=jnp.float32,
                      attention_fn=make_attention_fn("dense",
                                                     causal=True))
    module = MaskedLMModel(encoder=enc)
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 8), np.int32))
    rng = np.random.default_rng(seed)
    # shared prefix covering whole blocks (reuse is whole-chunk only),
    # distinct per-prompt tails
    shared = rng.integers(2, vocab, size=prompt_len - block_len)
    prompts = [list(map(int, np.concatenate(
        [shared, rng.integers(2, vocab, size=block_len)])))
        for _ in range(n_prompts)]

    engine = LLMEngine(
        module, variables,
        draft_module=module if spec_k else None,
        draft_variables=variables if spec_k else None,
        slots=slots, block_len=block_len,
        max_seq_len=prompt_len + max_new_tokens + block_len,
        spec_k=spec_k, service=service, registry=reg)
    windows = sorted({_bucket_window(len(p)) for p in prompts}
                     | {_bucket_window(block_len)} | {1})
    fps = engine.warm(prefill_windows=tuple(windows), mark_steady=True)
    try:
        outputs = {}
        # rounds 1-2: one sequence in flight at a time, so the TTFT
        # histogram holds pure submit→prefill→first-token latencies
        for rnd, reuse in ((0, "cold"), (1, "warm")):
            for i, p in enumerate(prompts):
                engine.submit(f"r{rnd}-s{i}", p, max_new_tokens)
                outputs.update(engine.run_until_drained())
        h = reg.metrics("gen_ttft_seconds")[0]
        ttft_ms = {
            "ttft_cold_p50_ms": h.quantile(0.5, service=service,
                                           reuse="cold") * 1e3,
            "ttft_warm_p50_ms": h.quantile(0.5, service=service,
                                           reuse="warm") * 1e3,
            "ttft_p99_ms": max(h.quantile(0.99, service=service,
                                          reuse=r) for r in
                               ("cold", "warm")) * 1e3,
        }
        # round 3: everything at once — continuous batching throughput
        t0 = time.monotonic()
        for i, p in enumerate(prompts):
            engine.submit(f"rt-s{i}", p, max_new_tokens)
        batch_out = engine.run_until_drained()
        wall_s = time.monotonic() - t0
        outputs.update(batch_out)
        compile_tracker.assert_steady_state()
        steady_ok = True
    finally:
        compile_tracker.unmark_steady()

    kv = engine.kv.stats()
    snap = reg.snapshot()

    def _sum(prefix: str) -> float:
        return sum(v for k, v in snap.items()
                   if k.startswith(prefix)
                   and f'service="{service}"' in k)

    hits = _sum("kv_prefix_hits_total")
    misses = _sum("kv_prefix_misses_total")
    # throughput counts round 3's committed tokens (decode commits plus
    # the prefill-produced first token per sequence) over round 3 wall
    batch_tokens = sum(len(v) for v in batch_out.values()) \
        - sum(len(p) for p in prompts)
    gen_tokens = int(_sum("gen_tokens_total")) \
        + len(outputs)   # + the prefill-produced first tokens
    return {
        "sequences": len(outputs),
        "gen_tokens": gen_tokens,
        "wall_s": wall_s,
        "tokens_per_s": batch_tokens / max(wall_s, 1e-9),
        **ttft_ms,
        "prefix_hits": int(hits),
        "prefix_misses": int(misses),
        "prefix_hit_rate": hits / max(hits + misses, 1),
        "tokens_reused": int(_sum("kv_prefix_tokens_reused_total")),
        "spec_accept_ratio": _sum("gen_spec_accept_ratio")
        if spec_k else None,
        "decode_steps": int(_sum("gen_decode_steps_total")),
        "kv_blocks": kv["blocks"],
        "kv_cached": kv["cached"],
        "aot_fingerprints": len(fps),
        "steady_state_ok": steady_ok,
        "outputs": {k: [int(t) for t in v] for k, v in outputs.items()},
    }


def llm_decode_scenario(*, service: str = "llm-decode-bench",
                        context_tokens: int = 4096,
                        block_len: int = 128,
                        max_new_tokens: int = 32, slots: int = 1,
                        vocab: int = 64, seed: int = 23,
                        registry=None) -> dict:
    """Long-context decode-throughput bench (ISSUE 18 acceptance):
    steady-state tokens/sec of the decode executor at ``context_tokens``
    of resident KV — the regime the paged-attention kernel exists for,
    where the old path re-gathered the whole dense cache every step.

    One sequence fills ``context_tokens - max_new_tokens`` prompt
    tokens, then the timed window covers ONLY the drained decode steps
    (the first engine boundary — prefill + first decode step — runs
    before the clock starts, so prefill cost never pollutes the decode
    number). Runs inside CompileTracker steady state: a runtime compile
    mid-decode fails the scenario. The path's identity rides along in
    the numbers — ``dense_gather_bytes`` is exactly 0 on the paged
    path and the old path's per-step re-gather total behind
    ``MMLSPARK_TPU_PAGED_ATTN=0`` — so the side-by-side bank
    (``bench_llm_decode``) can prove which kernel produced which
    column."""
    import jax.numpy as jnp
    import numpy as np

    from ..dl import MaskedLMModel, TextEncoder
    from ..dl.paged_kv import paged_attention_enabled
    from ..dl.text_encoder import make_attention_fn
    from ..obs.metrics import registry as _default
    from ..obs.profile import compile_tracker
    from ..serving.llm import LLMEngine, _bucket_window

    import jax

    reg = registry if registry is not None else _default
    enc = TextEncoder(vocab=vocab, width=32, depth=1, heads=2,
                      mlp_dim=64, dtype=jnp.float32,
                      attention_fn=make_attention_fn("dense",
                                                     causal=True))
    module = MaskedLMModel(encoder=enc)
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 8), np.int32))
    rng = np.random.default_rng(seed)
    prompt_len = int(context_tokens) - int(max_new_tokens)
    prompt = [int(t) for t in rng.integers(2, vocab, size=prompt_len)]

    engine = LLMEngine(module, variables, slots=slots,
                       block_len=block_len, max_seq_len=context_tokens,
                       service=service, registry=reg)
    windows = sorted({_bucket_window(prompt_len), 1})
    fps = engine.warm(prefill_windows=tuple(windows), mark_steady=True)
    try:
        engine.submit("ctx0", prompt, max_new_tokens)
        engine.step()            # admit + prefill + first decode step
        snap0 = reg.snapshot()

        def _sum(snapshot, prefix):
            return sum(v for k, v in snapshot.items()
                       if k.startswith(prefix)
                       and f'service="{service}"' in k)

        tok0 = _sum(snap0, "gen_tokens_total")
        t0 = time.monotonic()
        outputs = engine.run_until_drained()
        decode_wall_s = time.monotonic() - t0
        compile_tracker.assert_steady_state()
        steady_ok = True
    finally:
        compile_tracker.unmark_steady()

    snap = reg.snapshot()
    decode_tokens = _sum(snap, "gen_tokens_total") - tok0
    gather_bytes = _sum(snap, "kv_dense_gather_bytes_total")
    attn_decode_s = sum(
        v for k, v in snap.items()
        if k.startswith("gen_decode_attn_seconds_sum")
        and f'service="{service}"' in k and 'phase="decode"' in k)
    steps = _sum(snap, "gen_decode_steps_total")
    return {
        "context_tokens": int(context_tokens),
        "context_blocks": -(-int(context_tokens) // int(block_len)),
        "paged_attention": bool(paged_attention_enabled()),
        "decode_tokens": int(decode_tokens),
        "decode_wall_s": decode_wall_s,
        "tokens_per_s": decode_tokens / max(decode_wall_s, 1e-9),
        "dense_gather_bytes": int(gather_bytes),
        "attn_ms_per_step": (attn_decode_s / max(steps, 1)) * 1e3,
        "decode_steps": int(steps),
        "aot_fingerprints": len(fps),
        "steady_state_ok": steady_ok,
        "outputs": {k: [int(t) for t in v] for k, v in outputs.items()},
    }


# ------------------------------------------------- zero-downtime deploy
def rollout_scenario(*, service: str = "rollout-bench", seed: int = 29,
                     period_s: float = 2.0, periods: int = 2,
                     max_queue: int = 128, max_batch: int = 8,
                     worker_max: int = 4,
                     canary_share: float = 0.25,
                     stage_at: float = 0.25, flip_at: float = 0.40,
                     canary_at: float = 0.55,
                     bad_batches: int = 1,
                     gold_slo_s: float = 0.6, silver_slo_s: float = 1.2,
                     burn_windows: dict | None = None,
                     tick_s: float = 0.05,
                     max_rollback_ticks: int = 80,
                     registry=None) -> dict:
    """Zero-downtime model-lifecycle acceptance (ISSUE 19).

    The mixed-tenant fleet from :func:`mixed_tenant_scenario` — diurnal
    gold/silver/best-effort load into one tenancy-enabled scheduler,
    drained by an autoscaled synthetic worker pool with the mesh's
    lease-replay semantics — while a model update rolls through the
    deploy plane's full lifecycle:

    1. **Blue/green flip under load.** ``v1`` serves; ``v2`` is
       registered, warmed and staged beside it, then promoted by ONE
       :meth:`~mmlspark_tpu.serving.VersionRouter.flip` mid-load while
       a seeded ``worker.death`` kills a worker holding a lease.
       Contract: zero non-canary 5xx, zero dropped admitted requests
       (kill included — the replay path completes them), every request
       answered **byte-identically by the version that admitted it**
       (pre-flip admissions complete on draining ``v1``), and
       ``deploy_draining_inflight`` reaches 0.
    2. **Seeded-bad canary auto-rollback.** ``v3`` is staged with a
       canary slice; a seeded ``model.bad`` rule makes it answer
       injected 500s. Those 500s land on the CANARY tenant's error
       budget (the router re-tenants the slice), the
       :class:`~mmlspark_tpu.obs.fleet.BurnRateMonitor` sees the burn,
       and the :class:`~mmlspark_tpu.serving.RolloutController` rolls
       back from burn rate alone — within a bounded number of ticks,
       with zero gold-tier sheds or 5xx (the blast radius IS the
       slice).

    Runs inside CompileTracker steady state end to end: the deploy
    plane itself (register/warm/stage/flip/rollback) must never
    trigger a runtime compile.

    Reproducible by seed: arrivals are precomputed pure functions of
    the tenant specs; the ``worker.death`` rule fires at a fixed
    matching-probe count and the ``model.bad`` rule is bounded to
    ``bad_batches`` firings (probes 1..N always fire) — so two runs
    realize the identical sorted ``schedule`` even though thread
    interleaving decides WHICH admissions land in the canary slice.
    """
    import queue as _queue

    from ..obs.fleet import BurnRateMonitor
    from ..obs.metrics import registry as _default
    from ..obs.profile import compile_tracker
    from ..resilience import FaultRule, WorkerKilled, faults
    from ..resilience.faults import injector as _inj
    from ..sched import RequestScheduler, Shed, Tenancy, TenantQuota
    from ..serving.autoscale import Autoscaler, AutoscaleConfig
    from ..serving.deploy import (ModelRegistry, RolloutConfig,
                                  RolloutController, VersionRouter)

    reg = registry if registry is not None else _default
    duration_s = period_s * periods
    tenancy = Tenancy(
        service,
        quotas={
            "cognitive": TenantQuota(tier="gold"),
            "lightgbm": TenantQuota(tier="silver"),
            "generate": TenantQuota(tier="best_effort", rate=30.0,
                                    burst=10.0, queue_share=0.25),
            # the canary slice's OWN budget bucket: injected 5xx burn
            # here, never on the gold tier the request arrived under
            "canary": TenantQuota(tier="silver"),
        },
        tier_deadlines={"gold": gold_slo_s, "silver": silver_slo_s},
        registry=reg)
    sched = RequestScheduler(
        service, max_queue=max_queue, tenancy=tenancy, registry=reg,
        on_shed=lambda item, reason, retry_after: item.reply(429))
    sched.estimator.observe(1, 0.004)
    m_t5 = reg.counter(
        "serving_tenant_requests_total",
        "requests answered, by service/tenant/status code")

    # -- the deploy plane ----------------------------------------------
    def _make_model(name: str):
        def fn(payload: bytes) -> bytes:
            return name.encode() + b":" + payload
        return fn

    mreg = ModelRegistry(service=service, registry=reg)
    router = VersionRouter(mreg, service=service, canary_tenant="canary",
                           metrics=reg)
    mreg.register("v1", transform=_make_model("v1"))
    router.set_active("v1")

    monitor = BurnRateMonitor(
        registry=reg, service=service,
        windows=dict(burn_windows) if burn_windows
        else {"fast": 0.5, "slow": 1.5},
        budget_for=tenancy.error_budget_for)
    ctl = RolloutController(
        router, burn=monitor, metrics=reg,
        config=RolloutConfig(interval=tick_s, burn_threshold=2.0,
                             slow_threshold=1.0, rollback_windows=2,
                             promote_windows=10 ** 6, cooldown=1.0,
                             flap_s=1.0))

    class _DeployRequest(_SynthRequest):
        """Carries the admission-stamped version and releases its
        router inflight slot on the first terminal reply — the same
        exactly-once contract ``_finish_request`` wires for real
        serving (the scheduler owns ``on_done`` for admission
        accounting, so the release can't ride there)."""

        __slots__ = ("version", "assigned_tenant", "payload", "result")

        def __init__(self):
            super().__init__()
            self.version = ""
            self.assigned_tenant = ""
            self.payload = b""
            self.result = None

        def reply(self, status):
            first = super().reply(status)
            if first and self.version:
                router.release(self.version)
            return first

    class _Worker:
        __slots__ = ("thread", "stop", "draining", "killed", "busy_s",
                     "items", "started", "ended")

        def __init__(self):
            self.thread = None
            self.stop = threading.Event()
            self.draining = False
            self.killed = False
            self.busy_s = 0.0
            self.items = 0
            self.started = time.monotonic()
            self.ended = None

    class _Pool:
        """mixed_tenant_scenario's lease-replay pool, version-aware:
        the executor groups each batch by the version stamped at
        admission (the serving executor's ``_transform_groups``
        contract) and probes ``model.bad`` once per version group."""

        def __init__(self):
            self._lock = threading.Lock()
            self.workers: dict[str, _Worker] = {}
            self.leases: dict[str, list] = {}
            self.replays = 0
            self._seq = 0

        def count(self):
            with self._lock:
                return sum(1 for w in self.workers.values()
                           if w.thread.is_alive() and not w.draining
                           and not w.killed)

        def scale_up(self):
            with self._lock:
                wid = f"w{self._seq}"
                self._seq += 1
                w = _Worker()
                w.thread = threading.Thread(
                    target=self._run, args=(wid, w), daemon=True)
                self.workers[wid] = w
                w.thread.start()
            return wid

        def scale_down(self):
            with self._lock:
                live = [(w.started, wid) for wid, w in
                        self.workers.items()
                        if w.thread.is_alive() and not w.draining
                        and not w.killed]
                if not live:
                    return None
                _, wid = max(live)
                self.workers[wid].draining = True
                self.workers[wid].stop.set()
            return wid

        def _run(self, wid, w):
            try:
                while not w.stop.is_set():
                    batch = sched.next_batch(max_batch=max_batch,
                                             max_wait=0.05)
                    if not batch:
                        continue
                    with self._lock:
                        self.leases[wid] = batch
                    _inj.apply("worker.death", key=wid)
                    _inj.apply("worker.slow", key=wid)
                    cost = sum(i.cost for i in batch) \
                        * _inj.degradation(wid)
                    time.sleep(cost)
                    w.busy_s += cost
                    w.items += len(batch)
                    sched.estimator.observe(len(batch), cost)
                    groups: dict[str, list] = {}
                    for item in batch:
                        groups.setdefault(item.version, []).append(item)
                    for ver, members in groups.items():
                        act = _inj.apply("model.bad", key=ver) \
                            if ver else None
                        if act is not None and act.kind == "error":
                            for item in members:
                                # mirror _finish_request's per-tenant
                                # status counting: the burn monitor
                                # reads 5xx from this family
                                m_t5.inc(1, service=service,
                                         tenant=item.assigned_tenant,
                                         code=str(act.status))
                                item.reply(act.status)
                            continue
                        fn = router.transform_for(ver)
                        for item in members:
                            out = fn(item.payload) if fn is not None \
                                else bytes(item.payload)
                            if act is not None and act.kind == "corrupt":
                                out = bytes(b ^ 0xFF for b in out)
                            item.result = out
                            tenancy.observe_latency(
                                item.assigned_tenant,
                                time.monotonic() - item.submitted)
                            item.reply(200)
                    with self._lock:
                        self.leases.pop(wid, None)
            except WorkerKilled:
                w.killed = True
            finally:
                w.ended = time.monotonic()

        def monitor(self, stop_ev):
            while not stop_ev.wait(0.05):
                dead = []
                with self._lock:
                    for wid, w in self.workers.items():
                        if wid in self.leases and (
                                w.killed or not w.thread.is_alive()):
                            dead.append((wid, self.leases.pop(wid)))
                for wid, batch in dead:
                    for item in batch:
                        if item._event.is_set():
                            continue
                        self.replays += 1
                        try:
                            sched.put_front(item)
                        except _queue.Full:
                            item.reply(503)

        def stop(self):
            with self._lock:
                ws = list(self.workers.values())
            for w in ws:
                w.stop.set()
            sched.wake()
            for w in ws:
                w.thread.join(timeout=5)
                if w.ended is None:
                    w.ended = time.monotonic()

    pool = _Pool()
    auto = Autoscaler(
        service, pool,
        AutoscaleConfig(min_workers=2, max_workers=worker_max,
                        interval=0.1, queue_high=6.0, queue_low=1.5,
                        slo_high=0.8, slo_low=0.4, up_stable=2,
                        down_stable=5, cooldown=0.6),
        registry=reg, tenancy=tenancy,
        item_seconds=sched.estimator.item_seconds)

    rules = [
        # the flip-under-chaos worker: killed mid-lease once it is
        # deep into the run (~the flip window, at this fleet's batch
        # rate) — the replayed batch must still complete on whatever
        # version each request was ADMITTED under
        FaultRule(point="worker.death", kind="kill", match="w1",
                  after=60, times=1),
        # a persistently sick first worker: builds the queue pressure
        # that makes the autoscaler spawn w1 (same dynamics as
        # mixed_tenant_scenario, which this fleet is)
        FaultRule(point="worker.slow", kind="slow", match="w0",
                  after=3, times=1, factor=3.0),
        # the bad canary: v3 answers injected 500s. Bounded to
        # bad_batches firings so the realized schedule is identical
        # across same-seed runs (probes 1..N always fire; batching
        # jitter only moves WHEN probe N happens, never whether —
        # and one bad batch keeps the fast burn window hot long
        # enough for the rollback streak, so the default is 1)
        FaultRule(point="model.bad", kind="error", match="v3",
                  status=500, times=bad_batches),
    ]

    class _TenantResult:
        __slots__ = ("requests", "intake_sheds")

        def __init__(self):
            self.requests = []
            self.intake_sheds = {}   # {(assigned_tenant, reason): n}

    results = {name: _TenantResult() for name in MIXED_TENANTS}
    arrivals = {name: _arrival_schedule(spec, period_s, duration_s)
                for name, spec in MIXED_TENANTS.items()}
    samples: list[tuple[float, int]] = []
    deploy_log: list[tuple] = []
    staged_v3 = threading.Event()
    stop_all = threading.Event()
    t0 = time.monotonic()

    def load(name, spec, res):
        for i, t_rel in enumerate(arrivals[name]):
            wait = (t0 + t_rel) - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            req = _DeployRequest()
            req.cost = spec["cost_s"]
            req.payload = f"{name}/{i}".encode()
            # admission-time routing: the version is stamped BEFORE the
            # scheduler sees the request (ServingServer._admit order),
            # and a canary pick re-tenants it onto the canary budget
            ver, override = router.assign(name)
            req.version = ver
            req.assigned_tenant = override or name
            try:
                sched.submit(req, tenant=req.assigned_tenant)
                res.requests.append(req)
            except Shed as s:
                router.release(ver)   # never admitted: undo the slot
                k = (req.assigned_tenant, s.reason)
                res.intake_sheds[k] = res.intake_sheds.get(k, 0) + 1

    def sampler():
        while not stop_all.wait(0.05):
            samples.append((time.monotonic() - t0, pool.count()))

    def driver():
        # phase 1: blue/green — build v2 beside v1, stage, one flip
        _sleep_until(t0 + stage_at * duration_s)
        mreg.register("v2", transform=_make_model("v2"))
        try:
            mreg.warm("v2")      # AOT warm standby (no-op for synth fns)
        except Exception:
            pass
        router.stage("v2")
        deploy_log.append(("stage", "v2",
                           round(time.monotonic() - t0, 3)))
        _sleep_until(t0 + flip_at * duration_s)
        router.flip()
        deploy_log.append(("flip", "v2",
                           round(time.monotonic() - t0, 3)))
        # phase 2: canary v3 — the seeded model.bad rule makes it burn
        _sleep_until(t0 + canary_at * duration_s)
        mreg.register("v3", transform=_make_model("v3"))
        router.stage("v3", canary_share=canary_share)
        deploy_log.append(("stage", "v3",
                           round(time.monotonic() - t0, 3)))
        staged_v3.set()

    def _sleep_until(t):
        d = t - time.monotonic()
        if d > 0:
            time.sleep(d)

    compile_tracker.mark_steady()
    try:
        with faults(seed, rules, inj=_inj) as inj:
            auto.start()
            mon = threading.Thread(target=pool.monitor,
                                   args=(stop_all,), daemon=True)
            mon.start()
            smp = threading.Thread(target=sampler, daemon=True)
            smp.start()
            drv = threading.Thread(target=driver, daemon=True)
            drv.start()
            loaders = [threading.Thread(target=load,
                                        args=(n, s, results[n]),
                                        daemon=True)
                       for n, s in MIXED_TENANTS.items()]
            for th in loaders:
                th.start()

            # the control loop: tick until the bad canary is rolled
            # back (bounded) and the offered load has ended
            rollback_ticks = None
            ticks_after_stage = 0
            while True:
                time.sleep(tick_s)
                r = ctl.tick()
                if staged_v3.is_set() and rollback_ticks is None:
                    ticks_after_stage += 1
                    if r == "rollback":
                        rollback_ticks = ticks_after_stage
                    elif ticks_after_stage > max_rollback_ticks:
                        break    # bounded: give up, report not rolled
                if not any(th.is_alive() for th in loaders) and (
                        rollback_ticks is not None
                        or not staged_v3.is_set()
                        or ticks_after_stage > max_rollback_ticks):
                    break
            for th in loaders:
                th.join(timeout=duration_s + 30)
            drv.join(timeout=duration_s + 30)
            # drain: every admitted request reaches a terminal state
            # and every flipped-away version empties
            drain_end = time.monotonic() + 10.0
            while time.monotonic() < drain_end:
                if sched.qsize() == 0 and not pool.leases \
                        and router.draining_inflight() == 0:
                    break
                time.sleep(0.05)
            draining_final = router.draining_inflight()
            schedule = inj.schedule()
            stop_all.set()
            auto.stop()
            pool.stop()
            mon.join(timeout=5)
            smp.join(timeout=5)
        runtime_compiles = compile_tracker.runtime_compiles()
    finally:
        compile_tracker.unmark_steady()

    # -- per-ASSIGNED-tenant outcomes ----------------------------------
    per_tenant: dict = {}
    mismatches = 0
    total_unanswered = 0
    for name, res in results.items():
        for req in res.requests:
            bucket = per_tenant.setdefault(
                req.assigned_tenant,
                {"answered_200": 0, "status_5xx": 0, "expired": 0,
                 "unanswered": 0, "sheds": {}, "lat": []})
            if req.status == 200:
                bucket["answered_200"] += 1
                if req.done_at is not None:
                    bucket["lat"].append(req.done_at - req.submitted)
                expected = req.version.encode() + b":" + req.payload
                if req.result != expected:
                    mismatches += 1
            elif req.status is not None and req.status >= 500:
                bucket["status_5xx"] += 1
            elif req.status == 429:
                bucket["expired"] += 1
            elif req.status is None:
                bucket["unanswered"] += 1
                total_unanswered += 1
        for (assigned, reason), n in res.intake_sheds.items():
            bucket = per_tenant.setdefault(
                assigned,
                {"answered_200": 0, "status_5xx": 0, "expired": 0,
                 "unanswered": 0, "sheds": {}, "lat": []})
            bucket["sheds"][reason] = bucket["sheds"].get(reason, 0) + n
    for name, b in per_tenant.items():
        lat = sorted(b.pop("lat"))
        b["p50_s"] = _pctl(lat, 0.50)
        b["p99_s"] = _pctl(lat, 0.99)
        b["shed_total"] = sum(b["sheds"].values()) + b["expired"]

    gold = per_tenant.get("cognitive", {})
    canary = per_tenant.get("canary", {})
    non_canary_5xx = sum(b["status_5xx"] for t, b in per_tenant.items()
                         if t != "canary")
    gold_sheds = gold.get("shed_total", 0)
    rollbacks = [e for e in ctl.events if e["kind"] == "rollback"]
    peak = max((c for _, c in samples), default=0)
    return {
        "seed": seed,
        "service": service,
        "duration_s": duration_s,
        "per_tenant": per_tenant,
        "deploy_log": deploy_log,
        # phase 1 contract: the flip is invisible to clients
        "non_canary_5xx": non_canary_5xx,
        "rollout_zero_5xx": bool(non_canary_5xx == 0),
        "unanswered": total_unanswered,
        "drained_completed": bool(total_unanswered == 0),
        "version_mismatches": mismatches,
        "byte_identical": bool(mismatches == 0),
        "draining_inflight_final": draining_final,
        "drained_to_zero": bool(draining_final == 0),
        "runtime_compiles": int(runtime_compiles),
        "zero_runtime_compiles": bool(runtime_compiles == 0),
        "worker_killed": any(p == "worker.death"
                             for p, *_ in schedule),
        "lease_replays": pool.replays,
        # phase 2 contract: burn-rate rollback, bounded, sliced blast
        "rollback_ticks": rollback_ticks,
        "rolled_back": bool(rollback_ticks is not None),
        "rollback_reason": rollbacks[-1]["reason"] if rollbacks
        else None,
        "active_after": router.active,
        "candidate_after": router.candidate,
        "canary_5xx": canary.get("status_5xx", 0),
        "canary_gold_sheds": gold_sheds,
        "gold_5xx": gold.get("status_5xx", 0),
        "gold_unharmed": bool(gold_sheds == 0
                              and gold.get("status_5xx", 0) == 0),
        "workers_peak": peak,
        "autoscaled": bool(peak >= 2),
        "schedule": sorted(schedule),
    }


# ---------------------------------------------- cost attribution plane
def synth_attribution_rows(n_rows: int = 1200, *, seed: int = 29,
                           service: str = "attr-bench") -> list[dict]:
    """Schema-v6 FeatureLog-shaped rows where part of the cost rides
    the ANALYTIC columns: each row's ``analytic_flops``/``analytic_
    bytes`` vary with the program variant that served it (seeded,
    independent of the other features), and ``execute_ms`` includes a
    per-Tflop term — the signal only a v6-aware model can price.
    Deterministic: two calls with one seed produce identical rows."""
    import numpy as np

    from ..obs.profile import FEATURE_SCHEMA_VERSION
    from ..sched.policy import bucket_of

    rng = np.random.default_rng(seed)
    routes = {"/feat": (0.8, 0.05), "/gen": (5.0, 0.40)}
    names = sorted(routes)
    ms_per_tflop = 2.5
    rows = []
    for _ in range(n_rows):
        route = names[int(rng.integers(0, len(names)))]
        base, per_row = routes[route]
        batch = int(rng.integers(1, 65))
        bucket = bucket_of(batch)
        depth = float(max(rng.normal(8.0, 4.0), 0.0))
        tflops = float(rng.uniform(0.2, 6.0))
        gbytes = tflops * float(rng.uniform(0.05, 0.15))
        ms = (base + per_row * bucket + ms_per_tflop * tflops
              + float(rng.normal(0.0, 0.15)))
        rows.append({
            "service": service, "route": route, "batch": batch,
            "bucket": bucket, "padded_batch": bucket,
            "entity_bytes": 1024.0, "queue_depth": depth,
            "execute_ms": max(ms, 0.05),
            "analytic_flops": tflops * 1e12,
            "analytic_bytes": gbytes * 1e9,
            "schema_version": FEATURE_SCHEMA_VERSION,
            "platform": "synthetic",
        })
    return rows


def attribution_scenario(*, seed: int = 29, n_rows: int = 1200,
                         holdout: float = 0.25, ticks: int = 12,
                         registry=None) -> dict:
    """Cost-attribution acceptance (ISSUE 20), three banked pieces:

    1. **Roofline placement** — two real programs compiled on the
       analytic path (a 256x256 matmul and a wide elementwise add),
       cost-analyzed and placed against the CPU :class:`PeakSpec`: the
       matmul must read compute-bound, the add memory-bound, and every
       utilization share <= 1.0 by construction.
    2. **Goodput under seeded chaos** — a private registry is driven
       through a deterministic tick schedule (useful step seconds
       every tick; seeded waste bursts: spec rejects, eager fallbacks,
       sheds, expirations, a runtime compile, a straggler window) and
       a :class:`~..obs.goodput.GoodputLedger` prices it. Banked: the
       final ratio, the itemized waste taxonomy, and the per-tick
       ratio trace (bit-identical per seed).
    3. **v6 model value** — the ridge cost model trained on rows whose
       cost partly rides the analytic columns must beat (or match) the
       SAME model trained with those columns stripped (the v5
       baseline) on held-out MAE.
    """
    import numpy as np

    from ..obs.attribution import CostAttribution, peak_spec
    from ..obs.goodput import GoodputLedger, WASTE_CAUSES
    from ..obs.metrics import MetricsRegistry
    from ..perf.costmodel import CostModel

    # -- 1: roofline placement off real compiled programs ---------------
    reg = registry if registry is not None else MetricsRegistry()
    attr = CostAttribution(registry=reg)
    rooflines: dict[str, dict] = {}
    import jax
    import jax.numpy as jnp

    a = jnp.ones((256, 256), jnp.float32)
    big = jnp.ones((4, 1 << 20), jnp.float32)
    programs = {
        "attr_matmul_256": jax.jit(lambda m: m @ m).lower(a).compile(),
        "attr_add_wide": jax.jit(lambda v: v + 1.0).lower(big).compile(),
    }
    for name, compiled in programs.items():
        info = attr.record_compiled(name, compiled,
                                    service="attr-bench",
                                    platform="cpu")
        if info is not None:
            rooflines[name] = {
                "bound": info["bound"],
                "flops": info["flops"],
                "bytes": info["bytes"],
                "utilization_compute": round(
                    info["compute_seconds"]
                    / max(info["roofline_seconds"], 1e-18), 6),
                "utilization_memory": round(
                    info["memory_seconds"]
                    / max(info["roofline_seconds"], 1e-18), 6),
            }

    # -- 2: goodput ledger under a seeded chaos schedule -----------------
    rng = np.random.default_rng(seed)
    greg = MetricsRegistry()
    ledger = GoodputLedger(registry=greg)
    h_step = greg.histogram("profile_step_seconds", "synthetic steps")
    h_decode = greg.histogram("gen_decode_attn_seconds", "synthetic")
    h_compile = greg.histogram("profile_compile_seconds", "synthetic")
    c_tokens = greg.counter("gen_tokens_total", "synthetic")
    c_spec = greg.counter("gen_spec_rejected_total", "synthetic")
    c_fallback = greg.counter("pipeline_fused_fallback_total",
                              "synthetic")
    c_shed = greg.counter("sched_shed_total", "synthetic")
    c_expired = greg.counter("sched_continuous_expired_total",
                             "synthetic")
    c_compiles = greg.counter("profile_runtime_compiles_total",
                              "synthetic")
    g_straggler = greg.gauge("fleet_straggler_score", "synthetic")
    ledger.tick()  # baseline
    ratio_trace = []
    for t in range(ticks):
        h_step.observe(0.010, stage="train")
        for _ in range(8):
            h_decode.observe(0.002, service="attr-bench")
            c_tokens.inc(1, service="attr-bench")
        if rng.random() < 0.5:
            c_spec.inc(int(rng.integers(1, 6)), service="attr-bench")
        if rng.random() < 0.3:
            c_fallback.inc(1, segment="seg0")
        if rng.random() < 0.3:
            c_shed.inc(int(rng.integers(1, 4)), reason="backpressure")
        if rng.random() < 0.2:
            c_expired.inc(1, service="attr-bench")
        if t == ticks // 2:
            c_compiles.inc(1, fn="late_fn")
            h_compile.observe(0.5, fn="late_fn")
        g_straggler.set(3.0 if t >= ticks - 3 else 0.0, worker="w1")
        payload = ledger.tick()
        ratio_trace.append(round(payload["goodput_ratio"], 6))
    waste = {c: round(payload["waste_seconds"][c], 6)
             for c in WASTE_CAUSES}

    # -- 3: v6 analytic columns vs the v5 baseline on held-out MAE -------
    service = "attr-bench"
    rows = synth_attribution_rows(n_rows, seed=seed, service=service)
    n_train = int(len(rows) * (1.0 - holdout))
    train, held = rows[:n_train], rows[n_train:]
    stripped = [{k: v for k, v in r.items()
                 if k not in ("analytic_flops", "analytic_bytes")}
                for r in train]
    m_v6 = CostModel(min_rows=32, registry=MetricsRegistry())
    m_v6.fit(train)
    m_v5 = CostModel(min_rows=32, registry=MetricsRegistry())
    m_v5.fit(stripped)
    v6_abs, v5_abs = [], []
    for r in held:
        actual = r["execute_ms"]
        for model, acc in ((m_v6, v6_abs), (m_v5, v5_abs)):
            pred = model.predict_batch_ms(
                service, r["batch"], route=r["route"],
                entity_bytes=r["entity_bytes"],
                queue_depth=r["queue_depth"], count=False)
            if pred is not None:
                acc.append(abs(pred - actual))
    v6_mae = sum(v6_abs) / len(v6_abs) if v6_abs else float("nan")
    v5_mae = sum(v5_abs) / len(v5_abs) if v5_abs else float("nan")

    return {
        "seed": seed,
        "platform_spec": {
            "platform": peak_spec("cpu").platform,
            "peak_flops": peak_spec("cpu").peak_flops,
            "hbm_bytes_per_s": peak_spec("cpu").hbm_bytes_per_s,
        },
        "rooflines": rooflines,
        "matmul_compute_bound": bool(
            rooflines.get("attr_matmul_256", {}).get("bound")
            == "compute"),
        "add_memory_bound": bool(
            rooflines.get("attr_add_wide", {}).get("bound")
            == "memory"),
        "utilization_max": max(
            [u for r in rooflines.values()
             for u in (r["utilization_compute"],
                       r["utilization_memory"])], default=0.0),
        "goodput_ratio": ratio_trace[-1] if ratio_trace else None,
        "goodput_ratio_trace": ratio_trace,
        "goodput_waste_seconds": waste,
        "goodput_waste_itemized": bool(
            sum(1 for v in waste.values() if v > 0) >= 4),
        "v6_mae_ms": v6_mae,
        "v5_mae_ms": v5_mae,
        "v6_no_worse": bool(v6_mae <= v5_mae * 1.001),
    }
