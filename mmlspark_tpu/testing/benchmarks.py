"""Benchmark regression harness.

Reference ``core/test/benchmarks/Benchmarks.scala:16-130``: named metric
values with explicit tolerance recorded in CSVs
(``src/test/resources/benchmarks/benchmarks_<Suite>.csv``); the test
recomputes each metric and ``compareBenchmark`` asserts it matches within
precision. Same CSV format here: ``name,value,precision`` rows.
"""

from __future__ import annotations

import csv
import os


class Benchmarks:
    """Accumulate metrics, then compare (or regenerate) the CSV."""

    def __init__(self, csv_path: str):
        self.csv_path = csv_path
        self.recorded: list[tuple[str, float, float]] = []

    def add(self, name: str, value: float, precision: float) -> None:
        """Reference ``addBenchmark``."""
        self.recorded.append((name, float(value), float(precision)))

    def _load(self) -> dict[str, tuple[float, float]]:
        out = {}
        with open(self.csv_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                out[row[0]] = (float(row[1]), float(row[2]))
        return out

    def _write(self) -> None:
        os.makedirs(os.path.dirname(self.csv_path), exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.writer(f)
            for name, value, precision in self.recorded:
                w.writerow([name, repr(value), repr(precision)])

    def verify(self, regenerate: bool = False) -> None:
        """Reference ``verifyBenchmarks``: assert every recorded metric is
        within its recorded precision; regenerate=True (or a missing CSV)
        writes the file instead — the reference's workflow for adding new
        benchmark rows."""
        if regenerate or not os.path.exists(self.csv_path):
            self._write()
            return
        expected = self._load()
        errors = []
        for name, value, precision in self.recorded:
            if name not in expected:
                errors.append(f"missing benchmark row {name!r}")
                continue
            exp_val, exp_prec = expected[name]
            if abs(value - exp_val) > exp_prec:
                errors.append(
                    f"{name}: got {value}, expected {exp_val} ± {exp_prec}")
        if errors:
            raise AssertionError("benchmark regressions:\n"
                                 + "\n".join(errors))
