"""Benchmark regression harness.

Reference ``core/test/benchmarks/Benchmarks.scala:16-130``: named metric
values with explicit tolerance recorded in CSVs
(``src/test/resources/benchmarks/benchmarks_<Suite>.csv``); the test
recomputes each metric and ``compareBenchmark`` asserts it matches within
precision. Same CSV format here: ``name,value,precision`` rows.

Timings come through the obs subsystem, not private stopwatches: a
``timed(...)`` region records into the process-wide registry
(``benchmark_seconds{name=...}``) and the benchmark row reads the value
back from that same histogram, so a benchmark timing is always also a
scrapeable series (``/metrics``, ``registry.snapshot()``) — one
measurement surface for benches, serving, and training alike.
"""

from __future__ import annotations

import contextlib
import csv
import os
import threading
import time
from math import ceil as _ceil

from ..obs.metrics import registry as _registry


class Benchmarks:
    """Accumulate metrics, then compare (or regenerate) the CSV."""

    def __init__(self, csv_path: str):
        self.csv_path = csv_path
        self.recorded: list[tuple[str, float, float]] = []

    def add(self, name: str, value: float, precision: float) -> None:
        """Reference ``addBenchmark``."""
        self.recorded.append((name, float(value), float(precision)))

    @contextlib.contextmanager
    def timed(self, name: str, precision: float):
        """Time a region through the obs registry and record the row.

        The wall seconds land in the process-wide
        ``benchmark_seconds{name=...}`` histogram (scrapeable alongside
        serving/training series) and THIS region's duration becomes the
        CSV row — not an aggregate over the labeled series, which would
        fold warmup passes and prior in-process runs into the value."""
        hist = _registry.histogram(
            "benchmark_seconds", "benchmark timed-region wall seconds")
        with hist.time(name=name) as t:
            yield
        self.add(name, t.seconds, precision)

    def add_from_registry(self, name: str, sample: str,
                          precision: float, registry=None) -> None:
        """Record a registry sample (a ``snapshot()`` key, e.g.
        ``serving_requests_total{route="/"}``) as a benchmark row."""
        snap = (registry if registry is not None else _registry) \
            .snapshot()
        if sample not in snap:
            raise KeyError(
                f"registry sample {sample!r} not found; known samples "
                f"include {sorted(snap)[:8]}...")
        self.add(name, snap[sample], precision)

    def _load(self) -> dict[str, tuple[float, float]]:
        out = {}
        with open(self.csv_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                out[row[0]] = (float(row[1]), float(row[2]))
        return out

    def _write(self) -> None:
        os.makedirs(os.path.dirname(self.csv_path), exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.writer(f)
            for name, value, precision in self.recorded:
                w.writerow([name, repr(value), repr(precision)])

    def verify(self, regenerate: bool = False) -> None:
        """Reference ``verifyBenchmarks``: assert every recorded metric is
        within its recorded precision; regenerate=True (or a missing CSV)
        writes the file instead — the reference's workflow for adding new
        benchmark rows."""
        if regenerate or not os.path.exists(self.csv_path):
            self._write()
            return
        expected = self._load()
        errors = []
        for name, value, precision in self.recorded:
            if name not in expected:
                errors.append(f"missing benchmark row {name!r}")
                continue
            exp_val, exp_prec = expected[name]
            if abs(value - exp_val) > exp_prec:
                errors.append(
                    f"{name}: got {value}, expected {exp_val} ± {exp_prec}")
        if errors:
            raise AssertionError("benchmark regressions:\n"
                                 + "\n".join(errors))


class _SynthRequest:
    """A scheduler item for the overload scenario: carries the latch the
    arrival thread waits on plus the attributes the sched subsystem
    decorates (route/deadline/on_done)."""

    __slots__ = ("submitted", "done_at", "status", "route", "deadline",
                 "on_done", "_event")

    def __init__(self):
        self.submitted = time.monotonic()
        self.done_at = None
        self.status = None
        self.route = "/"
        self.deadline = None
        self.on_done = None
        self._event = threading.Event()

    def reply(self, status: int) -> bool:
        # reply-exactly-once latch, same contract as serving's
        # CachedRequest (the scheduler's expiry shed path calls this)
        if self._event.is_set():
            return False
        self.status = status
        self.done_at = time.monotonic()
        self._event.set()
        cb, self.on_done = self.on_done, None
        if cb is not None:
            cb()
        return True


def overload_scenario(*, service: str = "overload-bench",
                      deadline_s: float = 0.2,
                      item_service_s: float = 0.004,
                      max_queue: int = 64,
                      max_batch: int = 8,
                      rate_factor: float = 2.0,
                      n_requests: int = 400,
                      registry=None) -> dict:
    """Synthetic overload benchmark for the sched subsystem (ISSUE 2
    acceptance): offer load at ``rate_factor``× the sustainable rate
    into a :class:`~mmlspark_tpu.sched.RequestScheduler` backed by a
    deterministic executor (``item_service_s`` seconds per request,
    batched up to ``max_batch``), then read the ``sched_*`` series back
    from the obs registry.

    A correct scheduler under 2× overload must (a) bound the queue —
    admission sheds BEFORE depth runs away, (b) keep the latency of
    requests it chose to admit within the deadline budget — expiry
    sheds fire before execution, never after — and (c) shed the excess
    as 429s rather than timing everyone out. The returned dict carries
    the measured p99/max depth plus the registry readings
    (``sched_admitted_total``, ``sched_shed_total`` by reason,
    ``sched_queue_wait_seconds`` count) so benches can bank and tests
    can assert on either surface.
    """
    from ..obs.metrics import registry as _default
    from ..sched import RequestScheduler, Shed

    reg = registry if registry is not None else _default
    shed_answered: list[_SynthRequest] = []
    sched = RequestScheduler(
        service, max_queue=max_queue, deadline=deadline_s, registry=reg,
        on_shed=lambda item, reason, retry_after:
            (shed_answered.append(item), item.reply(429)))
    done: list[_SynthRequest] = []
    stop = threading.Event()
    depth_high = [0]

    def executor():
        while not stop.is_set() or sched.qsize():
            batch = sched.next_batch(max_batch=max_batch, max_wait=0.05)
            if not batch:
                continue
            t0 = time.monotonic()
            time.sleep(item_service_s * len(batch))  # deterministic work
            sched.estimator.observe(len(batch),
                                    time.monotonic() - t0)
            for item in batch:
                item.reply(200)
                done.append(item)

    worker = threading.Thread(target=executor, daemon=True)
    worker.start()
    interval = item_service_s / rate_factor
    admitted = shed_at_intake = 0
    # prime the service-time EWMA so predictive admission has a model
    # from the first request (a cold registry sheds nothing until the
    # first batch lands)
    sched.estimator.observe(1, item_service_s)
    for _ in range(n_requests):
        req = _SynthRequest()
        try:
            sched.submit(req)
            admitted += 1
        except Shed:
            shed_at_intake += 1
        depth_high[0] = max(depth_high[0], sched.qsize())
        time.sleep(interval)
    stop.set()
    sched.wake()
    worker.join(timeout=10)
    lat = sorted((r.done_at - r.submitted) for r in done
                 if r.done_at is not None)
    snap = reg.snapshot()

    def _series(prefix: str) -> dict:
        return {k: v for k, v in snap.items()
                if k.startswith(prefix) and f'service="{service}"' in k}

    return {
        "offered": n_requests,
        "admitted": admitted,
        "answered_200": len(lat),
        "shed_at_intake": shed_at_intake,
        "shed_after_queueing": len(shed_answered),
        "deadline_s": deadline_s,
        "max_queue": max_queue,
        "max_depth_seen": depth_high[0],
        # nearest-rank percentiles: ceil(q*n)-1 — int(n*0.99)-1 would
        # sit one rank low and hide exactly the tail samples a
        # deadline-SLO acceptance check exists to catch
        "p50_s": lat[max(_ceil(0.50 * len(lat)) - 1, 0)]
        if lat else float("nan"),
        "p99_s": lat[max(_ceil(0.99 * len(lat)) - 1, 0)]
        if lat else float("nan"),
        "sched_admitted_total": _series("sched_admitted_total"),
        "sched_shed_total": _series("sched_shed_total"),
        "sched_queue_wait_count": _series("sched_queue_wait_seconds_count"),
    }
