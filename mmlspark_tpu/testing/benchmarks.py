"""Benchmark regression harness.

Reference ``core/test/benchmarks/Benchmarks.scala:16-130``: named metric
values with explicit tolerance recorded in CSVs
(``src/test/resources/benchmarks/benchmarks_<Suite>.csv``); the test
recomputes each metric and ``compareBenchmark`` asserts it matches within
precision. Same CSV format here: ``name,value,precision`` rows.

Timings come through the obs subsystem, not private stopwatches: a
``timed(...)`` region records into the process-wide registry
(``benchmark_seconds{name=...}``) and the benchmark row reads the value
back from that same histogram, so a benchmark timing is always also a
scrapeable series (``/metrics``, ``registry.snapshot()``) — one
measurement surface for benches, serving, and training alike.
"""

from __future__ import annotations

import contextlib
import csv
import os
import threading
import time
from math import ceil as _ceil

from ..obs.metrics import registry as _registry


class Benchmarks:
    """Accumulate metrics, then compare (or regenerate) the CSV."""

    def __init__(self, csv_path: str):
        self.csv_path = csv_path
        self.recorded: list[tuple[str, float, float]] = []

    def add(self, name: str, value: float, precision: float) -> None:
        """Reference ``addBenchmark``."""
        self.recorded.append((name, float(value), float(precision)))

    @contextlib.contextmanager
    def timed(self, name: str, precision: float):
        """Time a region through the obs registry and record the row.

        The wall seconds land in the process-wide
        ``benchmark_seconds{name=...}`` histogram (scrapeable alongside
        serving/training series) and THIS region's duration becomes the
        CSV row — not an aggregate over the labeled series, which would
        fold warmup passes and prior in-process runs into the value."""
        hist = _registry.histogram(
            "benchmark_seconds", "benchmark timed-region wall seconds")
        with hist.time(name=name) as t:
            yield
        self.add(name, t.seconds, precision)

    def add_from_registry(self, name: str, sample: str,
                          precision: float, registry=None) -> None:
        """Record a registry sample (a ``snapshot()`` key, e.g.
        ``serving_requests_total{route="/"}``) as a benchmark row."""
        snap = (registry if registry is not None else _registry) \
            .snapshot()
        if sample not in snap:
            raise KeyError(
                f"registry sample {sample!r} not found; known samples "
                f"include {sorted(snap)[:8]}...")
        self.add(name, snap[sample], precision)

    def _load(self) -> dict[str, tuple[float, float]]:
        out = {}
        with open(self.csv_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                out[row[0]] = (float(row[1]), float(row[2]))
        return out

    def _write(self) -> None:
        os.makedirs(os.path.dirname(self.csv_path), exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.writer(f)
            for name, value, precision in self.recorded:
                w.writerow([name, repr(value), repr(precision)])

    def verify(self, regenerate: bool = False) -> None:
        """Reference ``verifyBenchmarks``: assert every recorded metric is
        within its recorded precision; regenerate=True (or a missing CSV)
        writes the file instead — the reference's workflow for adding new
        benchmark rows."""
        if regenerate or not os.path.exists(self.csv_path):
            self._write()
            return
        expected = self._load()
        errors = []
        for name, value, precision in self.recorded:
            if name not in expected:
                errors.append(f"missing benchmark row {name!r}")
                continue
            exp_val, exp_prec = expected[name]
            if abs(value - exp_val) > exp_prec:
                errors.append(
                    f"{name}: got {value}, expected {exp_val} ± {exp_prec}")
        if errors:
            raise AssertionError("benchmark regressions:\n"
                                 + "\n".join(errors))


class _SynthRequest:
    """A scheduler item for the overload scenario: carries the latch the
    arrival thread waits on plus the attributes the sched subsystem
    decorates (route/deadline/on_done)."""

    __slots__ = ("submitted", "done_at", "status", "route", "deadline",
                 "on_done", "span", "queue_wait", "_event")

    def __init__(self):
        self.submitted = time.monotonic()
        self.done_at = None
        self.status = None
        self.route = "/"
        self.deadline = None
        self.on_done = None
        self.span = None        # request span (tracing scenarios)
        self.queue_wait = None  # stamped by the scheduler at pop
        self._event = threading.Event()

    def reply(self, status: int) -> bool:
        # reply-exactly-once latch, same contract as serving's
        # CachedRequest (the scheduler's expiry shed path calls this)
        if self._event.is_set():
            return False
        self.status = status
        self.done_at = time.monotonic()
        self._event.set()
        cb, self.on_done = self.on_done, None
        if cb is not None:
            cb()
        return True


def overload_scenario(*, service: str = "overload-bench",
                      deadline_s: float = 0.2,
                      item_service_s: float = 0.004,
                      max_queue: int = 64,
                      max_batch: int = 8,
                      rate_factor: float = 2.0,
                      n_requests: int = 400,
                      registry=None) -> dict:
    """Synthetic overload benchmark for the sched subsystem (ISSUE 2
    acceptance): offer load at ``rate_factor``× the sustainable rate
    into a :class:`~mmlspark_tpu.sched.RequestScheduler` backed by a
    deterministic executor (``item_service_s`` seconds per request,
    batched up to ``max_batch``), then read the ``sched_*`` series back
    from the obs registry.

    A correct scheduler under 2× overload must (a) bound the queue —
    admission sheds BEFORE depth runs away, (b) keep the latency of
    requests it chose to admit within the deadline budget — expiry
    sheds fire before execution, never after — and (c) shed the excess
    as 429s rather than timing everyone out. The returned dict carries
    the measured p99/max depth plus the registry readings
    (``sched_admitted_total``, ``sched_shed_total`` by reason,
    ``sched_queue_wait_seconds`` count) so benches can bank and tests
    can assert on either surface.
    """
    from ..obs.metrics import registry as _default
    from ..sched import RequestScheduler, Shed

    reg = registry if registry is not None else _default
    shed_answered: list[_SynthRequest] = []
    sched = RequestScheduler(
        service, max_queue=max_queue, deadline=deadline_s, registry=reg,
        on_shed=lambda item, reason, retry_after:
            (shed_answered.append(item), item.reply(429)))
    done: list[_SynthRequest] = []
    stop = threading.Event()
    depth_high = [0]

    def executor():
        while not stop.is_set() or sched.qsize():
            batch = sched.next_batch(max_batch=max_batch, max_wait=0.05)
            if not batch:
                continue
            t0 = time.monotonic()
            time.sleep(item_service_s * len(batch))  # deterministic work
            sched.estimator.observe(len(batch),
                                    time.monotonic() - t0)
            for item in batch:
                item.reply(200)
                done.append(item)

    worker = threading.Thread(target=executor, daemon=True)
    worker.start()
    interval = item_service_s / rate_factor
    admitted = shed_at_intake = 0
    # prime the service-time EWMA so predictive admission has a model
    # from the first request (a cold registry sheds nothing until the
    # first batch lands)
    sched.estimator.observe(1, item_service_s)
    for _ in range(n_requests):
        req = _SynthRequest()
        try:
            sched.submit(req)
            admitted += 1
        except Shed:
            shed_at_intake += 1
        depth_high[0] = max(depth_high[0], sched.qsize())
        time.sleep(interval)
    stop.set()
    sched.wake()
    worker.join(timeout=10)
    lat = sorted((r.done_at - r.submitted) for r in done
                 if r.done_at is not None)
    snap = reg.snapshot()

    def _series(prefix: str) -> dict:
        return {k: v for k, v in snap.items()
                if k.startswith(prefix) and f'service="{service}"' in k}

    return {
        "offered": n_requests,
        "admitted": admitted,
        "answered_200": len(lat),
        "shed_at_intake": shed_at_intake,
        "shed_after_queueing": len(shed_answered),
        "deadline_s": deadline_s,
        "max_queue": max_queue,
        "max_depth_seen": depth_high[0],
        # nearest-rank percentiles: ceil(q*n)-1 — int(n*0.99)-1 would
        # sit one rank low and hide exactly the tail samples a
        # deadline-SLO acceptance check exists to catch
        "p50_s": lat[max(_ceil(0.50 * len(lat)) - 1, 0)]
        if lat else float("nan"),
        "p99_s": lat[max(_ceil(0.99 * len(lat)) - 1, 0)]
        if lat else float("nan"),
        "sched_admitted_total": _series("sched_admitted_total"),
        "sched_shed_total": _series("sched_shed_total"),
        "sched_queue_wait_count": _series("sched_queue_wait_seconds_count"),
    }


def tracing_overhead_scenario(*, service: str = "tracing-bench",
                              n_requests: int = 200,
                              item_service_s: float = 0.005,
                              max_batch: int = 8,
                              reps: int = 3,
                              registry=None) -> dict:
    """Profiler-overhead guard (ISSUE 8 satellite): the same synthetic
    serving pipeline (RequestScheduler + deterministic executor — no
    HTTP socket, so loopback jitter cannot masquerade as tracing cost)
    measured with the full tracing+profiler stack OFF vs ON, asserting
    the instrumented p99 stays within 5%% of bare.

    ON means everything a traced serving request pays: a request span
    per item, the scheduler's ``sched.queue`` child span, a retroactive
    execute span, a cost-model feature-log record, a ``StepProfiler``
    step around each executor batch, and a flight-recorder
    ``note_request`` per reply. The modes run INTERLEAVED (off, on,
    off, on, ...) and each mode keeps its best-of-``reps`` p99 — the
    same min-of-runs discipline bench.py's loaded rows use: the
    per-rep minimum is the deterministic floor (service time + any
    instrumentation cost), so host contention and sleep jitter — which
    hit both modes but not symmetrically within one rep — cannot
    manufacture or mask overhead. Returns both p99s, ``overhead_pct``,
    and ``within_bound`` (the 5%% contract — asserted by the test AND
    banked in the bench JSON).
    """
    from ..obs.export import flight_recorder
    from ..obs.profile import StepProfiler, feature_log
    from ..obs.metrics import registry as _default
    from ..obs.tracing import tracer
    from ..sched import RequestScheduler

    reg = registry if registry is not None else _default
    profiler = StepProfiler(service=service, registry=reg)
    flight_recorder.install()

    def one_run(traced: bool) -> float:
        sched = RequestScheduler(f"{service}-{'on' if traced else 'off'}",
                                 registry=reg)
        done: list[_SynthRequest] = []
        stop = threading.Event()

        def executor():
            while not stop.is_set() or sched.qsize():
                batch = sched.next_batch(max_batch=max_batch,
                                         max_wait=0.05)
                if not batch:
                    continue
                if traced:
                    with profiler.step("tracing-bench.batch") as h:
                        time.sleep(item_service_s * len(batch))
                        h.done(None)
                else:
                    time.sleep(item_service_s * len(batch))
                for item in batch:
                    span = getattr(item, "span", None)
                    if span is not None:
                        tracer.emit_span(
                            "serving.execute", parent=span,
                            seconds=item_service_s * len(batch),
                            service=service, rows=len(batch))
                        feature_log.record(
                            service=service, route="/",
                            batch=len(batch),
                            queue_ms=(getattr(item, "queue_wait", 0.0)
                                      or 0.0) * 1e3,
                            execute_ms=item_service_s * len(batch)
                            * 1e3, trace_id=span.trace_id)
                    item.reply(200)
                    if span is not None:
                        span.set_attr("status", 200)
                        tracer.end_span(span)
                        flight_recorder.note_request(
                            span.trace_id,
                            time.monotonic() - item.submitted,
                            status=200)
                    done.append(item)

        worker = threading.Thread(target=executor, daemon=True)
        worker.start()
        # pace BELOW saturation: the executor's cost is linear in batch
        # size here, so an overloaded run would measure queue growth —
        # the one thing that is NOT tracing overhead — in both modes
        interval = item_service_s * 1.5
        for _ in range(n_requests):
            req = _SynthRequest()
            if traced:
                req.span = tracer.start_span(
                    "serving.request", parent=None, current=False,
                    service=service, route="/")
            try:
                sched.submit(req)
            except Exception:
                req.reply(503)
            time.sleep(interval)
        stop.set()
        sched.wake()
        worker.join(timeout=20)
        lat = sorted((r.done_at - r.submitted) for r in done
                     if r.done_at is not None and r.status == 200)
        if not lat:
            return float("nan")
        return lat[max(_ceil(0.99 * len(lat)) - 1, 0)]

    offs, ons = [], []
    for _ in range(reps):
        offs.append(one_run(False))
        ons.append(one_run(True))
    p99_off, p99_on = min(offs), min(ons)
    overhead_pct = (p99_on - p99_off) / p99_off * 100.0
    return {
        "n_requests": n_requests,
        "item_service_s": item_service_s,
        "reps": reps,
        "p99_off_s": p99_off,
        "p99_on_s": p99_on,
        "overhead_pct": overhead_pct,
        "bound_pct": 5.0,
        "within_bound": overhead_pct <= 5.0,
        "feature_records": len(feature_log),
    }


# span names a COMPLETE cross-process tree must contain for a request
# answered through the worker mesh (chaos acceptance): the driver-side
# request root + its queue wait, and the compute worker's execute +
# device spans, all under one trace id
COMPLETE_TRACE_SPANS = frozenset({"serving.request", "sched.queue",
                                  "worker.execute", "worker.device"})


def chaos_scenario(*, service: str = "chaos-bench", seed: int = 11,
                   n_requests: int = 40, n_workers: int = 3,
                   error_rate: float = 0.05,
                   latency_spike_s: float = 0.05,
                   latency_rate: float = 0.05,
                   kill_after_leases: int = 1,
                   request_timeout_s: float = 10.0,
                   trace_dir: str | None = None) -> dict:
    """Seeded chaos acceptance for the resilience subsystem (ISSUE 4):
    a real worker mesh (driver registry with heartbeat liveness, one
    ingest server, ``n_workers`` in-thread compute workers) driven under
    an armed fault schedule — one injected worker death mid-lease
    (``worker.death``, after ``kill_after_leases`` healthy leases), 5%%
    injected 503s and latency spikes on the client's ``http.send`` hop —
    while a closed-loop client offers ``n_requests`` through the
    resilience :class:`~mmlspark_tpu.resilience.RetryPolicy`.

    The contract measured: every accepted request is answered 200 (the
    killed worker's leases replay to survivors, injected 503s are
    re-offered per ``Retry-After``) or shed per policy (429/503 only);
    ZERO transport errors (status 0 / connection reset) reach the
    client. The returned dict carries the realized fault ``schedule`` —
    a pure function of the seed and per-point probe order, so re-running
    with the same seed reproduces it — plus the ``resilience_*`` /
    ``serving_lease_replays_total`` registry readings the acceptance
    asserts on.

    Fault decisions are per-point deterministic; the client runs
    single-threaded so the realized schedule is also totally ordered.

    Tracing (ISSUE 8 acceptance): every client request runs under a
    ``client.request`` root span, so the whole run is cross-process
    traced — the result reports, per answered request, whether its span
    tree is COMPLETE (:data:`COMPLETE_TRACE_SPANS` under one trace id)
    and samples one such tree; ``trace_dir`` additionally exports the
    collected spans as Chrome-trace/Perfetto JSON
    (``<trace_dir>/chaos_trace.json``).
    """
    import json as _json
    import os as _os

    import numpy as np

    from ..io.http.clients import send_request
    from ..io.http.schema import HTTPRequestData, HTTPResponseData
    from ..obs.export import SpanCollector, chrome_trace
    from ..obs.tracing import tracer
    from ..resilience import FaultRule, RetryPolicy, faults
    from ..serving import (DistributedServingServer, DriverRegistry,
                           remote_worker_loop)

    def echo(df):
        replies = np.empty(len(df), object)
        replies[:] = [HTTPResponseData(status_code=200,
                                       entity=(r.entity or b"").upper())
                      for r in df["request"]]
        return df.with_column("reply", replies)

    snap_before = _registry.snapshot()
    driver = DriverRegistry(heartbeat_timeout=0.75).start()
    server = DistributedServingServer(
        service, driver.address, lease_timeout=2.0, reply_timeout=15.0,
        load_report_interval=0.2).start()
    stop = threading.Event()
    workers = [threading.Thread(
        target=remote_worker_loop,
        args=(driver.address, service, echo),
        kwargs={"stop_event": stop, "heartbeat_interval": 0.2,
                "max_batch": 4, "worker_id": f"chaos-w{i}"},
        daemon=True) for i in range(n_workers)]
    rules = [
        FaultRule(point="worker.death", kind="kill", p=1.0,
                  after=kill_after_leases, times=1),
        FaultRule(point="http.send", kind="error", p=error_rate,
                  status=503, retry_after=0.05),
        FaultRule(point="http.send", kind="latency", p=latency_rate,
                  latency_s=latency_spike_s),
    ]
    policy = RetryPolicy(seed=seed, base_delay=0.02, max_delay=0.5,
                         max_attempts=5)
    statuses: list[int] = []
    trace_ids: list[str] = []
    url = f"http://{server.address[0]}:{server.address[1]}/"
    try:
        with SpanCollector() as collector, faults(seed, rules) as inj:
            for w in workers:
                w.start()
            for i in range(n_requests):
                # client-side root span: the trace id every downstream
                # hop (ingest, lease, worker, reply) joins
                with tracer.span("client.request", i=i) as sp:
                    trace_ids.append(sp.trace_id)
                    resp = send_request(
                        HTTPRequestData(url=url, method="POST",
                                        headers={},
                                        entity=f"req-{i}".encode()),
                        timeout=request_timeout_s, policy=policy)
                statuses.append(resp.status_code)
            schedule = inj.schedule()
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=5)
        server.stop()
        driver.stop()
    # span-tree completeness per answered request (trace acceptance)
    names = collector.names_by_trace()
    answered_trees = {t: sorted(n for n in names.get(t, set()) if n)
                      for t, s in zip(trace_ids, statuses)
                      if 200 <= s < 300}
    complete = {t for t, ns in answered_trees.items()
                if COMPLETE_TRACE_SPANS <= set(ns)}
    sampled = None
    trace_path = None
    if complete:
        sample_id = sorted(complete)[0]
        sampled = {"trace_id": sample_id,
                   "spans": answered_trees[sample_id]}
        if trace_dir is not None:
            spans = [d for d in collector.spans()
                     if d.get("traceId") in complete]
            trace_path = _os.path.join(trace_dir, "chaos_trace.json")
            with open(trace_path, "w") as f:
                _json.dump(chrome_trace(spans, extra_metadata={
                    "scenario": "chaos", "seed": seed,
                    "sampled_trace_id": sample_id}), f)
    snap = _registry.snapshot()

    def _delta(prefix: str) -> float:
        return sum(v - snap_before.get(k, 0.0)
                   for k, v in snap.items() if k.startswith(prefix))

    answered = sum(1 for s in statuses if 200 <= s < 300)
    policy_sheds = sum(1 for s in statuses if s in (429, 503))
    return {
        "offered": n_requests,
        "answered_200": answered,
        "policy_sheds": policy_sheds,
        "answered_traces": len(answered_trees),
        "complete_traces": len(complete),
        "sampled_trace": sampled,
        "trace_path": trace_path,
        "transport_errors": sum(1 for s in statuses if s == 0),
        "non_policy_errors": sum(
            1 for s in statuses
            if not (200 <= s < 300) and s not in (429, 503)),
        "schedule": schedule,
        "retries_taken": _delta("resilience_retry_total"),
        "faults_injected": _delta("resilience_faults_injected_total"),
        "lease_replays": _delta("serving_lease_replays_total"),
        "worker_deaths_detected": _delta("resilience_worker_deaths_total"),
        "breaker_state_present": any(
            k.startswith("resilience_breaker_state") for k in snap),
        "retry_total_present": any(
            k.startswith("resilience_retry_total") for k in snap),
        "lease_replays_present": any(
            k.startswith("serving_lease_replays_total") for k in snap),
    }
