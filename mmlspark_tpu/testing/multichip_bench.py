"""Multi-device throughput bench body for bench.py's ``multichip``
section.

Every BENCH_r* number so far is single-host even though the multichip
harness sees 8 devices — MULTICHIP_r*.json has been a liveness check,
not a benchmark. This module turns it into a throughput read: the
partition-rule-sharded BERT train step and the shard_map'd LightGBM
histogram build run on ALL local devices and on one device, and the
ratio is the scaling story the pod-scale roadmap items build on.

Execution contract (mirrors ``__graft_entry__.dryrun_multichip``): the
PUBLIC entry point is bench.py's ``bench_multichip``, which re-execs
:func:`main` in a subprocess whose environment is scrubbed to a virtual
n-device CPU platform — the session environment pins JAX to the
single-chip TPU tunnel, under which ``jax.devices()`` can never yield
n devices (and a wedged tunnel would hang the suite). On a real
multi-chip host the same body runs unscrubbed and the numbers become
chip numbers. :func:`main` prints ONE JSON line on stdout.

Scaling efficiency is weak-scaling (fixed PER-DEVICE batch):
``ips_n / (n * ips_1)`` — 1.0 means the n-device step is n× the
1-device step. Per-device MFU is achieved FLOP/s per device over the
v5e bf16 peak; on the CPU harness that is a liveness-scale number (the
honest read there is the efficiency ratio), and the JSON says which
platform produced it.
"""

from __future__ import annotations

import json
import time

from ..obs.attribution import peak_spec as _peak_spec

# per-chip bf16 peak from the shared PeakSpec table (bench.py's —
# env-overridable via MMLSPARK_TPU_PEAK_FLOPS)
V5E_PEAK_BF16_FLOPS = _peak_spec("tpu-v5e").peak_flops


def _min_time(fn, reps: int = 3) -> float:
    """Best-of-reps wall seconds of one blocking call."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _bert_step_ips(devices, per_device_batch: int, iters: int = 4):
    """(images/sec, flops_per_image) of the rule-sharded BERT train
    step over a dp mesh on ``devices``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ..dl.bert import BertEncoder
    from ..dl.train import (init_train_state, make_partitioned_train_step,
                            partition_train_state)
    from ..parallel import MeshSpec, build_mesh
    from ..parallel.partition import partition_rules_for

    n = len(devices)
    mesh = build_mesh(MeshSpec(dp=n, tp=1), devices=np.asarray(devices))
    # bf16 like every other *_mfu row in bench.py: the per-device MFU
    # normalizes by the bf16 chip peak, so an f32 model would read ~2x
    # low on real chips
    module = BertEncoder(vocab=1024, width=128, depth=2, heads=4,
                         mlp_dim=256, max_len=64, pooler=False,
                         dtype=jnp.bfloat16)
    tx = optax.adamw(1e-3)
    rng = np.random.default_rng(0)
    B, T = per_device_batch * n, 48
    ids = jnp.asarray(rng.integers(1, 1024, size=(B, T)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 128, size=B), jnp.int32)

    state = init_train_state(module, jax.random.PRNGKey(0), ids[:1], tx)
    state, shardings = partition_train_state(
        state, mesh, partition_rules_for("BertEncoder"))
    step = make_partitioned_train_step(module, tx, mesh, shardings,
                                       fetch="pooled")
    flops_per_image = 0.0
    try:
        compiled = step.lower(state, ids, labels).compile()
    except Exception:
        compiled = None
    if compiled is not None:
        from ..parallel.compat import cost_analysis
        cost = cost_analysis(compiled)
        if cost is not None:
            # sharded programs report per-device flops: scale back to
            # the global batch so flops/image is mesh-size-independent
            flops_per_image = cost["flops"] * n / B
    box = {"s": state}

    def run():
        s, loss = box["s"], None
        for _ in range(iters):
            s, loss = step(s, ids, labels)
        jax.block_until_ready(loss)
        box["s"] = s

    run()  # warm (and the donated state threads through the box)
    secs = _min_time(run)
    return B * iters / secs, flops_per_image


def _gbdt_hist_rows_per_sec(devices, rows_per_device: int,
                            iters: int = 3):
    """rows/sec of the shard_map'd tree grow (histogram build + psum
    tree all-reduce) over a dp mesh on ``devices``."""
    import jax
    import numpy as np

    from ..lightgbm.engine import TreeParams
    from ..lightgbm.trainer import make_grower
    from ..parallel import MeshSpec, build_mesh

    n = len(devices)
    mesh = build_mesh(MeshSpec(dp=n, tp=1), devices=np.asarray(devices))
    rng = np.random.default_rng(1)
    N, F = rows_per_device * n, 32
    tp = TreeParams(num_leaves=31, max_bin=63, min_data_in_leaf=5)
    bins = rng.integers(0, 64, size=(N, F)).astype(np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = np.ones(N, np.float32)
    fm = np.ones(F, bool)
    rm = np.ones(N, np.float32)
    grow = make_grower(mesh=mesh, mesh_axis="dp", tp=tp, multi=False,
                       num_features=F, dense_bins=bins)

    def run():
        out = None
        for _ in range(iters):
            out = grow(g, h, fm, rm)
        jax.block_until_ready(out)

    run()  # warm
    secs = _min_time(run)
    return N * iters / secs


def run(n_devices: int = 8) -> dict:
    """The bench body: returns the multichip extras dict."""
    import jax

    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"multichip bench needs {n_devices} devices, have "
            f"{len(devices)} — run under the virtual-mesh env "
            "(bench.bench_multichip does this)")
    devices = devices[:n_devices]
    out: dict = {
        "multichip_devices": n_devices,
        "multichip_platform": devices[0].platform,
    }

    per_dev_batch = 16
    ips_n, flops_per_image = _bert_step_ips(devices, per_dev_batch)
    ips_1, _ = _bert_step_ips(devices[:1], per_dev_batch)
    out["sharded_train_images_per_sec"] = round(ips_n, 1)
    out["sharded_train_images_per_sec_1dev"] = round(ips_1, 1)
    out["sharded_scaling_efficiency"] = round(
        ips_n / (n_devices * ips_1), 4) if ips_1 else 0.0
    if flops_per_image:
        out["sharded_train_flops_per_image"] = flops_per_image
        out["sharded_train_per_device_flops_per_sec"] = round(
            ips_n * flops_per_image / n_devices, 1)
        out["sharded_train_per_device_mfu"] = round(
            ips_n * flops_per_image / n_devices / V5E_PEAK_BF16_FLOPS, 6)

    rows_per_dev = 8192
    rps_n = _gbdt_hist_rows_per_sec(devices, rows_per_dev)
    rps_1 = _gbdt_hist_rows_per_sec(devices[:1], rows_per_dev)
    out["sharded_gbdt_hist_rows_per_sec"] = round(rps_n, 1)
    out["sharded_gbdt_hist_rows_per_sec_1dev"] = round(rps_1, 1)
    out["sharded_gbdt_scaling_efficiency"] = round(
        rps_n / (n_devices * rps_1), 4) if rps_1 else 0.0
    out.update(crosshost(local_devices=n_devices // 2))
    return out


def crosshost(local_devices: int = 4, timeout: float = 420.0) -> dict:
    """The DCN section: the SAME (2, local) dcn×ici mesh program run as
    a 2-process pod (4 devices per worker, gloo collectives over
    loopback) and as 1 process owning all 8 devices. Identical global
    mesh, identical program, identical data — the throughput ratio
    isolates the process boundary (serialization, gloo hops, per-rank
    dispatch), which is the crosshost scaling-efficiency number the
    pod roadmap items track. Plus: cross-host fused-serving p99 with
    the bit-equality digest checked against the single-process run,
    the instrumented dp-axis allreduce's per-shard byte count, and
    the warmed pod worker's runtime-compile count (must be 0)."""
    from ..parallel.multihost import launch_pod

    scen = "mmlspark_tpu.testing.multihost_scenarios"
    mesh = [2, local_devices]
    total = 2 * local_devices
    # Per-step compute must dominate the per-step process-boundary cost
    # (gloo hops + per-rank dispatch are ~fixed per step) or the ratio
    # measures dispatch overhead, not the data plane: at batch 64 /
    # width 128 the ratio reads ~0.44, at this size ~0.9.
    train_args = {"mesh": mesh, "steps": 2, "batch": 128, "seq_len": 64,
                  "width": 192, "bench_iters": 4, "seed": 0}
    pod = launch_pod(f"{scen}:train_trajectory", num_processes=2,
                     local_devices=local_devices, args=train_args,
                     timeout=timeout)
    solo = launch_pod(f"{scen}:train_trajectory", num_processes=1,
                      local_devices=total, args=train_args,
                      timeout=timeout)
    out: dict = {
        "crosshost_processes": 2,
        "crosshost_mesh": mesh,
        "crosshost_train_images_per_sec": round(pod[0]["ips"], 1),
        "crosshost_train_images_per_sec_1proc": round(
            solo[0]["ips"], 1),
        "crosshost_scaling_efficiency": round(
            pod[0]["ips"] / solo[0]["ips"], 4) if solo[0]["ips"] else 0.0,
        "crosshost_loss_max_abs_diff": max(
            abs(a - b) for a, b in zip(pod[0]["losses"],
                                       solo[0]["losses"])),
        "crosshost_runtime_compiles": sum(
            r["runtime_compiles"] for r in pod),
    }
    serve_args = {"mesh": mesh, "rows": 64, "feats": 16, "requests": 24,
                  "seed": 0}
    spod = launch_pod(f"{scen}:fused_serving", num_processes=2,
                      local_devices=local_devices, args=serve_args,
                      timeout=timeout)
    ssolo = launch_pod(f"{scen}:fused_serving", num_processes=1,
                       local_devices=total, args=serve_args,
                       timeout=timeout)
    out["crosshost_serving_p99_ms"] = max(r["p99_ms"] for r in spod)
    out["crosshost_serving_bit_equal"] = bool(
        all(r["bit_equal"] for r in spod + ssolo)
        and len({r["digest"] for r in spod + ssolo}) == 1)
    cb = launch_pod(f"{scen}:collective_bytes", num_processes=2,
                    local_devices=local_devices,
                    args={"mesh": mesh, "rows": 1024}, timeout=timeout)
    out["crosshost_collective_bytes"] = sum(r["bytes"] for r in cb)
    return out


def main(n_devices: int = 8) -> None:
    """Subprocess entry: one JSON line on stdout (bench.py parses the
    LAST line that parses, so stray backend chatter above is fine)."""
    print(json.dumps(run(n_devices)), flush=True)


if __name__ == "__main__":  # pragma: no cover - manual runs
    main()
