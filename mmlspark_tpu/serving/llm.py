"""LLM serving engine: disaggregated prefill/decode over the paged KV
cache, with speculation inside the continuous batch.

The pre-existing generation path (``dl.ContinuousGenerator``) is a
monolithic dense-cache decoder: every slot owns a ``[max_len]`` cache
row, prompts prefill inside the decode program, and a long prompt
admission stalls the whole batch for its prefill. This module is the
serving-shaped rebuild the ROADMAP names (and the TPU serving
comparison in arXiv:2605.25645 measures): the two phases have opposite
execution profiles — prefill is a large, MXU-saturating causal forward;
decode is a tiny launch-latency-bound step — so they get SEPARATE
executors with separate padding buckets and separate AOT-fingerprinted
programs, stitched together by a handoff of (sequence, block chain)
over the paged KV pool (``dl.paged_kv``):

- :class:`PrefillExecutor` fills KV blocks in padding-bucketed batches
  (one compiled program per window bucket), starting AFTER any
  prefix-reused blocks — a warm prompt skips exactly the prefill the
  cache already holds, which is the TTFT win the bench measures.
- :class:`DecodeExecutor` runs the fixed-shape continuous-batching step
  over block tables. Attention reads the pools IN PLACE through the
  block table (``dl.pallas_paged_attention`` — the Pallas kernel on
  TPU, its bit-exact lax reference on CPU): each step embeds the
  slots' tokens, scatters the new kv through the table, and attends
  each slot's own chain with no dense gather — the
  ``gather_dense``-per-step round trip of the first cut is gone
  (``MMLSPARK_TPU_PAGED_ATTN=0`` brings it back, loudly:
  ``kv_dense_gather_bytes_total`` counts every re-gathered byte and
  reads 0 on the paged path). Greedy output stays token-identical to
  ``dl.generate`` (pinned by test). With a draft model,
  ``dl.speculative``'s draft/verify window runs PER SLOT: each slot
  accepts its own longest agreeing prefix (no batch sync-on-min —
  block chains advance independently), so accepted bursts move a slot
  by up to k+1 tokens per step; the verify window is the kernel's
  windowed variant (k+1 query rows per slot).
- Handoff rides :class:`HandoffQueue`: the prefill side exports the
  sequence from the block table (:meth:`PagedKVManager.export_seq` —
  ownership moves with the payload), the decode side adopts it when it
  has a free slot (load-aware pull). The payload is a flat JSON dict —
  :func:`pack_handoff` / :func:`unpack_handoff` — exactly the shape the
  distributed tier's ``__lease__`` envelope (``serving.distributed``)
  already carries for replayed work, so a cross-host split reuses that
  plumbing unchanged (plus a block-content transfer, which in-process
  disaggregation doesn't need: both executors address the same pools).

Every device program is built through ``compile_tracker.jit`` with a
stable name and carries an AOT fingerprint (``core.aot.fingerprints``
over the program's static shape key), so a warmed worker serves both
phases with zero runtime compiles (``mark_steady`` + the CompileTracker
steady-state assertion is the acceptance test). On TPU-class backends
the pools are DONATED to every program (``donate_argnums``): each step
writes its kv into the buffers it read from, so steady-state decode
allocates nothing per step (donation is skipped off-TPU, where XLA
ignores it with a warning).

Obs: ``gen_ttft_seconds{reuse=cold|warm}``, ``gen_tokens_total``,
``gen_spec_accept_ratio``, ``gen_decode_steps_total``,
``gen_decode_attn_seconds{phase}`` and the dense-fallback odometer
``kv_dense_gather_bytes_total`` here, the ``kv_*`` families in
``dl.paged_kv`` — all federated fleet-wide and recorded by the
telemetry history plane. Completions land FeatureLog rows with
``decode_steps``/``prefill_tokens``/``context_blocks`` so the cost
model prices the two phases separately and decode by resident context
(``perf.costmodel``, schema v5).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

import numpy as np

from ..core import aot
from ..dl.paged_kv import (OutOfBlocks, PagedKVManager, gather_dense,
                           init_pools, paged_attention_enabled,
                           scatter_positions, take_positions)
from ..obs import registry as _default_registry
from ..obs.attribution import cost_attribution
from ..obs.profile import compile_tracker, feature_log
from ..sched.continuous import SlotScheduler

__all__ = ["LLMEngine", "PrefillExecutor", "DecodeExecutor",
           "HandoffQueue", "pack_handoff", "unpack_handoff"]


def _bucket_window(n: int) -> int:
    """Pad a prefill window to the compile-cache-friendly grid —
    the same ladder ``dl.generate`` buckets prefix lengths on (≥64:
    multiple of 64, below: power of two)."""
    n = max(int(n), 1)
    if n >= 64:
        return ((n + 63) // 64) * 64
    p = 1
    while p < n:
        p <<= 1
    return p


def _attribute_warm(prog, service: str, *args) -> None:
    """Analytic roofline attribution for a warmed program
    (obs.attribution, ISSUE 20): re-lower the tracked jit AOT and read
    ``cost_analysis`` off the Lowered (a trace, not a compile — the
    compile only happens on JAX builds whose Lowered cannot answer).
    Runs at warm time, before ``mark_steady``, so the extra trace never
    counts as a runtime compile. Failures degrade silently: attribution
    is telemetry, never a serving gate."""
    lower = getattr(prog, "lower", None)
    if lower is None:
        return
    name = getattr(prog, "__tracked_label__", f"llm_{service}")
    try:
        lowered = lower(*args)
    except Exception:
        return
    if cost_attribution.record_compiled(
            name, lowered, service=service) is not None:
        return
    try:
        compiled = lowered.compile()
    except Exception:
        return
    cost_attribution.record_compiled(name, compiled, service=service)


def _encoder_key(module) -> dict:
    """Static fingerprint fragment for a causal-LM module: everything
    that changes the compiled program besides the batch shapes."""
    enc = module.encoder
    return {"vocab": enc.vocab, "width": enc.width, "depth": enc.depth,
            "heads": enc.heads, "mlp_dim": enc.mlp_dim,
            "dtype": np.dtype(enc.dtype).name}


def _donate_pools_kwargs() -> dict:
    """``donate_argnums`` for the pool arguments (positions 2/3 of
    every executor program) on backends where donation is real — each
    step then writes its kv into the buffers it read from, so warmed
    decode allocates nothing per step. Off-TPU XLA ignores donation
    with a warning per program, so skip it there."""
    try:
        from ..utils.platform import target_platform
        if target_platform() in ("tpu", "axon"):
            return {"donate_argnums": (2, 3)}
    except Exception:  # pragma: no cover - platform probe best-effort
        pass
    return {}


def _dense_gather_bytes(module, n_rows: int, max_blocks: int,
                        block_len: int) -> int:
    """Bytes ONE ``gather_dense`` over ``n_rows`` chains materializes
    for ``module``'s pools — what the ``MMLSPARK_TPU_PAGED_ATTN=0``
    fallback moves per call and the paged path doesn't."""
    enc = module.encoder
    hd = enc.width // enc.heads
    return int(2 * enc.depth * n_rows * max_blocks * block_len
               * enc.heads * hd * np.dtype(enc.dtype).itemsize)


def _paged_window_walk(mod, toks, pools, rows, pos, valid):
    """The paged decode forward: [S, w] token ids at per-slot global
    positions ``[pos[s], pos[s]+w)`` → ([S, w, V] logits, updated
    pools), reading/writing the pools IN PLACE through the block table.

    Per block: project qkv, scatter the window's kv through the table
    (write-then-attend, the order ``decode_step``/``decode_window``
    keep; ``valid`` False redirects a row's writes to the trash block),
    then ``dl.paged_window_attention`` over each slot's own chain — no
    dense gather anywhere. The embed/projection/attention/ffn math is
    element-for-element the ``embed_window → decode_window_blocks →
    lm_head`` composition (the lax attention path shares
    ``decode_window``'s exact formulation), so greedy tokens stay
    byte-identical to ``dl.generate`` on CPU tier-1.

    Runs under ``module.apply(..., method=_paged_window_walk)`` —
    ``mod`` is the bound ``MaskedLMModel``."""
    import jax.numpy as jnp

    from ..dl.pallas_paged_attention import paged_window_attention

    enc = mod.encoder
    w = toks.shape[1]
    # batched embed_window: same constants/ops per element, positions
    # per slot instead of one traced scalar
    x = enc.embed_layer(toks)                           # [S, w, W]
    dim = jnp.arange(enc.width // 2)[None, None, :]
    p = (pos[:, None] + jnp.arange(w)[None, :]
         ).astype(jnp.float32)[:, :, None]
    ang = p / (10000.0 ** (2 * dim / enc.width))
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    x = x + pe.astype(enc.dtype)
    wrote = pos[:, None] + jnp.arange(w)[None]          # [S, w]
    new_pools = []
    for blk, (kp, vp) in zip(enc.blocks, pools):
        q, k, v = blk._project_qkv(x)                   # [S, H, w, hd]
        (kp, vp), = scatter_positions(
            ((kp, vp),), rows, wrote,
            ((k.transpose(0, 2, 1, 3).astype(kp.dtype),
              v.transpose(0, 2, 1, 3).astype(vp.dtype)),),
            valid=valid)
        o = paged_window_attention(q, kp, vp, rows, pos)
        x = blk.ffn(x + blk._merge_out(o))
        new_pools.append((kp, vp))
    x = enc.final_ln(x)
    return mod.lm_head(x), tuple(new_pools)


# ----------------------------------------------------------------- handoff

def pack_handoff(payload: dict) -> bytes:
    """Serialize a prefill→decode handoff for the wire — the body the
    distributed tier's ``__lease__`` envelope carries when the two
    executors live on different hosts."""
    return json.dumps(payload, sort_keys=True).encode()


def unpack_handoff(data: bytes) -> dict:
    return json.loads(data.decode())


class HandoffQueue:
    """The prefill→decode boundary: prefill pushes exported sequences,
    decode pulls AT MOST its free-slot count per boundary (load-aware —
    a saturated decoder leaves work queued instead of overcommitting).
    Payloads round-trip :func:`pack_handoff` so the in-process queue
    and the cross-host lease path carry identical bytes."""

    def __init__(self):
        self._q: list[dict] = []

    def push(self, payload: dict) -> None:
        # serialize/deserialize even in-process: the payload must stay
        # wire-shaped or the cross-host path rots silently
        self._q.append(unpack_handoff(pack_handoff(payload)))

    def pull(self, max_items: int) -> list[dict]:
        n = max(int(max_items), 0)
        out, self._q = self._q[:n], self._q[n:]
        return out

    def __len__(self) -> int:
        return len(self._q)


class _PoolState:
    """Shared mutable holder for the device pools: both executors read
    and replace the SAME pools (in-process disaggregation — the block
    table addresses one physical pool)."""

    def __init__(self, target, draft=None):
        self.target = target
        self.draft = draft


# --------------------------------------------------------------- executors

class PrefillExecutor:
    """Fills KV blocks for admitted prompts in padding-bucketed batches.

    One compiled program per window bucket ``w``: run the paged window
    walk (:func:`_paged_window_walk`) over the prompt SUFFIX
    (everything past the prefix-reused blocks) at per-row start
    positions — SCATTER-ONLY: each block's kv writes through the table
    as it is computed and attention reads the pools in place, no
    ``gather_dense``/``take_positions`` round trip — and emit each
    row's first generated token (the logits at its last prompt
    position — TTFT is measured here). With a draft model the same
    window also fills the DRAFT pools, so prefix-reused blocks hold
    both models' kv consistently. ``MMLSPARK_TPU_PAGED_ATTN=0`` keeps
    the old gather→vmapped-``decode_window``→scatter program callable
    (every gathered byte counted ``kv_dense_gather_bytes_total``)."""

    def __init__(self, module, variables, kv: PagedKVManager,
                 pools: _PoolState, *, draft_module=None,
                 draft_variables=None, max_blocks: int, batch: int = 4,
                 pad_id: int = 0, service: str = "llm", registry=None):
        self.module = module
        self.variables = variables
        self.draft_module = draft_module
        self.draft_variables = draft_variables
        self.kv = kv
        self.pools = pools
        self.max_blocks = int(max_blocks)
        self.batch = max(int(batch), 1)
        self.pad_id = int(pad_id)
        self.service = service
        self.paged = paged_attention_enabled()
        reg = registry if registry is not None else _default_registry
        self._h_attn = reg.histogram(
            "gen_decode_attn_seconds",
            "attention-program wall time, by service and phase",
            buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1,
                     .25, .5, 1., 2.5))
        self._c_gather = reg.counter(
            "kv_dense_gather_bytes_total",
            "bytes materialized by gather_dense in the dense-attention "
            "fallback (0 on the paged-kernel path), by service/phase")
        self._gather_bytes = _dense_gather_bytes(
            module, self.batch, self.max_blocks, kv.block_len)
        if draft_module is not None:
            self._gather_bytes += _dense_gather_bytes(
                draft_module, self.batch, self.max_blocks, kv.block_len)
        self._programs: dict[int, object] = {}
        self._fps: dict[str, tuple[str, str]] = {}

    # -- compiled program per window bucket --------------------------------
    def _program(self, w: int):
        prog = self._programs.get(w)
        if prog is not None:
            return prog
        import jax
        import jax.numpy as jnp
        module, draft = self.module, self.draft_module
        pad_id, P = self.pad_id, self.batch

        if self.paged:
            def run(params, dparams, pools_t, pools_d, rows, toks, pos,
                    lens):
                valid = (jnp.arange(w)[None] < lens[:, None]) & \
                    (lens[:, None] > 0)
                logits, pools_t = module.apply(
                    {"params": params}, toks, pools_t, rows, pos,
                    valid, method=_paged_window_walk)   # [P, w, V]
                if draft is not None:
                    _, pools_d = draft.apply(
                        {"params": dparams}, toks, pools_d, rows, pos,
                        valid, method=_paged_window_walk)
                logits = logits.at[:, :, pad_id].set(-jnp.inf)
                last = jnp.clip(lens - 1, 0, w - 1)
                row_logits = jnp.take_along_axis(
                    logits,
                    last[:, None, None].repeat(logits.shape[-1], 2),
                    axis=1)[:, 0]                       # [P, V]
                first = jnp.argmax(row_logits, -1).astype(jnp.int32)
                return pools_t, pools_d, first
        else:
            def run(params, dparams, pools_t, pools_d, rows, toks, pos,
                    lens):
                dense_t = gather_dense(pools_t, rows)

                def one(mod, prm, tk, cache, p):
                    c = jax.tree.map(lambda a: a[None], cache)
                    logits, c = mod.apply({"params": prm}, tk[None], c,
                                          p, method="decode_window")
                    return logits[0], jax.tree.map(lambda a: a[0], c)

                logits, dense_t = jax.vmap(
                    lambda tk, c, p: one(module, params, tk, c, p)
                )(toks, dense_t, pos)                   # [P, w, V]
                wrote = pos[:, None] + jnp.arange(w)[None]  # [P, w]
                valid = (jnp.arange(w)[None] < lens[:, None]) & \
                    (lens[:, None] > 0)
                new_kv = take_positions(dense_t, wrote)
                pools_t = scatter_positions(pools_t, rows, wrote,
                                            new_kv, valid=valid)
                if draft is not None:
                    dense_d = gather_dense(pools_d, rows)
                    _, dense_d = jax.vmap(
                        lambda tk, c, p: one(draft, dparams, tk, c, p)
                    )(toks, dense_d, pos)
                    pools_d = scatter_positions(
                        pools_d, rows, wrote,
                        take_positions(dense_d, wrote), valid=valid)
                logits = logits.at[:, :, pad_id].set(-jnp.inf)
                last = jnp.clip(lens - 1, 0, w - 1)
                row_logits = jnp.take_along_axis(
                    logits,
                    last[:, None, None].repeat(logits.shape[-1], 2),
                    axis=1)[:, 0]                       # [P, V]
                first = jnp.argmax(row_logits, -1).astype(jnp.int32)
                return pools_t, pools_d, first

        name = f"llm_prefill_{self.service}_w{w}_b{P}"
        prog = compile_tracker.jit(run, name=name,
                                   **_donate_pools_kwargs())
        self._programs[w] = prog
        key = {"phase": "prefill", "service": self.service,
               "window": w, "batch": P,
               "attn": "paged" if self.paged else "dense",
               "max_blocks": self.max_blocks,
               "block_len": self.kv.block_len,
               "encoder": _encoder_key(self.module),
               "draft": None if draft is None else _encoder_key(draft),
               "versions": aot.runtime_versions()}
        self._fps[name] = aot.fingerprints(key, [], [])
        return prog

    def aot_fingerprints(self) -> dict:
        """program name -> (static_fp, full_fp) for every program built
        so far — the identity a warmed worker advertises."""
        return dict(self._fps)

    # -- host driver --------------------------------------------------------
    def prefill(self, jobs: list) -> dict:
        """``jobs``: list of ``(seq_id, prompt_tokens)`` whose chains
        are already allocated in ``kv``. Runs bucketed batches, commits
        lengths (``kv.advance`` + ``kv.publish``), returns
        ``seq_id -> (first_token, suffix_len)``."""
        import jax.numpy as jnp
        out: dict = {}
        for start in range(0, len(jobs), self.batch):
            chunk = jobs[start:start + self.batch]
            metas = []
            for seq_id, prompt in chunk:
                h = self.kv.handle(seq_id)
                # a fully reused prompt still re-feeds its last token:
                # the window must emit logits for the first generated
                # position (the rewrite stores bit-identical kv)
                s0 = min(h.reused_tokens, h.prompt_len - 1)
                metas.append((seq_id, list(prompt), s0,
                              h.prompt_len - s0))
            w = _bucket_window(max(m[3] for m in metas))
            P = self.batch
            toks = np.zeros((P, w), np.int32)
            pos = np.zeros(P, np.int32)
            lens = np.zeros(P, np.int32)
            ids: list = [m[0] for m in metas]
            for i, (seq_id, prompt, s0, n) in enumerate(metas):
                toks[i, :n] = prompt[s0:]
                pos[i] = s0
                lens[i] = n
            rows = self.kv.block_rows(
                ids + [None] * (P - len(ids)), self.max_blocks)
            prog = self._program(w)
            t0 = time.perf_counter()
            pools_t, pools_d, first = prog(
                self.variables["params"],
                None if self.draft_module is None
                else self.draft_variables["params"],
                self.pools.target, self.pools.draft,
                jnp.asarray(rows), jnp.asarray(toks),
                jnp.asarray(pos), jnp.asarray(lens))
            self._h_attn.observe(time.perf_counter() - t0,
                                 service=self.service, phase="prefill")
            if not self.paged:
                self._c_gather.inc(self._gather_bytes,
                                   service=self.service,
                                   phase="prefill")
            self.pools.target = pools_t
            if self.draft_module is not None:
                self.pools.draft = pools_d
            first = np.asarray(first)
            for i, (seq_id, prompt, s0, n) in enumerate(metas):
                h = self.kv.handle(seq_id)
                self.kv.advance(seq_id, h.prompt_len - h.length)
                self.kv.publish(seq_id)
                out[seq_id] = (int(first[i]), int(n))
        return out

    def warm(self, windows=(1,)) -> None:
        """Compile (and run, against the trash block only) the programs
        for the given window buckets — the warmup sweep before
        ``compile_tracker.mark_steady()``."""
        import jax.numpy as jnp
        P = self.batch
        for w in windows:
            w = _bucket_window(w)
            rows = jnp.zeros((P, self.max_blocks), jnp.int32)
            prog = self._program(w)
            args = (
                self.variables["params"],
                None if self.draft_module is None
                else self.draft_variables["params"],
                self.pools.target, self.pools.draft, rows,
                jnp.zeros((P, w), jnp.int32), jnp.zeros(P, jnp.int32),
                jnp.zeros(P, jnp.int32))
            # attribution must lower BEFORE the call: donation
            # invalidates the pool buffers the args reference
            _attribute_warm(prog, self.service, *args)
            pools_t, pools_d, _ = prog(*args)
            self.pools.target = pools_t
            if self.draft_module is not None:
                self.pools.draft = pools_d


class DecodeExecutor:
    """The fixed-shape continuous-batching decode step over block
    tables. All shapes are pinned at construction — ``[slots]`` state
    vectors, ``[slots, max_blocks]`` block tables — so ONE program per
    mode serves every step (the zero-runtime-compile contract).

    Plain mode: ONE paged window walk of width 1 — embed the slots'
    last tokens, scatter kv through the table, paged attention over
    each chain in place, greedy ``argmax`` with pad masked — the
    numerics of ``dl.generate``'s cached path with zero dense
    gathers. Spec mode (draft present): ``dl.speculative``'s
    draft/verify runs as k width-1 draft walks plus one width-(k+1)
    target walk (the kernel's windowed variant); each slot accepts its
    own longest agreeing prefix — no batch sync-on-min, block chains
    advance independently. ``MMLSPARK_TPU_PAGED_ATTN=0`` keeps the
    old gather→vmapped-``decode_step``→scatter program callable
    (``kv_dense_gather_bytes_total`` counts what it moves)."""

    def __init__(self, module, variables, kv: PagedKVManager,
                 pools: _PoolState, *, draft_module=None,
                 draft_variables=None, slots: int, max_blocks: int,
                 spec_k: int = 0, pad_id: int = 0,
                 service: str = "llm", registry=None):
        if spec_k and draft_module is None:
            raise ValueError("spec_k > 0 needs a draft model")
        self.module = module
        self.variables = variables
        self.draft_module = draft_module
        self.draft_variables = draft_variables
        self.kv = kv
        self.pools = pools
        self.slots = int(slots)
        self.max_blocks = int(max_blocks)
        self.spec_k = int(spec_k)
        self.pad_id = int(pad_id)
        self.service = service
        self.paged = paged_attention_enabled()
        reg = registry if registry is not None else _default_registry
        self._h_attn = reg.histogram(
            "gen_decode_attn_seconds",
            "attention-program wall time, by service and phase",
            buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1,
                     .25, .5, 1., 2.5))
        self._c_gather = reg.counter(
            "kv_dense_gather_bytes_total",
            "bytes materialized by gather_dense in the dense-attention "
            "fallback (0 on the paged-kernel path), by service/phase")
        self._gather_bytes = _dense_gather_bytes(
            module, int(slots), int(max_blocks), kv.block_len)
        if draft_module is not None:
            self._gather_bytes += _dense_gather_bytes(
                draft_module, int(slots), int(max_blocks),
                kv.block_len)
        # host-side slot state (the engine owns seq metadata)
        self.seq_ids: list = [None] * self.slots
        self.ptr = np.ones(self.slots, np.int32)   # committed tokens
        self.end = np.ones(self.slots, np.int32)   # commit cap
        self.last = np.zeros(self.slots, np.int32)  # token @ ptr-1
        self.active = np.zeros(self.slots, bool)
        self._program = None
        self._fps: dict[str, tuple[str, str]] = {}

    @property
    def free_slots(self) -> int:
        return int(self.slots - self.active.sum())

    # -- slot lifecycle -----------------------------------------------------
    def activate(self, slot_hint, state: dict) -> int:
        """Adopt a handoff payload into a free slot. ``slot_hint`` (the
        scheduler's assignment) is used when free; any free slot
        otherwise."""
        slot = slot_hint if (slot_hint is not None
                             and not self.active[slot_hint]) else \
            int(np.flatnonzero(~self.active)[0])
        handle = self.kv.adopt(state["seq"])
        self.seq_ids[slot] = handle.seq_id
        # cache holds [0, prompt_len); the first generated token (from
        # prefill) is committed at position prompt_len, pending embed
        self.ptr[slot] = handle.length + 1
        self.end[slot] = handle.length + int(state["max_new_tokens"])
        self.last[slot] = int(state["first"])
        self.active[slot] = True
        return slot

    def deactivate(self, slot: int) -> None:
        self.seq_ids[slot] = None
        self.active[slot] = False
        self.ptr[slot] = 1
        self.end[slot] = 1
        self.last[slot] = self.pad_id

    # -- the compiled step --------------------------------------------------
    def _build(self):
        if self._program is not None:
            return self._program
        import jax
        import jax.numpy as jnp
        module, draft = self.module, self.draft_module
        pad_id, k, S = self.pad_id, self.spec_k, self.slots

        def expand(c):
            return jax.tree.map(lambda a: a[None], c)

        def strip(c):
            return jax.tree.map(lambda a: a[0], c)

        if self.paged and k == 0:
            def run(params, dparams, pools_t, pools_d, rows, last, ptr,
                    end, active):
                logits, pools_t = module.apply(
                    {"params": params}, last[:, None], pools_t, rows,
                    ptr - 1, active[:, None],
                    method=_paged_window_walk)          # [S, 1, V]
                logits = logits[:, 0].at[:, pad_id].set(-jnp.inf)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                committed = nxt[:, None]                # [S, 1]
                n_new = jnp.where(active, 1, 0)
                return pools_t, pools_d, committed, n_new, n_new
        elif self.paged:
            def run(params, dparams, pools_t, pools_d, rows, last, ptr,
                    end, active):
                pos = ptr - 1
                av = active[:, None]
                tok = last[:, None]                     # [S, 1]
                drafts = []
                for j in range(k):
                    ld, pools_d = draft.apply(
                        {"params": dparams}, tok, pools_d, rows,
                        pos + j, av, method=_paged_window_walk)
                    ld = ld[:, 0].at[:, pad_id].set(-jnp.inf)
                    tok = jnp.argmax(ld, -1).astype(jnp.int32)[:, None]
                    drafts.append(tok[:, 0])
                # extra cache-fill step: d_k's kv, or the next round's
                # draft attends a zero hole after a full accept (same
                # fix as dl.speculative)
                _, pools_d = draft.apply(
                    {"params": dparams}, tok, pools_d, rows, pos + k,
                    av, method=_paged_window_walk)
                d = jnp.stack(drafts, 1)                # [S, k]
                window = jnp.concatenate([last[:, None], d], 1)
                lt, pools_t = module.apply(
                    {"params": params}, window, pools_t, rows, pos,
                    av & jnp.ones((S, k + 1), bool),
                    method=_paged_window_walk)          # [S, k+1, V]
                lt = lt.at[:, :, pad_id].set(-jnp.inf)
                t = jnp.argmax(lt, -1).astype(jnp.int32)
                agree = jnp.cumprod(
                    (d == t[:, :k]).astype(jnp.int32), axis=1)
                n_acc = agree.sum(axis=1)               # PER-SLOT
                bonus = jnp.take_along_axis(
                    t, n_acc[:, None], axis=1)[:, 0]
                ar = jnp.arange(k + 1)[None]            # [1, k+1]
                d_ext = jnp.concatenate(
                    [d, jnp.zeros((S, 1), jnp.int32)], 1)
                committed = jnp.where(
                    ar < n_acc[:, None], d_ext,
                    jnp.where(ar == n_acc[:, None], bonus[:, None],
                              pad_id))                  # [S, k+1]
                # never commit past the slot's budget (end - ptr
                # tokens remain; runnable slots have at least 1)
                n_new = jnp.clip(n_acc + 1, 1,
                                 jnp.maximum(end - ptr, 1))
                n_new = jnp.where(active, n_new, 0)
                return pools_t, pools_d, committed, n_new, \
                    jnp.where(active, n_acc, 0)
        elif k == 0:
            def run(params, dparams, pools_t, pools_d, rows, last, ptr,
                    end, active):
                dense = gather_dense(pools_t, rows)

                def one(tk, cache, p):
                    logits, c = module.apply(
                        {"params": params}, tk[None], expand(cache),
                        p - 1, method="decode_step")
                    return logits[0], strip(c)

                logits, dense = jax.vmap(one)(last, dense, ptr)
                logits = logits.at[:, pad_id].set(-jnp.inf)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                wrote = (ptr - 1)[:, None]              # [S, 1]
                pools_t = scatter_positions(
                    pools_t, rows, wrote, take_positions(dense, wrote),
                    valid=active[:, None])
                committed = nxt[:, None]                # [S, 1]
                n_new = jnp.where(active, 1, 0)
                return pools_t, pools_d, committed, n_new, n_new
        else:
            def run(params, dparams, pools_t, pools_d, rows, last, ptr,
                    end, active):
                dense_t = gather_dense(pools_t, rows)
                dense_d = gather_dense(pools_d, rows)

                def one(tk, ct, cd, p):
                    ct, cd = expand(ct), expand(cd)
                    tok = tk[None]
                    drafts = []
                    for j in range(k):
                        ld, cd = draft.apply(
                            {"params": dparams}, tok, cd, p - 1 + j,
                            method="decode_step")
                        ld = ld.at[:, pad_id].set(-jnp.inf)
                        tok = jnp.argmax(ld, -1).astype(jnp.int32)
                        drafts.append(tok)
                    # extra cache-fill step: d_k's kv, or the next
                    # round's draft attends a zero hole after a full
                    # accept (same fix as dl.speculative)
                    _, cd = draft.apply(
                        {"params": dparams}, tok, cd, p - 1 + k,
                        method="decode_step")
                    d = jnp.stack(drafts, 1)            # [1, k]
                    window = jnp.concatenate([tk[None][:, None], d], 1)
                    lt, ct = module.apply(
                        {"params": params}, window, ct, p - 1,
                        method="decode_window")         # [1, k+1, V]
                    lt = lt.at[:, :, pad_id].set(-jnp.inf)
                    t = jnp.argmax(lt, -1).astype(jnp.int32)
                    agree = jnp.cumprod(
                        (d == t[:, :k]).astype(jnp.int32), axis=1)
                    n_acc = agree.sum(axis=1)[0]        # PER-SLOT
                    bonus = t[0, n_acc]
                    return (d[0], n_acc, bonus, strip(ct), strip(cd))

                d, n_acc, bonus, dense_t, dense_d = jax.vmap(one)(
                    last, dense_t, dense_d, ptr)
                ar = jnp.arange(k + 1)[None]            # [1, k+1]
                d_ext = jnp.concatenate(
                    [d, jnp.zeros((S, 1), jnp.int32)], 1)
                committed = jnp.where(
                    ar < n_acc[:, None], d_ext,
                    jnp.where(ar == n_acc[:, None], bonus[:, None],
                              pad_id))                  # [S, k+1]
                # never commit past the slot's budget (end - ptr
                # tokens remain; runnable slots have at least 1)
                n_new = jnp.clip(n_acc + 1, 1,
                                 jnp.maximum(end - ptr, 1))
                n_new = jnp.where(active, n_new, 0)
                wrote = (ptr - 1)[:, None] + ar         # [S, k+1]
                valid = active[:, None] & jnp.ones_like(wrote, bool)
                pools_t = scatter_positions(
                    pools_t, rows, wrote,
                    take_positions(dense_t, wrote), valid=valid)
                pools_d = scatter_positions(
                    pools_d, rows, wrote,
                    take_positions(dense_d, wrote), valid=valid)
                return pools_t, pools_d, committed, n_new, \
                    jnp.where(active, n_acc, 0)

        attn = "paged" if self.paged else "dense"
        name = f"llm_decode_{attn}_{self.service}_S{S}_k{k}"
        self._program = compile_tracker.jit(run, name=name,
                                            **_donate_pools_kwargs())
        key = {"phase": "decode", "service": self.service, "slots": S,
               "spec_k": k, "attn": attn,
               "max_blocks": self.max_blocks,
               "block_len": self.kv.block_len,
               "encoder": _encoder_key(self.module),
               "draft": None if draft is None else _encoder_key(draft),
               "versions": aot.runtime_versions()}
        self._fps[name] = aot.fingerprints(key, [], [])
        return self._program

    def aot_fingerprints(self) -> dict:
        return dict(self._fps)

    @property
    def runnable(self) -> np.ndarray:
        """Slots that should actually decode this step: active AND
        budget remaining (a 1-token sequence is complete the moment its
        prefill-produced first token lands)."""
        return self.active & (self.ptr < self.end)

    def step(self) -> dict:
        """One decode step over every runnable slot. Returns
        ``slot -> (tokens_committed list, n_accepted)``; the caller
        commits tokens, advances the block table, and retires finished
        sequences."""
        import jax.numpy as jnp
        runnable = self.runnable
        if not runnable.any():
            return {}
        # capacity for this step's writes: positions up to ptr-1+k
        for s in range(self.slots):
            if runnable[s]:
                self.kv.ensure_capacity(self.seq_ids[s],
                                        int(self.ptr[s]) + self.spec_k)
        rows = self.kv.block_rows(
            [sid if runnable[i] else None
             for i, sid in enumerate(self.seq_ids)], self.max_blocks)
        prog = self._build()
        t0 = time.perf_counter()
        pools_t, pools_d, committed, n_new, n_acc = prog(
            self.variables["params"],
            None if self.draft_module is None
            else self.draft_variables["params"],
            self.pools.target, self.pools.draft, jnp.asarray(rows),
            jnp.asarray(self.last), jnp.asarray(self.ptr),
            jnp.asarray(self.end), jnp.asarray(runnable))
        self._h_attn.observe(time.perf_counter() - t0,
                             service=self.service, phase="decode")
        if not self.paged:
            # the fallback's whole cost, made loud: these bytes are
            # exactly what the paged kernel does not move
            self._c_gather.inc(self._gather_bytes,
                               service=self.service, phase="decode")
        self.pools.target = pools_t
        if self.draft_module is not None:
            self.pools.draft = pools_d
        committed = np.asarray(committed)
        n_new = np.asarray(n_new)
        n_acc = np.asarray(n_acc)
        out = {}
        for s in range(self.slots):
            if not runnable[s]:
                continue
            n = int(n_new[s])
            toks = [int(t) for t in committed[s, :n]]
            self.kv.advance(self.seq_ids[s], n)
            self.ptr[s] += n
            self.last[s] = toks[-1]
            out[s] = (toks, int(n_acc[s]))
        return out

    def warm(self) -> None:
        """Run the step program once against the trash block (all slots
        inactive — every write lands in block 0) — the warmup before
        ``mark_steady``."""
        import jax.numpy as jnp
        prog = self._build()
        S = self.slots
        args = (
            self.variables["params"],
            None if self.draft_module is None
            else self.draft_variables["params"],
            self.pools.target, self.pools.draft,
            jnp.zeros((S, self.max_blocks), jnp.int32),
            jnp.zeros(S, jnp.int32), jnp.ones(S, jnp.int32),
            jnp.full(S, 2, jnp.int32), jnp.zeros(S, bool))
        # attribution must lower BEFORE the call: donation invalidates
        # the pool buffers the args reference
        _attribute_warm(prog, self.service, *args)
        pools_t, pools_d, *_ = prog(*args)
        self.pools.target = pools_t
        if self.draft_module is not None:
            self.pools.draft = pools_d


# ------------------------------------------------------------------ engine

@dataclass
class _SeqMeta:
    prompt: list
    max_new_tokens: int
    t_submit: float
    slot: int | None = None
    t_first: float | None = None
    first_token: int | None = None
    reused_tokens: int = 0
    prefill_tokens: int = 0
    decode_steps: int = 0
    generated: list = field(default_factory=list)


class LLMEngine:
    """The assembled serving engine: paged KV pool + prefill executor +
    decode executor + continuous-batching scheduler.

    Greedy-only (``dl.generate`` temperature-0 semantics — the output
    contract is token identity with ``generate``); sampled speculative
    serving needs the rejection-sampling correction wired per slot and
    is out of scope here (``dl.speculative`` has the batched version).

    ``submit`` then ``step`` at boundaries (or ``run_until_drained``):
    each boundary admits pending sequences through the scheduler
    (shedding expired deadlines), prefills their suffixes in bucketed
    batches, hands off to decode through the load-aware queue, and runs
    one decode step. ``warm()`` precompiles both phases and declares
    CompileTracker steady state."""

    def __init__(self, module, variables, *, draft_module=None,
                 draft_variables=None, slots: int = 2,
                 block_len: int = 8, max_seq_len: int = 128,
                 num_blocks: int | None = None, spec_k: int = 0,
                 pad_id: int = 0, prefill_batch: int = 2,
                 hbm_fraction: float = 0.5, service: str = "llm",
                 registry=None, clock=time.monotonic):
        from ..dl.paged_kv import blocks_for_hbm_budget
        reg = registry if registry is not None else _default_registry
        self.module = module
        self.variables = variables
        self.pad_id = int(pad_id)
        self.service = service
        self.clock = clock
        self.max_seq_len = int(max_seq_len)
        self.block_len = int(block_len)
        self.max_blocks = -(-self.max_seq_len // self.block_len)
        enc = module.encoder
        hd = enc.width // enc.heads
        block_bytes = (2 * enc.depth * self.block_len * enc.heads * hd
                       * np.dtype(enc.dtype).itemsize)
        if num_blocks is None:
            # HBM-derived sizing with a host/CPU fallback generous
            # enough for the slot count
            num_blocks = blocks_for_hbm_budget(
                block_bytes, fraction=hbm_fraction,
                default=1 + 2 * slots * self.max_blocks)
        self.kv = PagedKVManager(
            num_blocks, self.block_len,
            block_budget=blocks_for_hbm_budget(
                block_bytes, fraction=hbm_fraction,
                default=num_blocks - 1),
            service=service, registry=reg)
        self.pools = _PoolState(
            init_pools(enc, num_blocks, self.block_len),
            None if draft_module is None else init_pools(
                draft_module.encoder, num_blocks, self.block_len))
        self.sched = SlotScheduler(slots, service=service,
                                   registry=reg, clock=clock)
        self.prefiller = PrefillExecutor(
            module, variables, self.kv, self.pools,
            draft_module=draft_module, draft_variables=draft_variables,
            max_blocks=self.max_blocks, batch=prefill_batch,
            pad_id=pad_id, service=service, registry=reg)
        self.decoder = DecodeExecutor(
            module, variables, self.kv, self.pools,
            draft_module=draft_module, draft_variables=draft_variables,
            slots=slots, max_blocks=self.max_blocks, spec_k=spec_k,
            pad_id=pad_id, service=service, registry=reg)
        self.handoff = HandoffQueue()
        self._meta: dict = {}
        self._to_prefill: list = []
        self._first_credit: dict = {}
        self._done: dict = {}
        self.expired: list = []
        self._spec_acc = [0, 0]     # accepted, offered
        self._h_ttft = reg.histogram(
            "gen_ttft_seconds",
            "submit→first-token latency, by service and prefix reuse",
            buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5,
                     1., 2.5, 5., 10.))
        self._c_tokens = reg.counter(
            "gen_tokens_total", "generated tokens committed, by service")
        self._c_steps = reg.counter(
            "gen_decode_steps_total", "decode steps executed, by service")
        self._g_accept = reg.gauge(
            "gen_spec_accept_ratio",
            "rolling fraction of offered draft tokens accepted, "
            "by service")
        self._c_spec_rejected = reg.counter(
            "gen_spec_rejected_total",
            "offered draft tokens rejected at verification, by service "
            "— target-model work the speculative gamble threw away "
            "(the goodput ledger prices it at the measured "
            "seconds-per-token)")

    # -- intake ------------------------------------------------------------
    def submit(self, seq_id, prompt, max_new_tokens: int,
               deadline: float | None = None) -> None:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if len(prompt) + int(max_new_tokens) > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len="
                f"{self.max_seq_len}")
        self._meta[seq_id] = _SeqMeta(prompt=prompt,
                                      max_new_tokens=int(max_new_tokens),
                                      t_submit=self.clock())
        self.sched.offer(seq_id, prompt, max_new_tokens,
                         deadline=deadline)

    # -- one step boundary --------------------------------------------------
    def step(self) -> list:
        """Admit → prefill → handoff → decode. Returns ``(seq_id,
        tokens)`` pairs (full sequence: prompt then generated) finished
        at this boundary."""
        for a in self.sched.admit():
            self._to_prefill.append(a)
        for seq_id in self.sched.drain_expired():
            self._meta.pop(seq_id, None)
            self.expired.append(seq_id)
        self._run_prefill()
        for payload in self.handoff.pull(self.decoder.free_slots):
            meta = self._meta[payload["seq"]["seq_id"]]
            slot = self.decoder.activate(meta.slot, payload)
            meta.slot = slot
            meta.first_token = int(payload["first"])
            # the prefill-produced first token spends 1 of the slot's
            # budget; credit it at this boundary's scheduler step
            self._first_credit[slot] = 1
        finished = []
        results = self.decoder.step()
        if results:
            self._c_steps.inc(1, service=self.service)
        tokens_by_slot = dict(self._first_credit)
        self._first_credit = {}
        for slot, (toks, n_acc) in results.items():
            seq_id = self.decoder.seq_ids[slot]
            meta = self._meta[seq_id]
            meta.generated.extend(toks)
            meta.decode_steps += 1
            tokens_by_slot[slot] = tokens_by_slot.get(slot, 0) \
                + len(toks)
            self._c_tokens.inc(len(toks), service=self.service)
            if self.decoder.spec_k:
                self._spec_acc[0] += n_acc
                self._spec_acc[1] += self.decoder.spec_k
                rejected = self.decoder.spec_k - n_acc
                if rejected > 0:
                    self._c_spec_rejected.inc(rejected,
                                              service=self.service)
        if self._spec_acc[1]:
            self._g_accept.set(self._spec_acc[0] / self._spec_acc[1],
                               service=self.service)
        active = self.sched.active_slots
        if active:
            # sequences still in prefill/handoff hold scheduler slots
            # but committed nothing this step
            for slot in active:
                tokens_by_slot.setdefault(slot, 0)
            for seq_id, slot in self.sched.step(tokens_by_slot):
                if self.decoder.active[slot] and \
                        self.decoder.seq_ids[slot] == seq_id:
                    self.decoder.deactivate(slot)
                finished.append((seq_id, self._finish(seq_id)))
        return finished

    def _run_prefill(self) -> None:
        ready = []
        still_stalled = []
        for a in self._to_prefill:
            try:
                h = self.kv.allocate(a.seq_id, a.prompt)
            except OutOfBlocks:
                # pool saturated: the slot idles (0-token step entries)
                # until decode completions release blocks
                still_stalled.append(a)
                continue
            meta = self._meta[a.seq_id]
            meta.slot = a.slot
            meta.reused_tokens = h.reused_tokens
            ready.append(a)
        self._to_prefill = still_stalled
        if not ready:
            return
        firsts = self.prefiller.prefill(
            [(a.seq_id, a.prompt) for a in ready])
        now = self.clock()
        for a in ready:
            first, suffix_len = firsts[a.seq_id]
            meta = self._meta[a.seq_id]
            meta.t_first = now
            meta.prefill_tokens = suffix_len
            self._h_ttft.observe(
                now - meta.t_submit, service=self.service,
                reuse="warm" if meta.reused_tokens else "cold")
            self.handoff.push({
                "seq": self.kv.export_seq(a.seq_id),
                "first": first,
                "max_new_tokens": a.max_new_tokens,
            })

    def _finish(self, seq_id) -> np.ndarray:
        meta = self._meta.pop(seq_id)
        self.kv.release(seq_id)
        total_len = min(len(meta.prompt) + 1 + len(meta.generated),
                        len(meta.prompt) + meta.max_new_tokens)
        a_flops, a_bytes = cost_attribution.service_cost(self.service)
        feature_log.record(
            service=self.service, route="decode",
            batch=self.decoder.slots,
            bucket=_bucket_window(len(meta.prompt)),
            queue_depth=self.sched.pending_count,
            decode_steps=meta.decode_steps,
            prefill_tokens=meta.prefill_tokens,
            context_blocks=-(-total_len // self.block_len),
            execute_ms=(self.clock() - meta.t_submit) * 1e3,
            analytic_flops=a_flops, analytic_bytes=a_bytes)
        # prompt + [prefill's first token] + decode commits, trimmed to
        # the budget (a final speculative burst can overshoot by 0 —
        # the decode step clamps — but trim defensively anyway)
        full = meta.prompt + [int(meta.first_token)] + \
            [int(t) for t in meta.generated]
        return np.asarray(full[:len(meta.prompt) + meta.max_new_tokens],
                          np.int32)

    # -- warmup / acceptance -----------------------------------------------
    def warm(self, prefill_windows=(1,), mark_steady: bool = True
             ) -> dict:
        """Precompile both phases (prefill for the given window
        buckets, the decode step) and optionally declare CompileTracker
        steady state. Returns the union of both executors' AOT
        fingerprints."""
        self.prefiller.warm(prefill_windows)
        self.decoder.warm()
        if mark_steady:
            compile_tracker.mark_steady()
        return {**self.prefiller.aot_fingerprints(),
                **self.decoder.aot_fingerprints()}

    def run_until_drained(self) -> dict:
        """Step until every submitted sequence completes or expires;
        returns ``seq_id -> [prompt + generated] int32 array``."""
        stalled = 0
        while self.sched.busy or self._to_prefill or len(self.handoff):
            before = len(self._done)
            for seq_id, toks in self.step():
                self._done[seq_id] = toks
            # deadlock guard: prefill permanently out of blocks with no
            # in-flight decode to release any is unrecoverable
            if len(self._done) == before and self._to_prefill and \
                    not self.decoder.active.any() and \
                    not len(self.handoff):
                stalled += 1
                if stalled > 3:
                    raise OutOfBlocks(
                        f"{len(self._to_prefill)} sequence(s) cannot "
                        "allocate KV blocks and no in-flight decode "
                        "can release any — the pool is too small for "
                        "this workload")
            else:
                stalled = 0
        out, self._done = self._done, {}
        return out
