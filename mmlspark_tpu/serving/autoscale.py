"""Obs-driven autoscaler: grow/shrink compute workers from live signals.

The mesh can shed, retry, and survive worker death — but its capacity
is whatever was started by hand. This module closes the loop: a control
thread reads the signals the rest of the stack already publishes into
the obs registry (queue depth, per-tenant EWMA latency vs its SLO tier,
open circuit breakers, detected worker deaths) and drives a worker pool
toward the load.

Design rules (each one is a production scar, not a preference):

- **Hysteresis**: a direction must hold for ``up_stable`` /
  ``down_stable`` consecutive evaluations before acting — a breaker
  flapping half-open or one bursty second must not thrash the pool.
- **Cooldown**: after any scale action, no further action for
  ``cooldown`` seconds — the new capacity needs time to show up in the
  very signals being read, or the loop chases its own wake.
- **Repair is not scaling**: a detected worker death is replaced
  immediately, bypassing hysteresis AND cooldown — restoring capacity
  the plan already called for must not wait out a window that exists to
  damp *decisions*.
- **Drain, never kill**: scale-down asks the pool to *drain* a worker.
  For mesh workers (:class:`ComputeWorkerPool`) that sets the worker's
  stop event: ``remote_worker_loop`` finishes and replies its current
  lease, then unregisters; anything it somehow strands is replayed by
  the ingest servers' existing lease-replay path. In-flight work is
  never lost to a scaling decision.
- **Monotonic time only** (:func:`sched.policy.now`): cooldown and
  event arithmetic must not jump with wall-clock steps (graftcheck's
  wallclock-deadline pass gates this file).

The pool is duck-typed (``count()`` / ``scale_up()`` /
``scale_down()``), so the same :class:`Autoscaler` drives real mesh
workers, subprocess pools, and the synthetic pools the
``mixed_tenant_scenario`` acceptance uses.

Import is stdlib + obs + sched only — no JAX, no device (the CI smoke
asserts it).
"""

from __future__ import annotations

import logging
import threading
import uuid
from dataclasses import dataclass, field

from ..obs import registry as _default_registry
from ..obs.fleet import render_sample
from ..obs.timeseries import TimeSeriesStore, timeseries_store
from ..sched.policy import now

_LOG = logging.getLogger("mmlspark_tpu.serving")

__all__ = ["AutoscaleConfig", "AutoscaleSignals", "Autoscaler",
           "ComputeWorkerPool"]


@dataclass
class AutoscaleConfig:
    """Knobs for :class:`Autoscaler` (see docs/serving.md "Tenancy,
    SLO tiers & autoscaling")."""

    min_workers: int = 1
    max_workers: int = 8
    interval: float = 0.5      # evaluation cadence seconds
    queue_high: float = 8.0    # queued requests PER WORKER → overload
    queue_low: float = 1.0     # queued requests per worker → idle
    slo_high: float = 0.9      # max tenant EWMA/SLO ratio → overload
    slo_low: float = 0.5       # below this (and queue_low) → idle
    up_stable: int = 2         # consecutive overloaded ticks before up
    down_stable: int = 4       # consecutive idle ticks before down
    cooldown: float = 5.0      # seconds after an action with no action
    step: int = 1              # workers added/removed per action
    # -- predictive capacity (ISSUE 12): scale on where the load is
    # GOING, not where it is, so scale-up LEADS the diurnal curve.
    # The depth trend over the last history_ticks evaluations is
    # extrapolated lead_ticks ahead; predicted pressure feeds the same
    # hysteresis machinery as measured pressure (a noisy slope still
    # cannot thrash the pool). wait_high (seconds) additionally prices
    # the predicted backlog through the learned cost model: when the
    # predicted per-worker drain time exceeds it, that is overload even
    # below the raw depth threshold. 0 = depth-only.
    predictive: bool = False
    lead_ticks: int = 4        # evaluation intervals to extrapolate
    history_ticks: int = 8     # trend window, in evaluations
    wait_high: float = 0.0     # predicted per-worker drain s → overload


@dataclass
class AutoscaleSignals:
    """One evaluation's inputs (registry-read by default; injectable
    for tests and synthetic scenarios)."""

    queue_depth: float = 0.0
    slo_pressure: float = 0.0   # max tenant EWMA latency / SLO deadline
    breakers_open: int = 0      # open/half-open breakers in the process
    worker_deaths: float = 0.0  # CUMULATIVE detected-death count
    stragglers: float = 0.0     # currently-flagged fleet_straggler ranks


@dataclass
class AutoscaleEvent:
    """One acted decision (the scenario asserts on these)."""

    t: float                    # monotonic timestamp
    direction: str              # up | down | replace
    workers: int                # pool size AFTER the action
    reason: str = ""


class Autoscaler:
    """The control loop: evaluate signals, decide, drive the pool.

    ``pool`` must expose ``count() -> int`` (live, non-draining
    workers), ``scale_up() -> worker_id`` and ``scale_down() ->
    worker_id | None`` (pick a victim and START draining it — the call
    must not block on the drain). ``tenancy`` (optional,
    :class:`~..sched.tenancy.Tenancy`) supplies SLO pressure;
    ``signals`` (optional callable → :class:`AutoscaleSignals`)
    replaces the registry reads entirely. ``item_seconds`` (optional
    zero-arg callable → per-item service seconds or None — typically
    the scheduler estimator's cost-model-backed ``item_seconds``)
    prices the predicted backlog when ``config.predictive`` is on; a
    cold model returns None and the loop degrades to depth thresholds,
    never to a stale price.
    """

    def __init__(self, service: str, pool,
                 config: AutoscaleConfig | None = None, *,
                 registry=None, tenancy=None, signals=None,
                 item_seconds=None, store=None):
        reg = registry if registry is not None else _default_registry
        self.service = service
        self.pool = pool
        self.config = config or AutoscaleConfig()
        self.tenancy = tenancy
        self._signals = signals
        self._item_seconds = item_seconds
        # depth trend lives in the time-series store (ISSUE 16): the
        # same window /debug/timeline serves is the one the slope is
        # fit over. Private registry → private store, so tests and
        # scenarios never share trend history through the singleton.
        self._store = (store if store is not None
                       else timeseries_store if registry is None
                       else TimeSeriesStore(reg))
        self._depth_series = render_sample(
            "autoscale_depth", {"service": service})
        self._store.ensure(self._depth_series,
                           maxlen=max(int(self.config.history_ticks), 2),
                           retention_s=86400.0)
        self._tick_i = 0
        self._registry = reg
        self.events: list[AutoscaleEvent] = []
        self._lock = threading.Lock()
        self._desired = max(self.config.min_workers, 0)
        self._cooldown_until = 0.0
        self._up_streak = 0
        self._down_streak = 0
        self._deaths_seen = 0.0
        self._straggler_level = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._g_workers = reg.gauge(
            "autoscale_workers", "live compute workers, by service")
        self._g_desired = reg.gauge(
            "autoscale_desired", "the autoscaler's target, by service")
        self._c_events = reg.counter(
            "autoscale_events_total",
            "acted scale decisions, by service/direction "
            "(up | down | replace)")
        self._c_blocked = reg.counter(
            "autoscale_blocked_total",
            "actionable pressure NOT acted on, by service/reason "
            "(cooldown | hysteresis | limit)")
        self._g_pred = reg.gauge(
            "autoscale_predicted_depth",
            "trend-extrapolated queue depth lead_ticks ahead, by service")
        self._c_pred = reg.counter(
            "autoscale_predictive_total",
            "overload pressure that fired on PREDICTED load before the "
            "raw thresholds did, by service")

    # -- signal acquisition --------------------------------------------------
    def read_signals(self) -> AutoscaleSignals:
        """Default signal source: the process-wide obs registry — the
        same series operators watch, so the loop scales on exactly what
        the dashboards show."""
        if self._signals is not None:
            return self._signals()
        snap = self._registry.snapshot()
        svc = f'service="{self.service}"'
        svc_sub = f'service="{self.service}#'  # e.g. <svc>#compute
        queue = sum(v for k, v in snap.items()
                    if k.startswith("sched_queue_depth{") and svc in k)
        deaths = sum(v for k, v in snap.items()
                     if k.startswith("resilience_worker_deaths_total")
                     and (svc in k or svc_sub in k))
        # only THIS service's mesh breakers (endpoints are
        # mesh:<service>:<worker> / mesh:<service>:ingest:<id>): an
        # unrelated service's stuck-open breaker in the same process
        # must not veto this pool's scale-down forever
        mesh = f"mesh:{self.service}:"
        breakers = sum(1 for k, v in snap.items()
                       if k.startswith("resilience_breaker_state")
                       and mesh in k and v >= 1.0)
        pressure = (self.tenancy.slo_pressure()
                    if self.tenancy is not None else 0.0)
        # fleet health (obs.fleet): ranks currently flagged straggler.
        # The gauge is keyed by worker/process, not service — one sick
        # rank degrades the whole fleet's step time, so every pool
        # sharing the process reads the same count.
        stragglers = sum(1 for k, v in snap.items()
                         if k.startswith("fleet_straggler{") and v >= 1.0)
        return AutoscaleSignals(queue_depth=queue, slo_pressure=pressure,
                                breakers_open=breakers,
                                worker_deaths=deaths,
                                stragglers=stragglers)

    # -- the decision --------------------------------------------------------
    def tick(self, signals: AutoscaleSignals | None = None) -> str:
        """One evaluation. Returns the decision taken: ``up`` /
        ``down`` / ``replace`` / ``cooldown`` (actionable pressure
        suppressed) / ``hold``."""
        cfg = self.config
        s = signals if signals is not None else self.read_signals()
        t = now()
        n = self.pool.count()
        self._g_workers.set(n, service=self.service)
        died = s.worker_deaths > self._deaths_seen
        self._deaths_seen = max(self._deaths_seen, s.worker_deaths)
        if n < self._desired and (died or n < cfg.min_workers):
            # repair: restore capacity the plan already called for —
            # bypasses hysteresis and cooldown (see module docstring)
            while self.pool.count() < self._desired:
                self.pool.scale_up()
            self._record("replace", t, "worker death detected")
            return "replace"
        if (s.stragglers > self._straggler_level
                and n < cfg.max_workers):
            # straggler replace (obs.fleet): a sick-but-alive rank was
            # flagged — add replacement capacity immediately (rising
            # edge only; bypasses hysteresis like the death path).
            # Routing already deprioritizes the flagged worker
            # (pick_least_loaded), and normal scale-down drains the
            # excess once the rank recovers.
            self._straggler_level = s.stragglers
            self.pool.scale_up()
            self._desired = max(self._desired, self.pool.count())
            self._record("replace", t, "straggler flagged")
            return "replace"
        self._straggler_level = min(self._straggler_level, s.stragglers)
        over = (s.queue_depth > cfg.queue_high * max(n, 1)
                or s.slo_pressure > cfg.slo_high)
        # an open breaker means some endpoint is sick: it VETOES
        # scale-down (idle signals may just mean traffic is failing
        # fast) but does not itself scale up — hysteresis absorbs flaps
        under = (s.queue_depth < cfg.queue_low * max(n, 1)
                 and s.slo_pressure < cfg.slo_low
                 and s.breakers_open == 0)
        if cfg.predictive:
            # predictive capacity (ISSUE 12): extrapolate the depth
            # trend lead_ticks ahead; predicted pressure runs through
            # the SAME hysteresis/cooldown machinery as measured
            # pressure, so it buys lead time, not thrash
            self._tick_i += 1
            self._store.append(self._depth_series, s.queue_depth)
            pred = self._predict_depth(s.queue_depth)
            self._g_pred.set(pred, service=self.service)
            over_pred = pred > cfg.queue_high * max(n, 1)
            if not over_pred and cfg.wait_high > 0:
                item_s = self._predicted_item_seconds()
                if item_s:
                    # the learned price: predicted backlog drain time
                    # per worker — overload before the raw depth
                    # threshold when requests are expensive
                    over_pred = (pred * item_s / max(n, 1)
                                 > cfg.wait_high)
            if over_pred and not over:
                self._c_pred.inc(1, service=self.service)
            over = over or over_pred
            # and never walk capacity down INTO a predicted rise
            under = under and pred < cfg.queue_low * max(n, 1)
        self._up_streak = self._up_streak + 1 if over else 0
        self._down_streak = self._down_streak + 1 if under else 0
        if t < self._cooldown_until:
            if over or under:
                self._c_blocked.inc(1, service=self.service,
                                    reason="cooldown")
                return "cooldown"
            return "hold"
        if over:
            if self._up_streak < cfg.up_stable:
                self._c_blocked.inc(1, service=self.service,
                                    reason="hysteresis")
                return "hold"
            if n >= cfg.max_workers:
                self._c_blocked.inc(1, service=self.service,
                                    reason="limit")
                return "hold"
            for _ in range(min(cfg.step, cfg.max_workers - n)):
                self.pool.scale_up()
            self._after_action(t)
            self._record("up", t, f"depth={s.queue_depth:.0f} "
                                  f"slo={s.slo_pressure:.2f}")
            return "up"
        if under:
            if self._down_streak < cfg.down_stable:
                self._c_blocked.inc(1, service=self.service,
                                    reason="hysteresis")
                return "hold"
            if n <= cfg.min_workers:
                return "hold"
            for _ in range(min(cfg.step, n - cfg.min_workers)):
                self.pool.scale_down()
            self._after_action(t)
            self._record("down", t, f"depth={s.queue_depth:.0f}")
            return "down"
        return "hold"

    def _predict_depth(self, depth: float) -> float:
        """Least-squares depth slope per tick over the history window
        (read back from the time-series store — sample index is the x
        axis, so wall-clock jitter between evaluations cannot tilt the
        fit), extrapolated ``lead_ticks`` ahead (clamped at zero).
        Under 3 samples there is no trend — predicted = measured."""
        h = list(enumerate(
            v for _, v in self._store.last_n(
                self._depth_series, max(int(self.config.history_ticks), 2))))
        if len(h) < 3:
            return depth
        n = len(h)
        mt = sum(t for t, _ in h) / n
        md = sum(d for _, d in h) / n
        num = sum((t - mt) * (d - md) for t, d in h)
        den = sum((t - mt) ** 2 for t, _ in h)
        if den <= 0:
            return depth
        slope = num / den
        return max(depth + slope * self.config.lead_ticks, 0.0)

    def _predicted_item_seconds(self) -> float | None:
        if self._item_seconds is None:
            return None
        try:
            v = self._item_seconds()
            return v if v and v > 0 else None
        except Exception:  # a bad price must not kill the loop
            return None

    def _after_action(self, t: float) -> None:
        self._desired = self.pool.count()
        self._cooldown_until = t + self.config.cooldown
        self._up_streak = self._down_streak = 0

    def _record(self, direction: str, t: float, reason: str) -> None:
        n = self.pool.count()
        self._desired = max(self._desired, self.config.min_workers)
        with self._lock:
            self.events.append(AutoscaleEvent(t=t, direction=direction,
                                              workers=n, reason=reason))
        self._c_events.inc(1, service=self.service, direction=direction)
        self._g_workers.set(n, service=self.service)
        self._g_desired.set(self._desired, service=self.service)

    def event_log(self) -> list[AutoscaleEvent]:
        with self._lock:
            return list(self.events)

    # -- lifecycle -----------------------------------------------------------
    def ensure_min(self) -> None:
        """Bring the pool up to ``min_workers`` (called by start)."""
        while self.pool.count() < self.config.min_workers:
            self.pool.scale_up()
        self._desired = max(self.pool.count(), self.config.min_workers)
        self._g_desired.set(self._desired, service=self.service)

    def start(self) -> "Autoscaler":
        self.ensure_min()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.config.interval):
            try:
                self.tick()
            except Exception:  # a bad read must not kill the loop
                _LOG.warning("autoscaler tick failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


@dataclass
class _PoolWorker:
    thread: threading.Thread
    stop: threading.Event
    started: float = 0.0
    draining: bool = field(default=False)


class ComputeWorkerPool:
    """An autoscalable pool of ``remote_worker_loop`` compute workers.

    Each ``scale_up`` spawns one worker thread running the standard
    lease-pull loop (heartbeats under ``<service>#compute``, identified
    lease pulls, per-ingest breakers — everything the resilience layer
    already provides). ``scale_down`` picks the NEWEST non-draining
    worker and sets its stop event: the loop finishes and replies its
    current lease round, then unregisters — and if it strands anything,
    the ingest servers' lease-replay path answers it on a survivor.
    Worker ids are stable (``<prefix>-w<N>``) so fault rules can target
    one by substring match.

    ``transform_factory`` (optional) builds a FRESH transform per
    ``scale_up`` — the honest model of a scale-up event, where the new
    worker is a new process with cold jit caches. Each fresh transform
    warm-loads the AOT executable store inside ``remote_worker_loop``
    (``core/aot.py``, ``docs/aot.md``), so an autoscaler-added worker's
    first request pays a store load, not a compile storm. Without a
    factory every worker shares ``transform_fn`` (and its already-
    warmed segments) — fine when threads stand in for one process's
    capacity, dishonest as a scale-up benchmark.

    ``version_router`` (deploy plane, ``serving.deploy``) supersedes
    both: a worker the autoscaler adds MID-DEPLOY must serve the
    version that is active at spawn time — not whatever transform the
    pool was built with — or a scale-up during a rollout silently
    un-flips part of the fleet. The router's ``active_transform`` is
    read per ``scale_up``, and the worker loop AOT-warms it like any
    factory-built transform.
    """

    def __init__(self, driver_address, service: str, transform_fn=None,
                 *, transform_factory=None, version_router=None,
                 max_batch: int = 64,
                 heartbeat_interval: float = 0.25,
                 mesh_secret: str = "", prefix: str | None = None):
        if transform_fn is None and transform_factory is None \
                and version_router is None:
            raise ValueError("ComputeWorkerPool needs transform_fn, "
                             "transform_factory, or version_router")
        self.driver_address = driver_address
        self.service = service
        self.transform_fn = transform_fn
        self.transform_factory = transform_factory
        self.version_router = version_router
        self.max_batch = max_batch
        self.heartbeat_interval = heartbeat_interval
        self.mesh_secret = mesh_secret
        self.prefix = prefix or f"pool-{uuid.uuid4().hex[:6]}"
        self._lock = threading.Lock()
        self._workers: dict[str, _PoolWorker] = {}
        self._seq = 0

    def count(self) -> int:
        """Live, non-draining workers (capacity the scheduler can use)."""
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if w.thread.is_alive() and not w.draining)

    def worker_ids(self) -> list[str]:
        with self._lock:
            return [wid for wid, w in self._workers.items()
                    if w.thread.is_alive() and not w.draining]

    def scale_up(self) -> str:
        from .distributed import remote_worker_loop
        # a factory means "fresh worker, cold caches": build its
        # transform before taking the lock (compiles/store loads must
        # not serialize the pool). A version router wins outright —
        # the new worker must honor the ACTIVE version at spawn time
        # (scale-up mid-deploy must not resurrect the old model)
        if self.version_router is not None:
            fn = self.version_router.active_transform() \
                or self.transform_fn
        elif self.transform_factory is not None:
            fn = self.transform_factory()
        else:
            fn = self.transform_fn
        with self._lock:
            wid = f"{self.prefix}-w{self._seq}"
            self._seq += 1
            stop = threading.Event()
            th = threading.Thread(
                target=remote_worker_loop,
                args=(self.driver_address, self.service, fn),
                kwargs={"stop_event": stop, "max_batch": self.max_batch,
                        "heartbeat_interval": self.heartbeat_interval,
                        "mesh_secret": self.mesh_secret,
                        "worker_id": wid},
                daemon=True, name=f"compute-{wid}")
            self._workers[wid] = _PoolWorker(thread=th, stop=stop,
                                             started=now())
            th.start()
        # HBM watermark at the scale-up event (obs.memory): the new
        # worker's warm boot shows its device-memory cost next to its
        # latency cost (mem_event_watermark_bytes{event="scale_up"})
        from ..obs.memory import memory_profiler
        memory_profiler.note_event("scale_up")
        return wid

    def scale_down(self) -> str | None:
        """Start draining the newest non-draining worker (LIFO: the
        oldest workers keep their warmed caches/breaker state)."""
        with self._lock:
            candidates = [(w.started, wid) for wid, w in
                          self._workers.items()
                          if w.thread.is_alive() and not w.draining]
            if not candidates:
                return None
            _, wid = max(candidates)
            self._workers[wid].draining = True
            self._workers[wid].stop.set()
        return wid

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.stop.set()
        for w in workers:
            w.thread.join(timeout=timeout)
