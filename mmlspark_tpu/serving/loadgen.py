"""Native load generator binding — honest loaded-tail measurement.

A Python ``http.client`` worker costs ~0.25 ms of GIL-held work per
request, so a 16-way closed loop caps at ~4k req/s CLIENT-side and the
"loaded p99" mostly measures the load generator (which also steals the
GIL from the very server under test). ``loadgen.cpp`` drives the same
closed loop from C++ threads (keep-alive, TCP_NODELAY, strict
request-response); this module shapes its raw latencies into the same
percentile summary the benches bank.

No reference counterpart — the reference's serving perf narrative
(``docs/mmlspark-serving.md``) relied on external load tooling.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..native.loader import NativeLoader

_loader = NativeLoader("loadgen", ["loadgen.cpp"])


def run_load(host: str, port: int, payload: bytes, *, nconn: int = 16,
             nreq: int = 300, path: str = "/",
             warmup: int = 20) -> dict:
    """Closed-loop load: ``nconn`` keep-alive connections, ``nreq``
    serial POSTs each. Returns ``{p50_ms, p99_ms, loaded_p99_ms,
    throughput_rps, errors}`` where ``loaded_p99_ms`` is the max over
    connections of the per-connection p99 (the benches' loaded-tail
    semantics). Percentiles and throughput cover requests that
    completed an HTTP round trip (non-200 replies included — they are
    also counted in ``errors``); transport failures are excluded from
    both. Raises when nothing could connect."""
    lib = _loader.load()
    lib.lg_run.restype = ctypes.c_long
    lib.lg_run.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double)]
    lat = np.empty(nconn * nreq, np.float64)
    wall = ctypes.c_double(0.0)
    errors = int(lib.lg_run(
        host.encode(), int(port), int(nconn), int(nreq), path.encode(),
        payload, len(payload),
        lat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(wall)))
    if errors < 0:
        raise RuntimeError("loadgen: no connection could be "
                           "established")
    lat = lat.reshape(nconn, nreq)
    steady = lat[:, warmup:] if nreq > warmup else lat
    ok = steady[steady >= 0]
    if ok.size == 0:
        raise RuntimeError("loadgen: every request failed")
    per_conn_p99 = [float(np.percentile(row[row >= 0], 99))
                    for row in steady if (row >= 0).any()]
    done = int((lat >= 0).sum())
    return {
        "p50_ms": float(np.percentile(ok, 50)),
        "p99_ms": float(np.percentile(ok, 99)),
        "loaded_p99_ms": max(per_conn_p99),
        "throughput_rps": done / max(wall.value, 1e-9),
        "errors": errors,
    }
