"""Native load generator binding — honest loaded-tail measurement.

A Python ``http.client`` worker costs ~0.25 ms of GIL-held work per
request, so a 16-way closed loop caps at ~4k req/s CLIENT-side and the
"loaded p99" mostly measures the load generator (which also steals the
GIL from the very server under test). ``loadgen.cpp`` drives the same
closed loop from C++ threads (keep-alive, TCP_NODELAY, strict
request-response); this module shapes its raw latencies into the same
percentile summary the benches bank.

With ``retry=True`` the client honors ``Retry-After`` on 429/503 sheds
with ONE bounded re-attempt per request (the resilience contract: back
off as told, re-offer once). Retried requests come back with status
``+1000`` (1200 = 200 on the re-attempt) and are reported as their own
``retried`` / ``retried_ok`` columns — retry traffic never blends into
the first-offer percentiles.

Trace correlation (obs subsystem): every request carries a
DETERMINISTIC traceparent — trace id ``<prefix><conn:4hex><req:8hex>``
— so the summary can reconstruct the trace ids of the p99-slowest
requests (``slowest`` column) and a bench outlier becomes a lookup key
into the server's flight recorder (``GET /debug/trace``).

Multi-tenant loads (sched.tenancy): ``run_load(..., tenants=[...])``
stamps ``X-Tenant`` per connection (lg_run5) and splits the summary
per tenant — a gold tenant's p99 and a best-effort tenant's shed rate
never blend into one column.

No reference counterpart — the reference's serving perf narrative
(``docs/mmlspark-serving.md``) relied on external load tooling.
"""

from __future__ import annotations

import ctypes
import uuid

import numpy as np

from ..native.loader import NativeLoader

_loader = NativeLoader("loadgen", ["loadgen.cpp"])

# statuses >= this mark a request answered on the bounded Retry-After
# re-attempt (loadgen.cpp encodes final_status + 1000)
_RETRIED_BASE = 1000


def trace_id_of(trace_prefix: str, conn: int, req: int) -> str:
    """The trace id loadgen.cpp stamped on request ``req`` of
    connection ``conn`` (the reconstruction contract both sides share)."""
    return f"{trace_prefix}{conn:04x}{req:08x}"


def _slowest_trace_ids(steady_lat: np.ndarray, ok: np.ndarray,
                       warmup_offset: int, trace_prefix: str,
                       top: int = 8) -> list[dict]:
    """Trace ids of the p99-slowest first-offer successes (at least the
    single slowest), slowest first — the flight-recorder lookup keys."""
    ci, ri = np.nonzero(ok)
    if not len(ci):
        return []
    lats = steady_lat[ci, ri]
    thr = float(np.percentile(lats, 99))
    order = np.argsort(-lats)
    picks = [j for j in order if lats[j] >= thr][:top] \
        or [int(order[0])]
    return [{"trace_id": trace_id_of(trace_prefix, int(ci[j]),
                                     int(ri[j]) + warmup_offset),
             "ms": round(float(lats[j]), 3)}
            for j in picks]


def summarize(lat: np.ndarray, status: np.ndarray, wall_s: float,
              warmup: int = 20, trace_prefix: str | None = None,
              tenants: list[str] | None = None,
              ttft: np.ndarray | None = None,
              versions=None) -> dict:
    """Shape raw per-request ``(latency_ms, http_status)`` matrices
    (connection-major ``[nconn, nreq]``; status -1 = transport failure,
    status >= 1000 = answered on a Retry-After re-attempt) into the
    bench summary. Split out so the shaping is testable without the
    native client.

    Success percentiles (``p50_ms``/``p99_ms``/``loaded_p99_ms``) cover
    ONLY first-offer 2xx round trips: a 429 shed answers in
    microseconds, so folding sheds into the latency columns would let
    an overloaded server look *faster* as it sheds more — and a retried
    request is not first-offer load, so it reports separately
    (``retried`` = re-attempts taken, ``retried_ok`` = re-attempts that
    landed 2xx). Non-2xx traffic is reported on its own — ``shed``
    (final outcome 429, whether on first offer or still shed on the
    re-attempt), ``rejected`` (other non-2xx), ``transport_errors`` —
    plus ``shed_rate`` over completed round trips; a shed that a
    re-attempt then answered counts in ``retried_ok``, not ``shed``.
    ``throughput_rps`` counts 2xx only (work actually served, retried
    or not); ``completed_rps`` keeps the old every-round-trip rate.

    ``tenants`` (one name per connection — lg_run5 stamps X-Tenant per
    connection) additionally splits the summary per tenant under a
    ``tenants`` key: mixed-workload bench numbers stay honest only if
    a gold tenant's p99 and a best-effort tenant's shed rate never
    blend into one column.

    ``ttft`` (generation mode — lg_run6's time-to-first-byte matrix,
    same connection-major shape and -1-on-failure convention as
    ``lat``) adds ``ttft_p50_ms``/``ttft_p99_ms`` over the SAME
    first-offer-success mask as the latency percentiles, globally and
    per tenant: an LLM front replies when the first token exists, so
    first-byte time is the client-observed time-to-first-token and the
    per-tenant split keeps a gold tenant's TTFT p99 honest under mixed
    load.

    ``versions`` (deploy plane — the ``X-Model-Version`` label each
    RESPONSE carried, connection-major like ``lat``; empty string =
    unversioned) splits p50/p99/error-rate per observed version under
    a ``versions`` key. Unlike the per-connection ``tenants`` row
    selection, a blue/green flip lands MID-connection, so this split
    is a per-request mask over the steady-state window — it is how a
    bench proves the flip from the client side (old version's
    percentiles before, new version's after, no error spike between)."""
    if not (status >= 0).any():
        raise RuntimeError("loadgen: every request failed")
    retried_all = status >= _RETRIED_BASE
    final = np.where(retried_all, status - _RETRIED_BASE, status)
    nreq = lat.shape[1]
    steady_lat = lat[:, warmup:] if nreq > warmup else lat
    steady_st = final[:, warmup:] if nreq > warmup else final
    steady_retried = retried_all[:, warmup:] if nreq > warmup \
        else retried_all
    ok = (steady_st >= 200) & (steady_st < 300) & ~steady_retried
    # an overloaded run can shed EVERYTHING: percentiles go NaN (there
    # is no success latency to report), the shed/rejected counts stand
    ok_lat = steady_lat[ok] if ok.any() else np.asarray([np.nan])
    ttft_ok = None
    if ttft is not None:
        steady_ttft = ttft[:, warmup:] if nreq > warmup else ttft
        good = ok & (steady_ttft >= 0)
        ttft_ok = steady_ttft[good] if good.any() \
            else np.asarray([np.nan])
    per_conn_p99 = [float(np.percentile(row[m], 99))
                    for row, m in zip(steady_lat, ok) if m.any()] \
        or [float("nan")]
    all_ok = (final >= 200) & (final < 300)
    completed = int((final >= 0).sum())
    # the FINAL outcome classifies: a request still shed on its bounded
    # re-attempt (1429) is a shed — excluding it would understate
    # shed_rate exactly when shedding is heaviest
    shed = int((final == 429).sum())
    slowest = [] if trace_prefix is None else _slowest_trace_ids(
        steady_lat, ok, warmup if nreq > warmup else 0, trace_prefix)
    by_tenant = {}
    if tenants:
        # tenant is constant per connection (lg_run5 stamps X-Tenant at
        # connect), so the split is a row selection on the
        # connection-major matrices — each tenant re-runs the same
        # shaping over its own rows (recursion bottoms out: the
        # sub-call passes tenants=None)
        for name in dict.fromkeys(tenants):   # stable unique order
            rows = [c for c, t in enumerate(tenants) if t == name]
            try:
                sub = summarize(lat[rows], status[rows], wall_s,
                                warmup=warmup,
                                ttft=None if ttft is None
                                else ttft[rows])
            except RuntimeError:
                # every one of this tenant's requests failed: report
                # the failure count rather than erasing the tenant
                sub = {"transport_errors":
                       int((status[rows] < 0).sum())}
            by_tenant[name] = {k: sub[k] for k in (
                "p50_ms", "p99_ms", "ttft_p50_ms", "ttft_p99_ms",
                "shed", "shed_rate", "retried", "retried_ok",
                "rejected", "throughput_rps",
                "transport_errors") if k in sub}
    by_version = {}
    if versions is not None:
        va = np.asarray(versions, dtype=object)
        steady_ver = va[:, warmup:] if nreq > warmup else va
        seen = dict.fromkeys(v for row in np.asarray(versions,
                                                     dtype=object)
                             for v in row if v)
        for name in seen:
            vmask = steady_ver == name
            v_ok = ok & vmask
            v_lat = steady_lat[v_ok] if v_ok.any() \
                else np.asarray([np.nan])
            v_final = steady_st[vmask]
            n = int((v_final >= 0).sum())
            # errors here = any non-2xx final outcome on this
            # version's responses (sheds included: a version that
            # sheds its riders is not serving them)
            errs = int(((v_final >= 0) & ((v_final < 200) |
                                          (v_final >= 300))).sum())
            by_version[name] = {
                "n": n,
                "p50_ms": float(np.percentile(v_lat, 50)),
                "p99_ms": float(np.percentile(v_lat, 99)),
                "errors": errs,
                "error_rate": errs / max(n, 1),
            }
    out_ttft = {} if ttft_ok is None else {
        "ttft_p50_ms": float(np.percentile(ttft_ok, 50)),
        "ttft_p99_ms": float(np.percentile(ttft_ok, 99)),
    }
    return {
        **out_ttft,
        "tenants": by_tenant,
        "versions": by_version,
        "slowest": slowest,
        "p50_ms": float(np.percentile(ok_lat, 50)),
        "p99_ms": float(np.percentile(ok_lat, 99)),
        "loaded_p99_ms": max(per_conn_p99),
        "throughput_rps": int(all_ok.sum()) / max(wall_s, 1e-9),
        "completed_rps": completed / max(wall_s, 1e-9),
        "shed": shed,
        "shed_rate": shed / max(completed, 1),
        "retried": int(retried_all.sum()),
        "retried_ok": int((retried_all & all_ok).sum()),
        "rejected": int(((final >= 0) & ~all_ok & (final != 429)).sum()),
        "transport_errors": int((final < 0).sum()),
        "errors": int(((final < 0) | ((final >= 0) & ~all_ok)).sum()),
    }


def run_load(host: str, port: int, payload: bytes, *, nconn: int = 16,
             nreq: int = 300, path: str = "/",
             warmup: int = 20, retry: bool = False,
             trace: bool = True,
             tenants: list[str] | None = None,
             ttft: bool = False) -> dict:
    """Closed-loop load: ``nconn`` keep-alive connections, ``nreq``
    serial POSTs each; see :func:`summarize` for the returned summary
    (success-only percentiles; 429 sheds and other non-2xx reported
    separately with ``shed_rate``). ``retry=True`` honors Retry-After
    on 429/503 with one bounded re-attempt per request, reported under
    ``retried``/``retried_ok``. ``trace=True`` (default) stamps every
    request with a deterministic traceparent and reports the
    p99-slowest requests' trace ids under ``slowest`` — look them up at
    the server's ``GET /debug/trace``. ``tenants`` assigns connection
    ``c`` the tenant ``tenants[c % len]``, stamped as ``X-Tenant`` on
    every request (lg_run5) and split out per tenant in the summary's
    ``tenants`` key. ``ttft=True`` (generation mode, lg_run6)
    additionally records each request's time-to-first-byte and adds
    ``ttft_p50_ms``/``ttft_p99_ms`` globally and per tenant. Raises
    when nothing could connect."""
    lib = _loader.load()
    # 20 hex prefix + 4 (conn) + 8 (req) = a 32-hex W3C-shaped trace id
    trace_prefix = uuid.uuid4().hex[:20] if trace else None
    dptr = ctypes.POINTER(ctypes.c_double)
    lib.lg_run6.restype = ctypes.c_long
    lib.lg_run6.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_char_p,
        dptr, ctypes.POINTER(ctypes.c_int), dptr, dptr]
    lat = np.empty(nconn * nreq, np.float64)
    status = np.empty(nconn * nreq, np.int32)
    first = np.empty(nconn * nreq, np.float64) if ttft else None
    wall = ctypes.c_double(0.0)
    errors = int(lib.lg_run6(
        host.encode(), int(port), int(nconn), int(nreq), path.encode(),
        payload, len(payload), 1 if retry else 0,
        (trace_prefix or "").encode(),
        ",".join(tenants or []).encode(),
        lat.ctypes.data_as(dptr),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        first.ctypes.data_as(dptr) if first is not None else None,
        ctypes.byref(wall)))
    if errors < 0:
        raise RuntimeError("loadgen: no connection could be "
                           "established")
    conn_tenants = [tenants[c % len(tenants)]
                    for c in range(nconn)] if tenants else None
    return summarize(lat.reshape(nconn, nreq),
                     status.reshape(nconn, nreq), wall.value,
                     warmup=warmup, trace_prefix=trace_prefix,
                     tenants=conn_tenants,
                     ttft=None if first is None
                     else first.reshape(nconn, nreq))
