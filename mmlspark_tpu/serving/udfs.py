"""Reply UDFs.

Reference ``streaming/ServingUDFs.scala:22-51``: ``makeReplyUDF`` (typed
value → HTTPResponseData) and ``sendReplyUDF`` (side-effecting reply via
the state holder, returning a success bool).
"""

from __future__ import annotations

import json

import numpy as np

from ..io.http.schema import HTTPResponseData, string_to_response
from .server import get_service


def make_reply_udf(value) -> HTTPResponseData:
    """Typed data → response (reference ``makeReplyUDF``)."""
    if isinstance(value, HTTPResponseData):
        return value
    if isinstance(value, (bytes, bytearray)):
        return HTTPResponseData(status_code=200, entity=bytes(value))
    if isinstance(value, str):
        return string_to_response(value)
    if isinstance(value, np.ndarray):
        value = value.tolist()
    return string_to_response(json.dumps(value),
                              content_type="application/json")


def send_reply_udf(service_name: str, request_id: str, value) -> bool:
    """Reply from anywhere in the pipeline (reference ``sendReplyUDF``):
    looks up the service registry, replies once, returns success."""
    try:
        server = get_service(service_name)
    except KeyError:
        return False
    with server._lock:
        cached = server.history.get(request_id)
    if cached is None:
        return False
    return cached.reply(make_reply_udf(value))
